"""Shared building blocks for the CTR model zoo."""
import hetu_tpu as ht
from hetu_tpu import init


def dense_layer(x, in_dim, out_dim, name, activation=None, stddev=0.1,
                bias=True, xavier=False):
    if xavier:
        w = init.xavier_normal([in_dim, out_dim], name=f"{name}_w")
    else:
        w = init.random_normal([in_dim, out_dim], stddev=stddev,
                               name=f"{name}_w")
    y = ht.matmul_op(x, w)
    if bias:
        b = init.zeros([out_dim], name=f"{name}_b") if xavier else \
            init.random_normal([out_dim], stddev=stddev, name=f"{name}_b")
        y = y + ht.broadcastto_op(b, y)
    if activation == "relu":
        y = ht.relu_op(y)
    elif activation == "sigmoid":
        y = ht.sigmoid_op(y)
    return y


def mlp(x, dims, name, stddev=0.1, out_activation=None):
    for i in range(len(dims) - 1):
        act = "relu" if i < len(dims) - 2 else out_activation
        x = dense_layer(x, dims[i], dims[i + 1], f"{name}{i + 1}",
                        activation=act, stddev=stddev, bias=False)
    return x


def bce_loss_and_train(y, y_, lr):
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(y, y_), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=lr)
    return loss, opt.minimize(loss)
