"""Wide & Deep on the Adult census dataset (reference
examples/ctr/models/wdl_adult.py): 8 categorical slots with per-slot
embedding tables + 4 numeric fields feed the deep tower; the wide part
concatenates raw wide features with the deep output."""
import hetu_tpu as ht
from hetu_tpu import init


def wdl_adult(X_deep, X_wide, y_, dim_wide=809, embed_rows=50, embed_dim=8):
    n_cat, n_num = 8, 4
    deep_in = n_cat * embed_dim + n_num

    parts = []
    for i in range(n_cat):
        table = init.random_normal([embed_rows, embed_dim], stddev=0.1,
                                   name=f"Embedding_deep_{i}", is_embed=True)
        parts.append(ht.array_reshape_op(
            ht.embedding_lookup_op(table, X_deep[i]), (-1, embed_dim)))
    for i in range(n_num):
        parts.append(ht.array_reshape_op(X_deep[n_cat + i], (-1, 1)))
    deep = parts[0]
    for p in parts[1:]:
        deep = ht.concat_op(deep, p, 1)

    w1 = init.random_normal([deep_in, 50], stddev=0.1, name="W1")
    b1 = init.random_normal([50], stddev=0.1, name="b1")
    w2 = init.random_normal([50, 20], stddev=0.1, name="W2")
    b2 = init.random_normal([20], stddev=0.1, name="b2")
    h = ht.matmul_op(deep, w1)
    h = ht.relu_op(h + ht.broadcastto_op(b1, h))
    h = ht.matmul_op(h, w2)
    dmodel = ht.relu_op(h + ht.broadcastto_op(b2, h))

    w_out = init.random_normal([dim_wide + 20, 2], stddev=0.1, name="W")
    wmodel = ht.matmul_op(ht.concat_op(X_wide, dmodel, 1), w_out)

    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(wmodel, y_), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=5 / 128)
    return loss, ht.softmax_op(wmodel), y_, opt.minimize(loss)
