"""Wide & Deep on Criteo (reference examples/ctr/models/wdl_criteo.py):
one shared embedding table over 26 sparse slots + a dense MLP over the 13
numeric fields, joined by a final linear layer. ``feature_dimension``
defaults to Criteo's 33.7M rows; pass a smaller value for synthetic runs."""
import hetu_tpu as ht
from hetu_tpu import init

from .common import bce_loss_and_train, mlp


def wdl_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
               embedding_size=128, learning_rate=0.01, n_slots=26,
               n_dense=13, stddev=0.01):
    table = init.random_normal([feature_dimension, embedding_size],
                               stddev=stddev, name="snd_order_embedding",
                               is_embed=True, ctx=ht.cpu(0))
    emb = ht.embedding_lookup_op(table, sparse_input)
    emb = ht.array_reshape_op(emb, (-1, n_slots * embedding_size))

    deep = mlp(dense_input, [n_dense, 256, 256, 256], "W", stddev=stddev)
    joint = ht.concat_op(emb, deep, axis=1)
    w_out = init.random_normal([256 + n_slots * embedding_size, 1],
                               stddev=stddev, name="W4")
    y = ht.sigmoid_op(ht.matmul_op(joint, w_out))
    loss, train_op = bce_loss_and_train(y, y_, learning_rate)
    return loss, y, y_, train_op
