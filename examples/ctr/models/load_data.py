"""CTR dataset loaders (reference examples/ctr/models/load_data.py).

Real Criteo/Adult downloads need egress; when the raw files are absent a
deterministic synthetic dataset with the same shapes/dtypes is generated so
every trainer and test runs offline (the CNN suite's MNIST fallback works the
same way, hetu_tpu/data.py)."""
import os

import numpy as np


def _synth_criteo(n_samples, feature_dimension, rng):
    dense = rng.randn(n_samples, 13).astype(np.float32)
    sparse = rng.randint(0, feature_dimension,
                         (n_samples, 26)).astype(np.float32)
    # labels correlate with dense features so training can learn
    labels = (dense.sum(1, keepdims=True) > 0).astype(np.float32)
    return dense, sparse, labels


def load_criteo_data(path=None, feature_dimension=33762577, n_train=8192,
                     n_test=2048, seed=0):
    """Returns (train, test) tuples of (dense, sparse, labels)."""
    if path and os.path.exists(path):
        data = np.load(path)
        return ((data["train_dense"], data["train_sparse"],
                 data["train_labels"]),
                (data["test_dense"], data["test_sparse"],
                 data["test_labels"]))
    rng = np.random.RandomState(seed)
    return (_synth_criteo(n_train, feature_dimension, rng),
            _synth_criteo(n_test, feature_dimension, rng))


def load_adult_data(path=None, n_train=8192, n_test=2048, seed=0,
                    dim_wide=809, embed_rows=50):
    """Adult census: 8 categorical slots, 4 numeric, wide features, labels
    one-hot over 2 classes (reference wdl_adult input layout)."""
    if path and os.path.exists(path):
        data = np.load(path)
        return ((data["train_deep"], data["train_wide"],
                 data["train_labels"]),
                (data["test_deep"], data["test_wide"], data["test_labels"]))
    rng = np.random.RandomState(seed)

    def synth(n):
        cat = [rng.randint(0, embed_rows, (n, 1)).astype(np.float32)
               for _ in range(8)]
        num = [rng.randn(n, 1).astype(np.float32) for _ in range(4)]
        wide = rng.randn(n, dim_wide).astype(np.float32)
        y = (wide[:, :1] + num[0] > 0).astype(np.int64).ravel()
        labels = np.eye(2, dtype=np.float32)[y]
        return cat + num, wide, labels

    return synth(n_train), synth(n_test)
