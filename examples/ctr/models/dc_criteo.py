"""Deep Crossing on Criteo (reference examples/ctr/models/dc_criteo.py):
stacked residual units over the concatenated embedding + dense features."""
import hetu_tpu as ht
from hetu_tpu import init

from .common import bce_loss_and_train, dense_layer


def _residual_unit(x, dim, hidden, layer_idx):
    # scale-aware init: the reference's fixed stddev=0.1 blows up for wide
    # residual stacks (5 layers x 400+ features compounds)
    h = dense_layer(x, dim, hidden, f"res{layer_idx}_1", activation="relu",
                    xavier=True)
    h = dense_layer(h, hidden, dim, f"res{layer_idx}_2", xavier=True)
    return ht.relu_op(h + x)


def dc_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
              embedding_size=8, learning_rate=0.001, n_slots=26, n_dense=13,
              num_layers=5):
    table = init.random_normal([feature_dimension, embedding_size],
                               stddev=0.01, name="snd_order_embedding",
                               is_embed=True, ctx=ht.cpu(0))
    emb = ht.embedding_lookup_op(table, sparse_input)
    emb = ht.array_reshape_op(emb, (-1, n_slots * embedding_size))
    x = ht.concat_op(emb, dense_input, axis=1)
    dim = n_slots * embedding_size + n_dense

    for i in range(num_layers):
        x = _residual_unit(x, dim, dim, i)

    w_out = init.random_normal([dim, 1], stddev=0.1, name="W4")
    y = ht.sigmoid_op(ht.matmul_op(x, w_out))
    loss, train_op = bce_loss_and_train(y, y_, learning_rate)
    return loss, y, y_, train_op
