"""Deep & Cross Network on Criteo (reference
examples/ctr/models/dcn_criteo.py): explicit feature crosses
x_{l+1} = x0 * (x_l w) + b + x_l alongside a deep tower."""
import hetu_tpu as ht
from hetu_tpu import init

from .common import bce_loss_and_train, mlp


def _cross_layer(x0, xl, width, layer_idx):
    w = init.random_normal((width, 1), stddev=0.01,
                           name=f"cross_w{layer_idx}")
    b = init.random_normal((width,), stddev=0.01, name=f"cross_b{layer_idx}")
    xlw = ht.matmul_op(xl, w)
    y = ht.mul_op(x0, ht.broadcastto_op(xlw, x0))
    return y + xl + ht.broadcastto_op(b, y)


def dcn_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
               embedding_size=128, learning_rate=0.003, n_slots=26,
               n_dense=13, cross_layers=3):
    table = init.random_normal([feature_dimension, embedding_size],
                               stddev=0.01, name="snd_order_embedding",
                               is_embed=True, ctx=ht.cpu(0))
    emb = ht.embedding_lookup_op(table, sparse_input)
    emb = ht.array_reshape_op(emb, (-1, n_slots * embedding_size))
    x0 = ht.concat_op(emb, dense_input, axis=1)
    width = n_slots * embedding_size + n_dense

    xl = x0
    for i in range(cross_layers):
        xl = _cross_layer(x0, xl, width, i)

    deep = mlp(x0, [width, 256, 256, 256], "W", stddev=0.01)
    joint = ht.concat_op(xl, deep, axis=1)
    w_out = init.random_normal([width + 256, 1], stddev=0.01, name="W4")
    y = ht.sigmoid_op(ht.matmul_op(joint, w_out))
    loss, train_op = bce_loss_and_train(y, y_, learning_rate)
    return loss, y, y_, train_op
