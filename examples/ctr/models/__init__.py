from . import load_data
from .wdl_adult import wdl_adult
from .wdl_criteo import wdl_criteo
from .deepfm_criteo import dfm_criteo
from .dcn_criteo import dcn_criteo
from .dc_criteo import dc_criteo
