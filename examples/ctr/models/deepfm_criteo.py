"""DeepFM on Criteo (reference examples/ctr/models/deepfm_criteo.py):
first-order embedding + FM second-order interaction (sum-square minus
square-sum trick) + a DNN over the flattened second-order embeddings."""
import hetu_tpu as ht
from hetu_tpu import init

from .common import bce_loss_and_train, mlp


def dfm_criteo(dense_input, sparse_input, y_, feature_dimension=33762577,
               embedding_size=128, learning_rate=0.01, n_slots=26,
               n_dense=13):
    # first-order terms
    emb1 = init.random_normal([feature_dimension, 1], stddev=0.01,
                              name="fst_order_embedding", is_embed=True,
                              ctx=ht.cpu(0))
    fm_w = init.random_normal([n_dense, 1], stddev=0.01,
                              name="dense_parameter")
    first_sparse = ht.embedding_lookup_op(emb1, sparse_input)
    y1 = ht.matmul_op(dense_input, fm_w) + ht.reduce_sum_op(first_sparse,
                                                            axes=1)

    # second-order FM interaction: ((Σe)² - Σe²) / 2
    emb2 = init.random_normal([feature_dimension, embedding_size],
                              stddev=0.01, name="snd_order_embedding",
                              is_embed=True, ctx=ht.cpu(0))
    e = ht.embedding_lookup_op(emb2, sparse_input)
    sum_e = ht.reduce_sum_op(e, axes=1)
    square_of_sum = ht.mul_op(sum_e, sum_e)
    sum_of_square = ht.reduce_sum_op(ht.mul_op(e, e), axes=1)
    y2 = ht.reduce_sum_op((square_of_sum + -1 * sum_of_square) * 0.5,
                          axes=1, keepdims=True)

    # deep tower over the flattened embeddings
    flat = ht.array_reshape_op(e, (-1, n_slots * embedding_size))
    y3 = mlp(flat, [n_slots * embedding_size, 256, 256, 1], "W", stddev=0.01)

    y = ht.sigmoid_op(y1 + y2 + y3)
    loss, train_op = bce_loss_and_train(y, y_, learning_rate)
    return loss, y, y_, train_op
