"""CTR trainer (reference examples/ctr/run_hetu.py): train/validate
subexecutors, loss/acc/AUC reporting, comm modes local / PS / Hybrid, with
optional bounded-staleness cache and BSP.

Run locally:            python run_hetu.py --model wdl_criteo
Under a PS cluster:     heturun -c cluster.yml python run_hetu.py --model \
                        wdl_criteo --comm Hybrid [--cache LFUOpt] [--bsp]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import metrics as ht_metrics  # noqa: E402
import models  # noqa: E402
from models.load_data import load_adult_data, load_criteo_data  # noqa: E402


def build(args):
    batch = args.batch_size
    if args.model == "wdl_adult":
        (train_deep, train_wide, train_y), (test_deep, test_wide, test_y) = \
            load_adult_data()
        X_deep = [
            ht.dataloader_op([
                ht.Dataloader(train_deep[i], batch, "train"),
                ht.Dataloader(test_deep[i], batch, "validate"),
            ]) for i in range(12)]
        X_wide = ht.dataloader_op([
            ht.Dataloader(train_wide, batch, "train"),
            ht.Dataloader(test_wide, batch, "validate")])
        y_ = ht.dataloader_op([
            ht.Dataloader(train_y, batch, "train"),
            ht.Dataloader(test_y, batch, "validate")])
        loss, y, labels, train_op = models.wdl_adult(X_deep, X_wide, y_)
    else:
        feature_dim = args.dim
        (tr_dense, tr_sparse, tr_y), (te_dense, te_sparse, te_y) = \
            load_criteo_data(feature_dimension=feature_dim)
        dense = ht.dataloader_op([
            ht.Dataloader(tr_dense, batch, "train"),
            ht.Dataloader(te_dense, batch, "validate")])
        sparse = ht.dataloader_op([
            ht.Dataloader(tr_sparse, batch, "train"),
            ht.Dataloader(te_sparse, batch, "validate")])
        y_ = ht.dataloader_op([
            ht.Dataloader(tr_y, batch, "train"),
            ht.Dataloader(te_y, batch, "validate")])
        model_fn = getattr(models, args.model)
        loss, y, labels, train_op = model_fn(
            dense, sparse, y_, feature_dimension=feature_dim)
    return loss, y, labels, train_op


def accuracy(y_val, pred):
    if y_val.shape[1] == 1:
        return np.equal(y_val, pred > 0.5).astype(np.float32).mean()
    return np.equal(np.argmax(y_val, 1),
                    np.argmax(pred, 1)).astype(np.float32).mean()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="wdl_criteo",
                        choices=["wdl_adult", "wdl_criteo", "dfm_criteo",
                                 "dcn_criteo", "dc_criteo"])
    parser.add_argument("--comm", default=None,
                        choices=[None, "PS", "Hybrid", "AllReduce"])
    parser.add_argument("--cache", default=None,
                        choices=[None, "LRU", "LFU", "LFUOpt"])
    parser.add_argument("--bsp", action="store_true")
    parser.add_argument("--bound", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--nepoch", type=int, default=1)
    parser.add_argument("--dim", type=int,
                        default=int(os.environ.get("HETU_CTR_DIM", 100000)),
                        help="feature dimension (full Criteo: 33762577)")
    parser.add_argument("--val", action="store_true")
    parser.add_argument("--all", dest="val", action="store_true")
    args = parser.parse_args()

    if args.comm in ("PS", "Hybrid"):
        ht.worker_init()

    loss, y, labels, train_op = build(args)
    executor = ht.Executor(
        {"train": [loss, y, labels, train_op], "validate": [loss, y, labels]},
        ctx=ht.tpu(0), comm_mode=args.comm, cstable_policy=args.cache,
        bsp=args.bsp, cache_bound=args.bound)

    n_train = executor.get_batch_num("train")
    n_val = executor.get_batch_num("validate")
    for ep in range(args.nepoch):
        t0 = time.time()
        tr_loss, tr_acc, tr_auc = [], [], []
        for _ in range(n_train):
            loss_val, pred, y_val, _ = executor.run(
                "train", convert_to_numpy_ret_vals=True)
            tr_loss.append(loss_val)
            tr_acc.append(accuracy(y_val, pred))
            if y_val.shape[1] == 1:
                try:
                    tr_auc.append(ht_metrics.auc(y_val.ravel(), pred.ravel()))
                except ValueError:
                    pass
        msg = (f"epoch {ep}: train loss {np.mean(tr_loss):.4f} "
               f"acc {np.mean(tr_acc):.4f}")
        if tr_auc:
            msg += f" auc {np.mean(tr_auc):.4f}"
        msg += f" time {time.time() - t0:.2f}s"
        if args.val:
            va_loss, va_acc = [], []
            for _ in range(n_val):
                loss_val, pred, y_val = executor.run(
                    "validate", convert_to_numpy_ret_vals=True)
                va_loss.append(loss_val)
                va_acc.append(accuracy(y_val, pred))
            msg += (f" | val loss {np.mean(va_loss):.4f} "
                    f"acc {np.mean(va_acc):.4f}")
        print(msg, flush=True)

    if args.comm in ("PS", "Hybrid"):
        ht.worker_finish()


if __name__ == "__main__":
    main()
