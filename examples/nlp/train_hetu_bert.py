"""BERT pretraining trainer — the full pipeline end to end (reference
``examples/nlp``: tokenizer + ``processBertData`` masking + trainer; the
reference stops at a causal transformer example, this completes the BERT
pretrain path BASELINE.md names as a north star):

  corpus sentences -> WordPiece tokenizer (hetu_tpu.tokenizers)
    -> sentence-pair MLM/NSP instances (processBertData)
    -> fused pretrain step on hetu_tpu.models.bert (flash attention on TPU)
    -> step-numbered orbax checkpoints with exact resume.

No egress: trains over a built-in corpus with a corpus-derived vocab.

  python examples/nlp/train_hetu_bert.py --num-epoch 20 --cpu
  python examples/nlp/train_hetu_bert.py --resume   # continue from latest
"""
import argparse
import collections
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

SAMPLE_SENTENCES = [
    "the quick brown fox jumps over the lazy dog",
    "the dog barks at the fox near the old oak tree",
    "the fox runs into the deep dark woods",
    "in the woods the fox meets another clever fox",
    "the two foxes play among the tall trees until sunset",
    "the tired dog finds the foxes at the edge of the woods",
    "the quick fox jumps over the sleeping dog once more",
    "every day the dog chases the fox across the green field",
    "every evening the fox escapes into the quiet woods",
    "the lazy dog never learns and the quick fox never tires",
    "a young fox watches the game from a hollow log",
    "the old tree stands at the center of the dark woods",
] * 4


def build_vocab(sentences):
    counts = collections.Counter(w for s in sentences for w in s.split())
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3, "[MASK]": 4}
    for word, _ in counts.most_common():
        vocab.setdefault(word, len(vocab))
    return vocab


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=128)
    ap.add_argument("--max-seq-length", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epoch", type=int, default=20)
    ap.add_argument("--learning-rate", type=float, default=3e-3)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--ckpt-every", type=int, default=0,
                    help="save a step-numbered checkpoint every N epochs")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from hetu_tpu.models import bert
    from hetu_tpu.tokenizers import BertTokenizer
    import processBertData as pbd

    vocab = build_vocab(SAMPLE_SENTENCES)
    tok = BertTokenizer(vocab)
    instances = pbd.create_instances_from_document(
        SAMPLE_SENTENCES, tok, max_seq_length=args.max_seq_length,
        max_predictions_per_seq=5)
    full = bert.batch_from_instances(instances)
    n = len(full["input_ids"])
    print(f"vocab {len(vocab)}, {n} pretrain instances", flush=True)

    cfg = bert.BertConfig(
        vocab_size=len(vocab), d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
        max_seq_len=args.max_seq_length,
        dtype=jnp.float32 if args.cpu else jnp.bfloat16, remat=False)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    opt = bert.init_opt_state(params)
    step_fn = bert.make_pretrain_step(cfg, lr=args.learning_rate)

    ck = None
    start_epoch = 0
    if args.ckpt_dir:
        from hetu_tpu import checkpoint
        ck = checkpoint.TrainCheckpointer(args.ckpt_dir, keep=3)
        if args.resume and ck.latest_step() is not None:
            state, start_epoch = ck.restore_latest(
                like={"params": params, "opt": opt})
            params = jax.tree.map(jnp.asarray, state["params"])
            opt = jax.tree.map(jnp.asarray, state["opt"])
            start_epoch += 1
            print(f"resumed from epoch {start_epoch - 1}", flush=True)

    if start_epoch >= args.num_epoch:
        # resumed a run that had already finished: nothing to train, nothing
        # to (re-)save; returns None (not an avg loss — no epoch ran)
        if ck is not None:
            ck.close()
        print("training already complete; nothing to do", flush=True)
        return None

    steps = max(1, n // args.batch_size)
    tot = 0.0
    for epoch in range(start_epoch, args.num_epoch):
        # per-EPOCH seed: a resumed run sees the same epoch permutations an
        # uninterrupted run would (an advancing shared RNG would diverge)
        order = np.random.RandomState(epoch).permutation(n)
        tot = tot_mlm = tot_nsp = 0.0
        t0 = time.time()
        for s in range(steps):
            idx = order[s * args.batch_size:(s + 1) * args.batch_size]
            batch = {k: v[idx] for k, v in full.items()}
            loss, (mlm, nsp), params, opt = step_fn(params, opt, batch)
            tot += float(loss)
            tot_mlm += float(mlm)
            tot_nsp += float(nsp)
        print(f"epoch {epoch}: loss {tot/steps:.4f} "
              f"(mlm {tot_mlm/steps:.4f} nsp {tot_nsp/steps:.4f}) "
              f"{time.time()-t0:.2f}s", flush=True)
        if ck is not None and args.ckpt_every and \
                (epoch + 1) % args.ckpt_every == 0:
            ck.save_step(epoch, {"params": params, "opt": opt})
    if ck is not None:
        if ck.latest_step() != args.num_epoch - 1:
            ck.save_step(args.num_epoch - 1, {"params": params, "opt": opt})
        ck.close()
    return tot / steps


if __name__ == "__main__":
    main()
