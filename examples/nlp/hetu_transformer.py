"""Transformer LM on the graph API (reference
``examples/nlp/hetu_transformer.py:56`` — multihead attention, layernorm,
FFN, dropout, token embeddings as graph ops).

Decoder-only causal LM built entirely from ``hetu_tpu`` graph ops
(embedding_lookup / batch_matmul / softmax / layer_normalization / dropout),
shapes fixed at build time (define-then-run, like the reference). The
JAX-native flagship (``hetu_tpu/models/transformer.py``) is the perf path;
this file is the graph-API parity surface.
"""
import numpy as np

import hetu_tpu as ht
from hetu_tpu import init


def layer_norm(x, feature_size, name, eps=1e-8):
    scale = init.ones((feature_size,), name=name + "_scale")
    bias = init.zeros((feature_size,), name=name + "_bias")
    return ht.layer_normalization_op(x, scale, bias, eps=eps)


def dense(x, fan_in, fan_out, name, activation=None):
    w = init.xavier_normal((fan_in, fan_out), name=name + "_weight")
    b = init.zeros((fan_out,), name=name + "_bias")
    y = ht.matmul_op(ht.array_reshape_op(x, (-1, fan_in)), w)
    y = y + ht.broadcastto_op(b, y)
    if activation is not None:
        y = activation(y)
    return y


def get_token_embeddings(vocab_size, num_units, name="embedding_table"):
    return init.xavier_normal((vocab_size, num_units), name=name)


def multihead_attention(x, batch, seq_len, d_model, n_heads, mask, name,
                        dropout_prob=0.1):
    """Causal multihead self-attention over (B, T, D)."""
    hd = d_model // n_heads

    def split_heads(t):
        t = ht.array_reshape_op(t, (batch, seq_len, n_heads, hd))
        return ht.transpose_op(t, (0, 2, 1, 3))     # (B, H, T, hd)

    q = split_heads(ht.array_reshape_op(
        dense(x, d_model, d_model, name + "_q"), (batch, seq_len, d_model)))
    k = split_heads(ht.array_reshape_op(
        dense(x, d_model, d_model, name + "_k"), (batch, seq_len, d_model)))
    v = split_heads(ht.array_reshape_op(
        dense(x, d_model, d_model, name + "_v"), (batch, seq_len, d_model)))

    scores = ht.batch_matmul_op(q, k, trans_B=True)     # (B, H, T, T)
    scores = ht.mul_byconst_op(scores, 1.0 / np.sqrt(hd))
    scores = scores + ht.broadcastto_op(mask, scores)   # -inf above diagonal
    attn = ht.softmax_op(scores)
    attn = ht.dropout_op(attn, 1.0 - dropout_prob)
    ctx = ht.batch_matmul_op(attn, v)                   # (B, H, T, hd)
    ctx = ht.transpose_op(ctx, (0, 2, 1, 3))
    ctx = ht.array_reshape_op(ctx, (batch, seq_len, d_model))
    out = dense(ctx, d_model, d_model, name + "_proj")
    return ht.array_reshape_op(out, (batch, seq_len, d_model))


def feed_forward(x, batch, seq_len, d_model, d_ff, name, dropout_prob=0.1):
    h = dense(x, d_model, d_ff, name + "_in", activation=ht.relu_op)
    h = ht.dropout_op(h, 1.0 - dropout_prob)
    h = dense(h, d_ff, d_model, name + "_out")
    return ht.array_reshape_op(h, (batch, seq_len, d_model))


def transformer_lm(tokens, labels, vocab_size, batch, seq_len, d_model=64,
                   n_heads=4, n_layers=2, d_ff=256, dropout_prob=0.1):
    """Build the causal LM graph. ``tokens``/``labels`` are fed (B, T)
    int-valued placeholders; returns (loss, logits, mask_node)."""
    table = get_token_embeddings(vocab_size, d_model)
    pos_table = init.xavier_normal((seq_len, d_model), name="pos_embedding")
    h = ht.embedding_lookup_op(table, tokens)            # (B, T, D)
    pos_idx = ht.Variable(
        "pos_idx", value=np.arange(seq_len, dtype=np.float32),
        trainable=False, batch=False)
    pos = ht.embedding_lookup_op(pos_table, pos_idx)     # (T, D)
    h = h + ht.broadcastto_op(pos, h)

    causal = np.triu(np.full((seq_len, seq_len), -1e9, np.float32), k=1)
    mask = ht.Variable("causal_mask", value=causal, trainable=False,
                       batch=False)

    for i in range(n_layers):
        a = multihead_attention(layer_norm(h, d_model, f"ln1_{i}"), batch,
                                seq_len, d_model, n_heads, mask,
                                f"attn_{i}", dropout_prob)
        h = h + a
        f = feed_forward(layer_norm(h, d_model, f"ln2_{i}"), batch, seq_len,
                         d_model, d_ff, f"ffn_{i}", dropout_prob)
        h = h + f

    h = layer_norm(h, d_model, "ln_f")
    logits = dense(h, d_model, vocab_size, "lm_head")    # (B*T, V)
    targets = ht.one_hot_op(ht.array_reshape_op(labels, (-1,)), vocab_size)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(logits, targets), [0])
    return loss, logits, mask
