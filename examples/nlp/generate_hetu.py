"""Inference demo for the flagship LM: train briefly on a tiny synthetic
corpus, then decode with every strategy the framework ships — greedy,
temperature / top-k sampling, beam search, EOS-aware early exit, and a
RAGGED batch (per-row prompt lengths in one call).

The reference framework stops at training; this surface is beyond-parity
(models/generate.py). Run:

    python generate_hetu.py [--steps 200] [--beam 4] [--cpu]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def make_corpus(vocab, n=4096, seed=0):
    """Synthetic 'language': arithmetic-progression sequences with a step
    drawn per sequence — enough structure for greedy decode to visibly
    learn the pattern."""
    rng = np.random.RandomState(seed)
    start = rng.randint(1, vocab - 64, n)
    step = rng.randint(1, 5, n)
    T = 16
    seqs = (start[:, None] + step[:, None] * np.arange(T)) % (vocab - 1) + 1
    return seqs.astype(np.int32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    def positive_int(v):
        v = int(v)
        if v < 1:
            raise argparse.ArgumentTypeError("--steps must be >= 1")
        return v

    ap.add_argument("--steps", type=positive_int, default=200)
    ap.add_argument("--beam", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=16)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        # authoritative platform switch: the env var alone is overridden by
        # site configuration on some hosts (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from hetu_tpu.models import transformer as tfm
    from hetu_tpu.models import generate as gen

    cfg = tfm.TransformerConfig(vocab_size=256, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_seq_len=32,
                                dtype=jnp.float32, remat=False)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = tfm.init_opt_state(params)
    step_fn = tfm.make_train_step(cfg, lr=3e-3)

    data = make_corpus(cfg.vocab_size)
    t0 = time.time()
    loss = None
    for i in range(args.steps):
        batch = data[(i * 64) % len(data):(i * 64) % len(data) + 64]
        tok = jnp.asarray(batch)
        loss, params, opt = step_fn(params, opt, tok,
                                    jnp.roll(tok, -1, axis=1))
    print(f"trained {args.steps} steps, final loss "
          f"{float(np.asarray(loss)):.3f} ({time.time() - t0:.1f}s)")

    prompt = jnp.asarray(data[:4, :6])
    M = args.max_len

    greedy = gen.make_generate_fn(cfg, max_len=M)
    toks, _ = greedy(params, prompt, jax.random.PRNGKey(1))
    print("greedy:      ", np.asarray(toks)[0])

    sampler = gen.make_generate_fn(cfg, max_len=M, sample=True, top_k=8)
    stoks, _ = sampler(params, prompt, jax.random.PRNGKey(2),
                       temperature=0.8)
    print("top-k sample:", np.asarray(stoks)[0])

    beam = gen.make_beam_search_fn(cfg, max_len=M, beam_size=args.beam)
    btoks, scores = beam(params, prompt)
    print(f"beam (K={args.beam}):", np.asarray(btoks)[0, 0],
          f"score {float(scores[0, 0]):.2f}")

    # a MID-rollout token as eos, single row: the loop exits as soon as
    # every row has finished, so this visibly stops early
    eos = int(np.asarray(toks)[0, M // 2])
    eosfn = gen.make_eos_generate_fn(cfg, max_len=M, eos_id=eos)
    etoks, nstep = eosfn(params, prompt[:1], jax.random.PRNGKey(3))
    print(f"eos-aware:    exited after {int(nstep)}/{M - 1} steps "
          f"(eos_id {eos})")

    lens = jnp.asarray([2, 4, 6, 3], jnp.int32)
    rtoks, _ = greedy(params, prompt, jax.random.PRNGKey(4),
                      prompt_lens=lens)
    rt = np.asarray(rtoks)
    print("ragged batch: per-row prompt lens", np.asarray(lens).tolist())
    for b in (1, 3):
        ln = int(lens[b])
        print(f"  row {b}: prompt {rt[b, :ln]} -> generated {rt[b, ln:]}")
    return float(np.asarray(loss))


if __name__ == "__main__":
    main()
