"""The full offline GPT-2 pipeline in one script — tokenizer to deploy.

1. build a byte-level BPE tokenizer from local vocab/merges files (or a
   tiny demo vocabulary when none are given — this image has no network),
2. load a ``transformers`` GPT-2 checkpoint (local directory via
   ``--from-pretrained``, or a small random one) weight-for-weight into
   the flagship trunk (``models/hf_gpt2``),
3. fine-tune a few steps on synthetic token streams (flagship jitted
   step, tied LM head — gradients flow into the embedding exactly as in
   HF),
4. decode with the one-scan KV cache (greedy + top-k sampling + the
   speculative path against a self-draft),
5. export the trained weights back into a live transformers model and
   verify HF greedy generation matches ours token for token.

The reference has no analogue for any of this (its nlp example trains a
from-scratch transformer only, SURVEY §2.5).
"""
import argparse
import json
import os
import sys
import tempfile

import numpy as np


def demo_tokenizer():
    """A tiny byte-level BPE over local files (no network)."""
    from hetu_tpu.tokenizers import GPT2Tokenizer, bytes_to_unicode
    d = tempfile.mkdtemp()
    b2u = bytes_to_unicode()
    vocab = {c: i for i, c in enumerate(sorted(b2u.values()))}
    merges = ["t h", "th e", "i n", "a n", "Ġ t", "Ġt h", "Ġth e"]
    for m in merges:
        vocab.setdefault(m.replace(" ", ""), len(vocab))
    with open(os.path.join(d, "vocab.json"), "w") as f:
        json.dump(vocab, f)
    with open(os.path.join(d, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n" + "\n".join(merges) + "\n")
    return GPT2Tokenizer(os.path.join(d, "vocab.json"),
                         os.path.join(d, "merges.txt"))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--from-pretrained", default=None,
                    help="local HF GPT-2 directory (weights + tokenizer); "
                         "default: small random model + demo tokenizer")
    ap.add_argument("--steps", type=int, default=30,
                    help="fine-tune steps (min 1)")
    ap.add_argument("--max-len", type=int, default=32)
    ap.add_argument("--spec-k", type=int, default=3)
    args = ap.parse_args(argv)
    if args.steps < 1:
        ap.error("--steps must be >= 1")

    import torch
    import transformers
    import jax
    import jax.numpy as jnp
    import dataclasses
    from hetu_tpu.models import transformer as tfm, generate as gen
    from hetu_tpu.models.hf_gpt2 import params_from_hf, export_to_hf

    torch.manual_seed(0)
    if args.from_pretrained:
        model = transformers.GPT2LMHeadModel.from_pretrained(
            args.from_pretrained)
        from hetu_tpu.tokenizers import GPT2Tokenizer
        tok = GPT2Tokenizer(
            os.path.join(args.from_pretrained, "vocab.json"),
            os.path.join(args.from_pretrained, "merges.txt"))
    else:
        tok = demo_tokenizer()
        model = transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=tok.vocab_size, n_positions=64, n_embd=64,
            n_layer=2, n_head=4))
    model = model.eval()
    params, cfg = params_from_hf(model)
    cfg = dataclasses.replace(cfg, remat=False)
    print(f"imported GPT-2: L={cfg.n_layers} D={cfg.d_model} "
          f"V={cfg.vocab_size} ({tfm.count_params(params):,} params, "
          "tied head)")

    # -- fine-tune on synthetic streams through the flagship step --
    step = tfm.make_train_step(cfg, lr=3e-4)
    opt = tfm.init_opt_state(params)
    rng = np.random.default_rng(0)
    T = min(33, cfg.max_seq_len)
    loss = None
    for it in range(args.steps):
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, T)),
                           jnp.int32)
        loss, params, opt = step(params, opt, toks[:, :-1], toks[:, 1:])
        if it % 10 == 0 or it == args.steps - 1:
            print(f"step {it:3d}  loss {float(loss):.4f}")

    # -- decode: tokenize a prompt, generate, detokenize --
    prompt_text = "the thin"
    ids = np.asarray([tok.encode(prompt_text)], np.int32)
    greedy = gen.generate(params, cfg, ids, max_len=args.max_len)
    print("greedy   :", repr(tok.decode(greedy[0])))
    sampled = gen.generate(params, cfg, ids, max_len=args.max_len,
                           temperature=0.9, rng=jax.random.PRNGKey(7))
    print("sampled  :", repr(tok.decode(sampled[0])))
    spec_fn = gen.make_speculative_generate_fn(cfg, cfg, args.max_len,
                                               k=args.spec_k)
    spec, rounds = spec_fn(params, params, jnp.asarray(ids))
    spec_match = np.array_equal(np.asarray(spec), greedy)
    # exact-tie argmax flips between the chunked verify and the tokenwise
    # decode are possible on TPU tilings (not on the CPU backend, where
    # the equality is pinned hard)
    if jax.default_backend() == "cpu":
        assert spec_match, "spec != greedy"
    print(f"speculative (self-draft k={args.spec_k}): "
          f"{'identical' if spec_match else 'near-identical'} tokens in "
          f"{int(rounds)} verify rounds")

    # -- deploy: export into transformers, check HF generates the same --
    fresh = transformers.GPT2LMHeadModel(model.config).eval()
    export_to_hf(params, cfg, fresh)
    with torch.no_grad():
        # eos_token_id=None: real GPT-2 checkpoints define eos=50256 and
        # HF would stop early on it, while our greedy decode is
        # fixed-length — disable it so the comparison is length-exact
        ref = fresh.generate(
            torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.ones(ids.shape, dtype=torch.long),
            max_new_tokens=args.max_len - ids.shape[1],
            do_sample=False, pad_token_id=0, eos_token_id=None)
    hf_match = np.array_equal(greedy, ref.numpy())
    if jax.default_backend() == "cpu":   # torch-vs-XLA exact ties on TPU
        assert hf_match, "HF deploy mismatch"
    print("exported to transformers: HF greedy generation "
          + ("identical" if hf_match else "near-identical"))
    return float(loss)


if __name__ == "__main__":
    main()
    sys.exit(0)
