"""Graph-API transformer LM trainer (reference
``examples/nlp/train_hetu_transformer.py``).

The reference trains a translation transformer on downloaded corpora; this
image has no egress, so the trainer runs a character-level LM over a built-in
text sample tokenized by the BERT WordPiece tokenizer
(``hetu_tpu.tokenizers``) with a corpus-derived vocabulary — the full
tokenizer -> graph-API-transformer -> Executor pipeline.
"""
import argparse
import collections
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import hetu_tpu as ht
from hetu_tpu.tokenizers import BertTokenizer
from hetu_transformer import transformer_lm

SAMPLE_TEXT = """
the quick brown fox jumps over the lazy dog . the dog barks at the fox ,
and the fox runs into the woods . in the woods the fox meets another fox .
the two foxes play in the woods until the dog finds them again . then the
quick brown fox jumps over the lazy dog once more , and the game repeats .
every day the dog chases the fox and every day the fox escapes into the
woods . the lazy dog never learns , and the quick fox never tires .
""" * 8


def build_vocab(text, min_count=1):
    """Word-level vocab with wordpiece suffix entries for coverage."""
    counts = collections.Counter(text.split())
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3, "[MASK]": 4}
    for word, c in counts.most_common():
        if c >= min_count and word not in vocab:
            vocab[word] = len(vocab)
    # character fallbacks so wordpiece never hits [UNK] on this corpus
    for ch in sorted(set(text.replace(" ", "").replace("\n", ""))):
        for piece in (ch, "##" + ch):
            if piece not in vocab:
                vocab[piece] = len(vocab)
    return vocab


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--timing", action="store_true")
    args = ap.parse_args()

    vocab = build_vocab(SAMPLE_TEXT)
    tok = BertTokenizer(vocab)
    ids = np.asarray(tok.encode(SAMPLE_TEXT), np.float32)
    print(f"corpus: {ids.size} tokens, vocab {len(vocab)}")

    B, T = args.batch_size, args.seq_len
    tokens = ht.Variable(name="tokens", trainable=False)
    labels = ht.Variable(name="labels", trainable=False)
    loss, logits, _ = transformer_lm(
        tokens, labels, len(vocab), B, T, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers)
    opt = ht.optim.AdamOptimizer(learning_rate=args.lr)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.tpu(0)
                     if os.environ.get("JAX_PLATFORMS") != "cpu"
                     else ht.cpu(0), seed=0)

    rng = np.random.RandomState(0)
    t0 = time.time()
    window = []
    for step in range(args.steps):
        starts = rng.randint(0, ids.size - T - 1, B)
        bx = np.stack([ids[s:s + T] for s in starts])
        by = np.stack([ids[s + 1:s + T + 1] for s in starts])
        lv = ex.run("train", feed_dict={tokens: bx, labels: by})[0]
        window.append(float(np.mean(lv.asnumpy())))
        if (step + 1) % 50 == 0:
            ppl = float(np.exp(np.mean(window)))
            print(f"step {step + 1}: loss {np.mean(window):.4f} ppl {ppl:.1f}")
            window = []
    if args.timing:
        print(f"{args.steps} steps in {time.time() - t0:.1f}s "
              f"({(time.time() - t0) / args.steps * 1000:.1f} ms/step)")


if __name__ == "__main__":
    main()
