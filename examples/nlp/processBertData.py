"""BERT pretraining data pipeline (reference
``examples/nlp/processBertData.py``): sentence-pair instances with
masked-LM (15%, 80/10/10) and next-sentence-prediction labels, built on the
``hetu_tpu.tokenizers`` WordPiece tokenizer."""
import collections

import numpy as np

MaskedLmInstance = collections.namedtuple("MaskedLmInstance",
                                          ["index", "label"])


def create_masked_lm_predictions(tokens, masked_lm_prob, max_predictions,
                                 vocab_words, rng):
    """Standard BERT masking: pick up to 15% of non-special positions;
    80% -> [MASK], 10% -> random token, 10% -> unchanged."""
    cand = [i for i, t in enumerate(tokens) if t not in ("[CLS]", "[SEP]")]
    rng.shuffle(cand)
    n_pred = min(max_predictions, max(1, int(round(len(tokens)
                                                   * masked_lm_prob))))
    out = list(tokens)
    masked = []
    for idx in sorted(cand[:n_pred]):
        if rng.rand() < 0.8:
            repl = "[MASK]"
        elif rng.rand() < 0.5:
            repl = vocab_words[rng.randint(0, len(vocab_words))]
        else:
            repl = tokens[idx]
        masked.append(MaskedLmInstance(index=idx, label=tokens[idx]))
        out[idx] = repl
    return out, masked


def create_instances_from_document(sentences, tokenizer, max_seq_length=128,
                                   masked_lm_prob=0.15,
                                   max_predictions_per_seq=20, seed=0):
    """Yield (input_ids, input_mask, segment_ids, mlm_positions, mlm_ids,
    nsp_label) numpy rows from a list of sentence strings."""
    rng = np.random.RandomState(seed)
    tokenized = [tokenizer.tokenize(s) for s in sentences if s.strip()]
    vocab_words = list(tokenizer.vocab.keys())
    max_tokens = max_seq_length - 3  # [CLS] a [SEP] b [SEP]
    instances = []
    for i in range(len(tokenized) - 1):
        a = list(tokenized[i])   # copies: truncation must not corrupt the
        if rng.rand() < 0.5 or len(tokenized) <= 2:
            b = list(tokenized[i + 1])  # stored corpus for later instances
            nsp = 1  # actual next sentence
        else:
            # negative sample: any sentence EXCEPT a and its real successor
            choices = [j for j in range(len(tokenized))
                       if j not in (i, i + 1)]
            b = list(tokenized[choices[rng.randint(0, len(choices))]])
            nsp = 0
        while len(a) + len(b) > max_tokens:
            (a if len(a) > len(b) else b).pop()
        tokens = ["[CLS]"] + a + ["[SEP]"] + b + ["[SEP]"]
        segment = [0] * (len(a) + 2) + [1] * (len(b) + 1)
        tokens, masked = create_masked_lm_predictions(
            tokens, masked_lm_prob, max_predictions_per_seq, vocab_words, rng)
        ids = tokenizer.convert_tokens_to_ids(tokens)
        pad = max_seq_length - len(ids)
        input_mask = [1] * len(ids) + [0] * pad
        ids = ids + [tokenizer.vocab["[PAD]"]] * pad
        segment = segment + [0] * pad
        mlm_pos = [m.index for m in masked]
        mlm_ids = tokenizer.convert_tokens_to_ids([m.label for m in masked])
        mlm_pad = max_predictions_per_seq - len(mlm_pos)
        mlm_pos = mlm_pos + [0] * mlm_pad
        mlm_ids = mlm_ids + [0] * mlm_pad
        instances.append((np.asarray(ids, np.int32),
                          np.asarray(input_mask, np.int32),
                          np.asarray(segment, np.int32),
                          np.asarray(mlm_pos, np.int32),
                          np.asarray(mlm_ids, np.int32), nsp))
    return instances
