"""Fine-tune a HuggingFace BERT checkpoint through the TPU-native stack.

The migration story in one script: take any ``transformers`` BERT
(here a locally instantiated one — the image has no network; pass
``--from-pretrained`` a local directory to use real weights), import it
weight-for-weight (``models/hf_bert.py``), graft a fresh classification
head, and fine-tune with the flagship jitted step (donated buffers,
AdamW fused in, dp/tp-shardable). The reference has no
pretrained-checkpoint interop (its nlp suite trains from scratch —
``/root/reference/examples/nlp``).

Synthetic task: the label is whether low-id tokens outnumber high-id
tokens in the sequence — linearly separable from mean-pooled embeddings,
so fine-tuning must push accuracy well above chance within ~100 steps.
"""
import argparse
import sys

import numpy as np

import jax
import jax.numpy as jnp


def make_task(rng, n, seq_len, vocab_size):
    ids = rng.integers(4, vocab_size, size=(n, seq_len))
    labels = (ids < vocab_size // 2).sum(1) > (seq_len // 2)
    return ids.astype(np.int32), labels.astype(np.int32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--from-pretrained", default=None,
                    help="local directory with a saved HF BERT; default: "
                         "a small randomly initialized BertModel")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--n-classes", type=int, default=2)
    args = ap.parse_args(argv)

    import torch
    import transformers
    from hetu_tpu.models import bert as hbert
    from hetu_tpu.models.hf_bert import params_from_hf

    torch.manual_seed(0)   # deterministic random init for the demo path
    if args.from_pretrained:
        model = transformers.BertModel.from_pretrained(args.from_pretrained)
    else:
        model = transformers.BertModel(transformers.BertConfig(
            vocab_size=500, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64))
    model = model.eval()
    params, cfg = params_from_hf(model)
    import dataclasses
    cfg = dataclasses.replace(cfg, remat=False)
    print(f"imported BERT: L={cfg.n_layers} D={cfg.d_model} "
          f"V={cfg.vocab_size} ({hbert.count_params(params):,} params)")

    # graft a fresh classification head on the imported trunk + pooler
    params = hbert.init_classifier_params(
        jax.random.PRNGKey(0), cfg, args.n_classes, pretrained=params)
    step = hbert.make_finetune_step(cfg, lr=args.lr)
    opt = hbert.init_opt_state(params)

    rng = np.random.default_rng(0)
    ids, labels = make_task(rng, 4096, args.seq_len, cfg.vocab_size)
    seg = np.zeros_like(ids)

    for it in range(args.steps):
        sel = rng.integers(0, len(ids), size=args.batch_size)
        batch = {"input_ids": jnp.asarray(ids[sel]),
                 "segment_ids": jnp.asarray(seg[sel]),
                 "label": jnp.asarray(labels[sel])}
        loss, acc, params, opt = step(params, opt, batch)
        if it % 20 == 0 or it == args.steps - 1:
            print(f"step {it:4d}  loss {float(loss):.4f}  "
                  f"batch acc {float(acc):.3f}")

    # held-out accuracy (batch acc is a 32-sample estimate; judge on 1024)
    hids, hlabels = make_task(rng, 1024, args.seq_len, cfg.vocab_size)
    logits = hbert.classify_logits(
        params, jnp.asarray(hids), jnp.zeros_like(jnp.asarray(hids)), cfg)
    heldout = float(np.mean(np.argmax(np.asarray(logits), -1) == hlabels))
    print(f"held-out acc over 1024: {heldout:.3f}")
    return heldout


if __name__ == "__main__":
    sys.exit(0 if main() > 0.8 else 1)
