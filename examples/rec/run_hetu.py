"""NCF trainer on MovieLens (reference examples/rec/run_hetu.py).

Local:  python run_hetu.py
PS:     heturun -c cluster.yml python run_hetu.py --comm Hybrid
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hetu_tpu as ht  # noqa: E402
from hetu_ncf import neural_mf  # noqa: E402
from movielens import getdata  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--comm", default=None,
                        choices=[None, "PS", "Hybrid", "AllReduce"])
    parser.add_argument("--cache", default=None,
                        choices=[None, "LRU", "LFU", "LFUOpt"])
    parser.add_argument("--bsp", action="store_true")
    parser.add_argument("--batch-size", type=int, default=1024)
    parser.add_argument("--nepoch", type=int, default=1)
    args = parser.parse_args()

    if args.comm in ("PS", "Hybrid"):
        ht.worker_init()

    users, items, labels, num_users, num_items = getdata()
    user_in = ht.dataloader_op([ht.Dataloader(users, args.batch_size, "train")])
    item_in = ht.dataloader_op([ht.Dataloader(items, args.batch_size, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(labels, args.batch_size, "train")])
    loss, y, train_op = neural_mf(user_in, item_in, y_, num_users, num_items)

    executor = ht.Executor({"train": [loss, y, y_, train_op]},
                           ctx=ht.tpu(0), comm_mode=args.comm,
                           cstable_policy=args.cache, bsp=args.bsp)
    n_batches = executor.get_batch_num("train")
    for ep in range(args.nepoch):
        t0 = time.time()
        losses, accs = [], []
        for _ in range(n_batches):
            loss_val, pred, y_val, _ = executor.run(
                "train", convert_to_numpy_ret_vals=True)
            losses.append(loss_val)
            accs.append(np.equal(y_val, pred > 0.5).astype(np.float32).mean())
        print(f"epoch {ep}: loss {np.mean(losses):.4f} "
              f"acc {np.mean(accs):.4f} time {time.time() - t0:.2f}s",
              flush=True)

    if args.comm in ("PS", "Hybrid"):
        ht.worker_finish()


if __name__ == "__main__":
    main()
