"""Neural Collaborative Filtering (reference examples/rec/hetu_ncf.py):
GMF (elementwise product of user/item factors) fused with an MLP tower over
concatenated latents; one embedding table per side carries both."""
import hetu_tpu as ht
from hetu_tpu import init


def neural_mf(user_input, item_input, y_, num_users, num_items,
              embed_dim=8, layers=(64, 32, 16, 8), learning_rate=0.01,
              embed_stddev=0.01):
    width = embed_dim + layers[0] // 2
    user_table = init.random_normal((num_users, width), stddev=embed_stddev,
                                    name="user_embed", is_embed=True,
                                    ctx=ht.cpu(0))
    item_table = init.random_normal((num_items, width), stddev=embed_stddev,
                                    name="item_embed", is_embed=True,
                                    ctx=ht.cpu(0))
    user_latent = ht.array_reshape_op(
        ht.embedding_lookup_op(user_table, user_input), (-1, width))
    item_latent = ht.array_reshape_op(
        ht.embedding_lookup_op(item_table, item_input), (-1, width))

    mf_user = ht.slice_op(user_latent, (0, 0), (-1, embed_dim))
    mlp_user = ht.slice_op(user_latent, (0, embed_dim), (-1, -1))
    mf_item = ht.slice_op(item_latent, (0, 0), (-1, embed_dim))
    mlp_item = ht.slice_op(item_latent, (0, embed_dim), (-1, -1))

    mf_vector = ht.mul_op(mf_user, mf_item)
    x = ht.concat_op(mlp_user, mlp_item, axis=1)
    for i in range(len(layers) - 1):
        w = init.random_normal((layers[i], layers[i + 1]), stddev=0.1,
                               name=f"W{i + 1}")
        x = ht.relu_op(ht.matmul_op(x, w))
    w_out = init.random_normal((embed_dim + layers[-1], 1), stddev=0.1,
                               name="W_out")
    y = ht.sigmoid_op(ht.matmul_op(ht.concat_op(mf_vector, x, axis=1), w_out))
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(y, y_), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=learning_rate)
    return loss, y, opt.minimize(loss)
