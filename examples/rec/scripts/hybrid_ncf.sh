#!/bin/bash
# NCF in Hybrid mode: dense on-device, embeddings via PS (reference
# examples/rec/hybrid_ncf.sh)
cd "$(dirname "$0")/.." || exit 1
cat > /tmp/ncf_cluster.yml <<'YML'
nodes:
  - host: localhost
    servers: 1
    workers: 2
    chief: true
YML
PYTHONPATH="$(cd ../.. && pwd):$PYTHONPATH" exec ../../bin/heturun \
    -c /tmp/ncf_cluster.yml python run_hetu.py --comm Hybrid "$@"
