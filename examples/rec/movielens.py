"""MovieLens data pipeline for NCF (reference examples/rec/movielens.py).

Without egress the loader synthesizes implicit-feedback triples with the
ml-1m shape: (user, item, label) with 4 negatives per positive."""
import os

import numpy as np


def getdata(dataset="ml-1m", path=None, num_users=600, num_items=1200,
            n_pos=20000, num_negatives=4, seed=0):
    if path and os.path.exists(path):
        data = np.load(path)
        return (data["users"], data["items"], data["labels"],
                int(data["num_users"]), int(data["num_items"]))
    rng = np.random.RandomState(seed)
    # each user has a latent preference over items: positives are sampled
    # from the top half of their preference ranking, so NCF can learn
    u_pref = rng.randn(num_users, 8)
    i_pref = rng.randn(num_items, 8)
    scores = u_pref @ i_pref.T
    users, items, labels = [], [], []
    for _ in range(n_pos):
        u = rng.randint(num_users)
        pos_pool = np.argsort(-scores[u])[:num_items // 2]
        items.append(pos_pool[rng.randint(len(pos_pool))])
        users.append(u)
        labels.append(1.0)
        for _ in range(num_negatives):
            users.append(u)
            items.append(rng.randint(num_items))
            labels.append(0.0)
    users = np.asarray(users, np.float32).reshape(-1, 1)
    items = np.asarray(items, np.float32).reshape(-1, 1)
    labels = np.asarray(labels, np.float32).reshape(-1, 1)
    perm = rng.permutation(len(users))
    return users[perm], items[perm], labels[perm], num_users, num_items
