"""Logistic regression (reference examples/cnn/models/LogReg.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def logreg(x, y_, num_class=10, input_dim=784):
    print("Building logistic regression model...")
    weight = init.zeros((input_dim, num_class), name='logreg_weight')
    bias = init.zeros((num_class,), name='logreg_bias')
    logit = ht.matmul_op(x, weight) + ht.broadcastto_op(bias, ht.matmul_op(x, weight))
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logit, y_), [0])
    return loss, logit
