"""Vision Transformer (CIFAR-sized) through the graph API.

No reference counterpart (the reference zoo stops at ResNet/RNN/LSTM,
``examples/cnn/models/``); this demonstrates attention models on the
define-then-run API with the same building blocks the nlp example uses:
conv patch embedding, BatchMatMul attention, LayerNorm residual blocks,
a learned [CLS] token readout.
"""
import numpy as np

import hetu_tpu as ht
from hetu_tpu import init


def _dense(x, fan_in, fan_out, name):
    w = init.xavier_uniform((fan_in, fan_out), name=name + '_w')
    b = init.zeros((fan_out,), name=name + '_b')
    y = ht.matmul_op(ht.array_reshape_op(x, (-1, fan_in)), w)
    return y + ht.broadcastto_op(b, y)


def _block(h, batch, tokens, d, heads, dff, name):
    """Pre-LN transformer encoder block on (B, T, D)."""
    hd = d // heads

    def split_heads(t):
        t = ht.array_reshape_op(t, (batch, tokens, heads, hd))
        return ht.transpose_op(t, (0, 2, 1, 3))

    ln1 = _ln(h, d, name + '_ln1')
    q = split_heads(ht.array_reshape_op(_dense(ln1, d, d, name + '_q'),
                                        (batch, tokens, d)))
    k = split_heads(ht.array_reshape_op(_dense(ln1, d, d, name + '_k'),
                                        (batch, tokens, d)))
    v = split_heads(ht.array_reshape_op(_dense(ln1, d, d, name + '_v'),
                                        (batch, tokens, d)))
    scores = ht.mul_byconst_op(ht.batch_matmul_op(q, k, trans_B=True),
                               1.0 / np.sqrt(hd))
    attn = ht.softmax_op(scores)                       # bidirectional
    ctx = ht.transpose_op(ht.batch_matmul_op(attn, v), (0, 2, 1, 3))
    ctx = ht.array_reshape_op(ctx, (batch, tokens, d))
    h = h + ht.array_reshape_op(_dense(ctx, d, d, name + '_o'),
                                (batch, tokens, d))

    ln2 = _ln(h, d, name + '_ln2')
    f = ht.relu_op(_dense(ln2, d, dff, name + '_f1'))
    f = ht.array_reshape_op(_dense(f, dff, d, name + '_f2'),
                            (batch, tokens, d))
    return h + f


def _ln(x, d, name):
    scale = init.ones((d,), name=name + '_scale')
    bias = init.zeros((d,), name=name + '_bias')
    return ht.layer_normalization_op(x, scale, bias)


def vit(x, y_, num_class=10, batch=128, image=32, patch=4, d=64,
        heads=4, layers=4, dff=128):
    """x: (B, 3, H, W) NCHW CIFAR batch -> (loss, probs)."""
    print('Building ViT model...')
    n_patch = (image // patch) ** 2                    # 64 tokens
    tokens = n_patch + 1                               # + [CLS]

    # patch embedding: conv stride=patch, then (B, D, P, P) -> (B, P*P, D)
    wp = init.he_normal((d, 3, patch, patch), name='vit_patch_w')
    h = ht.conv2d_op(x, wp, padding=0, stride=patch)   # (B, D, 8, 8)
    h = ht.array_reshape_op(h, (batch, d, n_patch))
    h = ht.transpose_op(h, (0, 2, 1))                  # (B, 64, D)

    cls = init.random_normal((1, 1, d), stddev=0.02, name='vit_cls')
    h = ht.concat_op(ht.broadcast_shape_op(cls, (batch, 1, d)), h, axis=1)
    pos = init.random_normal((1, tokens, d), stddev=0.02, name='vit_pos')
    h = h + ht.broadcastto_op(pos, h)

    for i in range(layers):
        h = _block(h, batch, tokens, d, heads, dff, f'vit_l{i}')

    h = _ln(h, d, 'vit_lnf')
    cls_out = ht.slice_op(h, (0, 0, 0), (batch, 1, d))
    logits = _dense(ht.array_reshape_op(cls_out, (batch, d)), d, num_class,
                    'vit_head')
    loss = ht.softmaxcrossentropy_op(logits, y_)
    loss = ht.reduce_mean_op(loss, [0])
    return loss, ht.softmax_op(logits)
