"""Vanilla RNN over MNIST rows as a 28-step sequence (reference
examples/cnn/models/RNN.py — graph statically unrolled over time)."""
import hetu_tpu as ht
from hetu_tpu import init


def rnn(x, y_, num_class=10, dimhidden=128, diminput=28, nsteps=28):
    print('Building RNN model...')
    w_ih = init.random_normal((diminput, dimhidden), stddev=0.1, name='rnn_w_ih')
    w_hh = init.random_normal((dimhidden, dimhidden), stddev=0.1, name='rnn_w_hh')
    b_h = init.zeros((dimhidden,), name='rnn_b_h')
    w_out = init.random_normal((dimhidden, num_class), stddev=0.1, name='rnn_w_out')
    b_out = init.zeros((num_class,), name='rnn_b_out')

    h = None
    for t in range(nsteps):
        x_t = ht.slice_op(x, (0, t * diminput), (-1, diminput))
        pre = ht.matmul_op(x_t, w_ih)
        if h is not None:
            pre = pre + ht.matmul_op(h, w_hh)
        pre = pre + ht.broadcastto_op(b_h, pre)
        h = ht.tanh_op(pre)
    y = ht.matmul_op(h, w_out)
    y = y + ht.broadcastto_op(b_out, y)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y
