"""ResNet-18/34 for CIFAR (capability parity with reference
examples/cnn/models/ResNet.py — basic blocks, BN, global pool)."""
import hetu_tpu as ht
from hetu_tpu import init


def conv_bn(x, in_c, out_c, stride, name, kernel=3):
    pad = kernel // 2
    w = init.he_normal((out_c, in_c, kernel, kernel), name=name + '_weight')
    x = ht.conv2d_op(x, w, padding=pad, stride=stride)
    scale = init.ones((out_c,), name=name + '_bn_scale')
    bias = init.zeros((out_c,), name=name + '_bn_bias')
    return ht.batch_normalization_op(x, scale, bias)


def basic_block(x, in_c, out_c, stride, name):
    out = conv_bn(x, in_c, out_c, stride, name + '_conv1')
    out = ht.relu_op(out)
    out = conv_bn(out, out_c, out_c, 1, name + '_conv2')
    if stride != 1 or in_c != out_c:
        x = conv_bn(x, in_c, out_c, stride, name + '_short', kernel=1)
    return ht.relu_op(out + x)


def _resnet(x, y_, layers, num_class=10):
    cur_c = 64
    x = ht.relu_op(conv_bn(x, 3, cur_c, 1, 'resnet_stem'))
    for stage, (n_blocks, out_c, stride) in enumerate(
            zip(layers, (64, 128, 256, 512), (1, 2, 2, 2))):
        for b in range(n_blocks):
            x = basic_block(x, cur_c, out_c, stride if b == 0 else 1,
                            f'resnet_s{stage}_b{b}')
            cur_c = out_c
    # global average pool: (N, 512, 4, 4) -> (N, 512)
    x = ht.reduce_mean_op(x, [2, 3])
    w = init.he_normal((512, num_class), name='resnet_fc_weight')
    b = init.zeros((num_class,), name='resnet_fc_bias')
    y = ht.matmul_op(x, w)
    y = y + ht.broadcastto_op(b, y)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y


def resnet18(x, y_, num_class=10):
    print('Building ResNet-18 model...')
    return _resnet(x, y_, (2, 2, 2, 2), num_class)


def resnet34(x, y_, num_class=10):
    print('Building ResNet-34 model...')
    return _resnet(x, y_, (3, 4, 6, 3), num_class)
