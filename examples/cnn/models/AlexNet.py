"""AlexNet (CIFAR-sized, reference examples/cnn/models/AlexNet.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def conv_relu(x, shape, name, padding=1, stride=1):
    w = init.he_normal(shape, name=name + '_weight')
    x = ht.conv2d_op(x, w, padding=padding, stride=stride)
    return ht.relu_op(x)


def fc(x, shape, name, with_relu=True):
    w = init.he_normal(shape, name=name + '_weight')
    b = init.zeros(shape[-1:], name=name + '_bias')
    y = ht.matmul_op(x, w)
    y = y + ht.broadcastto_op(b, y)
    return ht.relu_op(y) if with_relu else y


def alexnet(x, y_, num_class=10):
    print('Building AlexNet model...')
    x = conv_relu(x, (64, 3, 3, 3), 'alexnet_conv1', padding=1)
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)            # 16x16
    x = conv_relu(x, (192, 64, 3, 3), 'alexnet_conv2', padding=1)
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)            # 8x8
    x = conv_relu(x, (384, 192, 3, 3), 'alexnet_conv3', padding=1)
    x = conv_relu(x, (256, 384, 3, 3), 'alexnet_conv4', padding=1)
    x = conv_relu(x, (256, 256, 3, 3), 'alexnet_conv5', padding=1)
    x = ht.max_pool2d_op(x, 2, 2, 0, 2)            # 4x4
    x = ht.array_reshape_op(x, (-1, 256 * 4 * 4))
    x = ht.dropout_op(fc(x, (256 * 4 * 4, 1024), 'alexnet_fc1'), 0.5)
    x = ht.dropout_op(fc(x, (1024, 512), 'alexnet_fc2'), 0.5)
    y = fc(x, (512, num_class), 'alexnet_fc3', with_relu=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y
