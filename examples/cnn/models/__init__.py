from .MLP import mlp
from .LogReg import logreg
from .CNN import cnn_3_layers
from .LeNet import lenet
from .AlexNet import alexnet
from .VGG import vgg16, vgg19
from .ResNet import resnet18, resnet34
from .RNN import rnn
from .LSTM import lstm
from .ViT import vit
