from .MLP import mlp
from .LogReg import logreg
from .CNN import cnn_3_layers
from .LeNet import lenet
