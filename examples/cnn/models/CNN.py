"""3-layer CNN for MNIST (reference examples/cnn/models/CNN.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def conv_relu_avg(x, shape, name):
    weight = init.random_normal(shape=shape, stddev=0.1, name=name + '_weight')
    x = ht.conv2d_op(x, weight, padding=2, stride=1)
    x = ht.relu_op(x)
    return ht.avg_pool2d_op(x, kernel_H=2, kernel_W=2, padding=0, stride=2)


def fc(x, shape, name):
    weight = init.random_normal(shape=shape, stddev=0.1, name=name + '_weight')
    bias = init.random_normal(shape=shape[-1:], stddev=0.1, name=name + '_bias')
    x = ht.array_reshape_op(x, (-1, shape[0]))
    y = ht.matmul_op(x, weight)
    return y + ht.broadcastto_op(bias, y)


def cnn_3_layers(x, y_, num_class=10):
    """x expected as (N, 1, 28, 28)."""
    print('Building CNN-3 model...')
    x = conv_relu_avg(x, (32, 1, 5, 5), 'cnn3_conv1')
    x = conv_relu_avg(x, (64, 32, 5, 5), 'cnn3_conv2')
    y = fc(x, (7 * 7 * 64, num_class), 'cnn3_fc')
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y
