"""VGG-16/19 for CIFAR (reference examples/cnn/models/VGG.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def conv_bn_relu(x, in_c, out_c, name):
    w = init.he_normal((out_c, in_c, 3, 3), name=name + '_weight')
    x = ht.conv2d_op(x, w, padding=1, stride=1)
    scale = init.ones((out_c,), name=name + '_bn_scale')
    bias = init.zeros((out_c,), name=name + '_bn_bias')
    x = ht.batch_normalization_op(x, scale, bias)
    return ht.relu_op(x)


def vgg_block(x, in_c, out_c, repeat, name):
    for i in range(repeat):
        x = conv_bn_relu(x, in_c if i == 0 else out_c, out_c, f'{name}_{i}')
    return ht.max_pool2d_op(x, kernel_H=2, kernel_W=2, padding=0, stride=2)


def fc(x, shape, name, with_relu=True):
    w = init.he_normal(shape, name=name + '_weight')
    b = init.zeros(shape[-1:], name=name + '_bias')
    y = ht.matmul_op(x, w)
    y = y + ht.broadcastto_op(b, y)
    return ht.relu_op(y) if with_relu else y


def _vgg(x, y_, repeats, num_class=10):
    for i, (out_c, rep) in enumerate(zip((64, 128, 256, 512, 512), repeats)):
        x = vgg_block(x, 3 if i == 0 else (64, 128, 256, 512, 512)[i - 1],
                      out_c, rep, f'vgg_block{i}')
    x = ht.array_reshape_op(x, (-1, 512))
    x = fc(x, (512, 4096), 'vgg_fc1')
    x = fc(x, (4096, 4096), 'vgg_fc2')
    y = fc(x, (4096, num_class), 'vgg_fc3', with_relu=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y


def vgg16(x, y_, num_class=10):
    print('Building VGG-16 model...')
    return _vgg(x, y_, (2, 2, 3, 3, 3), num_class)


def vgg19(x, y_, num_class=10):
    print('Building VGG-19 model...')
    return _vgg(x, y_, (2, 2, 4, 4, 4), num_class)
