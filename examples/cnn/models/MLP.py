"""3-layer MLP (capability parity with reference examples/cnn/models/MLP.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def fc(x, shape, name, with_relu=True):
    weight = init.random_normal(shape=shape, stddev=0.1, name=name + '_weight')
    bias = init.random_normal(shape=shape[-1:], stddev=0.1, name=name + '_bias')
    x = ht.matmul_op(x, weight)
    x = x + ht.broadcastto_op(bias, x)
    if with_relu:
        x = ht.relu_op(x)
    return x


def mlp(x, y_, num_class=10, input_dim=3072):
    """MLP for flattened CIFAR10 (3072) or MNIST (784)."""
    print("Building MLP model...")
    x = fc(x, (input_dim, 256), 'mlp_fc1', with_relu=True)
    x = fc(x, (256, 256), 'mlp_fc2', with_relu=True)
    y = fc(x, (256, num_class), 'mlp_fc3', with_relu=False)
    loss = ht.softmaxcrossentropy_op(y, y_)
    loss = ht.reduce_mean_op(loss, [0])
    return loss, y
