"""LSTM over MNIST rows as a 28-step sequence (reference
examples/cnn/models/LSTM.py — statically unrolled; the 4 gate matmuls are
fused into one (D, 4H) projection so each step is a single MXU call)."""
import hetu_tpu as ht
from hetu_tpu import init


def lstm(x, y_, num_class=10, dimhidden=128, diminput=28, nsteps=28):
    print('Building LSTM model...')
    H = dimhidden
    w_ih = init.xavier_uniform((diminput, 4 * H), name='lstm_w_ih')
    w_hh = init.xavier_uniform((H, 4 * H), name='lstm_w_hh')
    b = init.zeros((4 * H,), name='lstm_b')
    w_out = init.random_normal((H, num_class), stddev=0.1, name='lstm_w_out')
    b_out = init.zeros((num_class,), name='lstm_b_out')

    h, c = None, None
    for t in range(nsteps):
        x_t = ht.slice_op(x, (0, t * diminput), (-1, diminput))
        gates = ht.matmul_op(x_t, w_ih)
        if h is not None:
            gates = gates + ht.matmul_op(h, w_hh)
        gates = gates + ht.broadcastto_op(b, gates)
        i = ht.sigmoid_op(ht.slice_op(gates, (0, 0), (-1, H)))
        f = ht.sigmoid_op(ht.slice_op(gates, (0, H), (-1, H)))
        g = ht.tanh_op(ht.slice_op(gates, (0, 2 * H), (-1, H)))
        o = ht.sigmoid_op(ht.slice_op(gates, (0, 3 * H), (-1, H)))
        c = i * g if c is None else f * c + i * g
        h = o * ht.tanh_op(c)
    y = ht.matmul_op(h, w_out)
    y = y + ht.broadcastto_op(b_out, y)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y
