"""LeNet-5 (reference examples/cnn/models/LeNet.py)."""
import hetu_tpu as ht
from hetu_tpu import init


def conv_pool(x, in_channel, out_channel, name):
    weight = init.random_normal(
        shape=(out_channel, in_channel, 5, 5), stddev=0.1, name=name + '_weight')
    x = ht.conv2d_op(x, weight, padding=2, stride=1)
    x = ht.relu_op(x)
    return ht.max_pool2d_op(x, kernel_H=2, kernel_W=2, padding=0, stride=2)


def fc(x, shape, name, with_relu=True):
    weight = init.random_normal(shape=shape, stddev=0.1, name=name + '_weight')
    bias = init.random_normal(shape=shape[-1:], stddev=0.1, name=name + '_bias')
    y = ht.matmul_op(x, weight)
    y = y + ht.broadcastto_op(bias, y)
    if with_relu:
        y = ht.relu_op(y)
    return y


def lenet(x, y_, num_class=10):
    """x expected as (N, 1, 28, 28)."""
    print('Building LeNet model...')
    x = conv_pool(x, 1, 6, 'lenet_conv1')
    x = conv_pool(x, 6, 16, 'lenet_conv2')
    x = ht.array_reshape_op(x, (-1, 7 * 7 * 16))
    x = fc(x, (7 * 7 * 16, 120), 'lenet_fc1')
    x = fc(x, (120, 84), 'lenet_fc2')
    y = fc(x, (84, num_class), 'lenet_fc3', with_relu=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    return loss, y
