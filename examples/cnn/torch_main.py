"""PyTorch competitor twin (reference ``examples/cnn/torch_main.py``): the
same models on the same data through ANOTHER framework, for A/B against the
graph-API executor and the pure-JAX twin. CPU build of torch in this image;
optional DataParallel-style multi-process DDP over gloo when launched with
the standard torch.distributed env (WORLD_SIZE/RANK/MASTER_ADDR), mirroring
the reference's DDP mode (torch_main.py worker(): init_process_group +
DistributedDataParallel).

Run:  python torch_main.py --model mlp --dataset MNIST --num-epochs 1
DDP:  torchrun --nproc-per-node 2 torch_main.py --model mlp --dataset MNIST
"""
import argparse
import os
import sys
import time

import numpy as np
import torch
import torch.nn as nn

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def build_model(name, dataset):
    n_cls = 10
    if name == "mlp":
        in_dim = 784 if dataset == "MNIST" else 3072
        return nn.Sequential(nn.Flatten(), nn.Linear(in_dim, 256), nn.ReLU(),
                             nn.Linear(256, 256), nn.ReLU(),
                             nn.Linear(256, n_cls))
    if name == "lenet":
        in_ch = 1 if dataset == "MNIST" else 3
        side = 28 if dataset == "MNIST" else 32
        flat = 16 * ((side // 4 - 2) ** 2)
        return nn.Sequential(
            nn.Conv2d(in_ch, 6, 5, padding=2), nn.ReLU(), nn.MaxPool2d(2),
            nn.Conv2d(6, 16, 5), nn.ReLU(), nn.MaxPool2d(2),
            nn.Flatten(), nn.Linear(flat, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(), nn.Linear(84, n_cls))
    raise SystemExit(f"unknown model {name!r} (torch twin has mlp, lenet)")


def load_data(dataset, model):
    """Same loaders as the hetu_tpu examples (synthetic fallback, no
    egress) so the A/B trains on identical bytes."""
    from hetu_tpu import data as htdata
    if dataset == "MNIST":
        (tx, ty), (vx, vy), _ = htdata.mnist(onehot=False)
        if model != "mlp":
            tx = tx.reshape(-1, 1, 28, 28)
            vx = vx.reshape(-1, 1, 28, 28)
    else:
        tx, ty, vx, vy = htdata.normalize_cifar(onehot=False)
        if model == "mlp":
            tx = tx.reshape(len(tx), -1)
            vx = vx.reshape(len(vx), -1)
    return (tx.astype(np.float32), np.asarray(ty, np.int64),
            vx.astype(np.float32), np.asarray(vy, np.int64))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="mlp")
    ap.add_argument("--dataset", default="MNIST",
                    choices=("MNIST", "CIFAR10"))
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--validate", action="store_true")
    ap.add_argument("--timing", action="store_true")
    args = ap.parse_args(argv)

    ddp = int(os.environ.get("WORLD_SIZE", "1")) > 1
    rank = int(os.environ.get("RANK", "0"))
    if ddp:
        import torch.distributed as dist
        dist.init_process_group("gloo")
    torch.manual_seed(0)

    model = build_model(args.model, args.dataset)
    if ddp:
        model = nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.SGD(model.parameters(), lr=args.learning_rate,
                          momentum=0.9)
    loss_fn = nn.CrossEntropyLoss()
    tx, ty, vx, vy = load_data(args.dataset, args.model)

    n = len(tx)
    last_acc = 0.0
    for epoch in range(args.num_epochs):
        order = np.random.RandomState(epoch).permutation(n)
        if ddp:  # each rank trains its own shard of the epoch (DDP averages)
            order = order[rank::int(os.environ["WORLD_SIZE"])]
        t0 = time.time()
        tot, correct, seen = 0.0, 0, 0
        for s in range(len(order) // args.batch_size):
            idx = order[s * args.batch_size:(s + 1) * args.batch_size]
            x = torch.from_numpy(tx[idx])
            y = torch.from_numpy(ty[idx])
            opt.zero_grad()
            out = model(x)
            loss = loss_fn(out, y)
            loss.backward()
            opt.step()
            tot += float(loss.detach())
            correct += int((out.argmax(1) == y).sum())
            seen += len(idx)
        last_acc = correct / max(seen, 1)
        if rank == 0:
            msg = (f"epoch {epoch}: loss {tot / max(1, len(order) // args.batch_size):.4f} "
                   f"acc {last_acc:.4f}")
            if args.timing:
                msg += f" time {time.time() - t0:.2f}s"
            print(msg, flush=True)
        if args.validate and rank == 0:
            with torch.no_grad():
                out = model(torch.from_numpy(vx[:2048]))
                vacc = float((out.argmax(1)
                              == torch.from_numpy(vy[:2048])).float().mean())
            print(f"  validate acc {vacc:.4f}", flush=True)
    if ddp:
        import torch.distributed as dist
        dist.destroy_process_group()
    return last_acc


if __name__ == "__main__":
    main()
