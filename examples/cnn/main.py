"""CNN example trainer — capability parity with reference examples/cnn/main.py.

Usage:
    python main.py --model mlp --dataset CIFAR10 --num-epochs 3 --validate --timing
    python main.py --model lenet --dataset MNIST --comm-mode AllReduce
"""
import argparse
import json
import logging
import os
import sys
from time import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))
import hetu_tpu as ht
import models

logging.basicConfig(level=logging.INFO,
                    format='%(asctime)s - %(name)s - %(levelname)s - %(message)s')
logger = logging.getLogger(__name__)


def print_rank0(msg):
    if device_id == 0:
        logger.info(msg)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', type=str, required=True)
    parser.add_argument('--dataset', type=str, required=True)
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--learning-rate', type=float, default=0.1)
    parser.add_argument('--opt', type=str, default='sgd',
                        help='sgd / momentum / nesterov / adagrad / adam')
    parser.add_argument('--num-epochs', type=int, default=10)
    parser.add_argument('--gpu', type=int, default=0,
                        help='device id; -1 means cpu (accepts tpu ids too)')
    parser.add_argument('--validate', action='store_true')
    parser.add_argument('--timing', action='store_true')
    parser.add_argument('--comm-mode', default=None)
    args = parser.parse_args()

    global device_id
    device_id = 0
    if args.comm_mode in ('AllReduce', 'Hybrid'):
        comm, device_id = ht.mpi_nccl_init()
        executor_ctx = ht.tpu(device_id) if args.gpu >= 0 else ht.cpu(0)
    else:
        executor_ctx = ht.cpu(0) if args.gpu == -1 else ht.tpu(args.gpu)
    print_rank0(f"Training {args.model} on hetu_tpu (ctx={executor_ctx})")

    model = getattr(models, args.model)
    assert args.dataset in ['MNIST', 'CIFAR10', 'CIFAR100']

    opt = {
        'sgd': lambda: ht.optim.SGDOptimizer(learning_rate=args.learning_rate),
        'momentum': lambda: ht.optim.MomentumOptimizer(learning_rate=args.learning_rate),
        'nesterov': lambda: ht.optim.MomentumOptimizer(
            learning_rate=args.learning_rate, nesterov=True),
        'adagrad': lambda: ht.optim.AdaGradOptimizer(
            learning_rate=args.learning_rate, initial_accumulator_value=0.1),
        'adam': lambda: ht.optim.AdamOptimizer(learning_rate=args.learning_rate),
    }[args.opt]()

    print_rank0('Loading %s data...' % args.dataset)
    if args.dataset == 'MNIST':
        datasets = ht.data.mnist()
        train_set_x, train_set_y = datasets[0]
        valid_set_x, valid_set_y = datasets[1]
        if args.model in ('cnn_3_layers', 'lenet'):
            train_set_x = train_set_x.reshape(-1, 1, 28, 28)
            valid_set_x = valid_set_x.reshape(-1, 1, 28, 28)
        input_dim = 784
        num_class = 10
    else:
        num_class = 10 if args.dataset == 'CIFAR10' else 100
        train_set_x, train_set_y, valid_set_x, valid_set_y = ht.data.normalize_cifar(
            num_class=num_class)
        if args.model == 'mlp':
            train_set_x = train_set_x.reshape(train_set_x.shape[0], -1)
            valid_set_x = valid_set_x.reshape(valid_set_x.shape[0], -1)
        input_dim = 3072

    x = ht.dataloader_op([
        ht.Dataloader(train_set_x, args.batch_size, 'train'),
        ht.Dataloader(valid_set_x, args.batch_size, 'validate'),
    ])
    y_ = ht.dataloader_op([
        ht.Dataloader(train_set_y, args.batch_size, 'train'),
        ht.Dataloader(valid_set_y, args.batch_size, 'validate'),
    ])
    if args.model in ('mlp', 'logreg'):
        loss, y = model(x, y_, num_class, input_dim)
    elif args.model == 'vit':
        # attention reshapes need the static batch size
        loss, y = model(x, y_, num_class, batch=args.batch_size)
    else:
        loss, y = model(x, y_, num_class)
    train_op = opt.minimize(loss)

    eval_nodes = {'train': [loss, y, y_, train_op], 'validate': [loss, y, y_]}
    executor = ht.Executor(eval_nodes, ctx=executor_ctx, comm_mode=args.comm_mode)
    n_train_batches = executor.get_batch_num('train')
    n_valid_batches = executor.get_batch_num('validate')

    print_rank0("Start training loop...")
    running_time = 0
    for i in range(args.num_epochs + 1):
        print_rank0("Epoch %d" % i)
        loss_all = 0
        batch_num = 0
        if args.timing:
            start = time()
        correct_predictions = []
        for minibatch_index in range(n_train_batches):
            loss_val, predict_y, y_val, _ = executor.run(
                'train', eval_node_list=[loss, y, y_, train_op])
            predict_y = predict_y.asnumpy()
            y_val = y_val.asnumpy()
            loss_all += loss_val.asnumpy()
            batch_num += 1
            correct_predictions.extend(
                np.equal(np.argmax(y_val, 1), np.argmax(predict_y, 1)).astype(float))
        loss_all /= batch_num
        print_rank0("Train loss = %f" % loss_all)
        print_rank0("Train accuracy = %f" % np.mean(correct_predictions))

        if args.timing:
            during_time = time() - start
            print_rank0("Running time of current epoch = %fs" % during_time)
            if i != 0:
                running_time += during_time
        if args.validate:
            correct_predictions = []
            val_loss_all = 0
            for minibatch_index in range(n_valid_batches):
                loss_val, valid_y_predicted, y_val = executor.run(
                    'validate', convert_to_numpy_ret_vals=True)
                val_loss_all += loss_val
                correct_predictions.extend(
                    np.equal(np.argmax(y_val, 1),
                             np.argmax(valid_y_predicted, 1)).astype(float))
            print_rank0("Validation loss = %f" % (val_loss_all / n_valid_batches))
            print_rank0("Validation accuracy = %f" % np.mean(correct_predictions))
    print_rank0("*" * 50)
    print_rank0("Running time of total %d epoch = %fs" % (args.num_epochs, running_time))
