#!/bin/bash
# 2 workers + 1 PS server via heturun (reference scripts/hetu_2gpu_ps.sh)
cd "$(dirname "$0")/.." || exit 1
PYTHONPATH="$(cd ../.. && pwd):$PYTHONPATH" exec ../../bin/heturun -c settings/local_s1_w2.yml \
    python main.py --model "${1:-mlp}" --dataset CIFAR10 --comm-mode PS --timing "${@:2}"
