#!/bin/bash
# single-chip training (reference scripts/hetu_1gpu.sh)
cd "$(dirname "$0")/.." || exit 1
python main.py --model "${1:-resnet18}" --dataset CIFAR10 --validate --timing "${@:2}"
