#!/bin/bash
# 8-device data parallelism (reference scripts/hetu_8gpu.sh). On a real
# v5e-8 the mesh is the 8 chips; off-TPU this provisions a virtual 8-CPU
# mesh — same program either way (GSPMD inserts the gradient allreduce).
cd "$(dirname "$0")/.." || exit 1
if [ -z "$TPU_CHIPS" ]; then
  export JAX_PLATFORMS=cpu
  export XLA_FLAGS="--xla_force_host_platform_device_count=8 $XLA_FLAGS"
fi
python main.py --model "${1:-resnet18}" --dataset CIFAR10 \
    --comm-mode AllReduce --validate --timing "${@:2}"
