"""Pure-JAX ResNet-18 training twin — the A/B competitor the reference keeps
in-repo for its own benchmarks (``examples/cnn/{tf_main,torch_main}.py``,
``run_tf_horovod.py``): the same model and step, written directly against
jax with no framework, so the graph-API executor's overhead is measurable
as (twin samples/s) / (executor samples/s).

Run: ``python jax_twin.py [--batch-size 256] [--dtype bf16]``
"""
import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def make_resnet18(cdtype):
    """Returns (init_params, loss_fn) matching models/ResNet.py's
    architecture (basic blocks 2-2-2-2, BN, global pool) in NCHW."""

    def conv(x, w, stride, pad):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def bn(x, scale, bias):
        m = jnp.mean(x, (0, 2, 3), keepdims=True)
        v = jnp.var(x, (0, 2, 3), keepdims=True)
        shp = (1, -1, 1, 1)
        return ((x - m) * jax.lax.rsqrt(v + 1e-2) * scale.reshape(shp)
                + bias.reshape(shp))

    def init_params(key):
        params = []

        def add_conv(key, cin, cout, k):
            w = jax.random.normal(key, (cout, cin, k, k), jnp.float32) \
                * np.sqrt(2.0 / (cin * k * k))
            params.append((w, jnp.ones(cout), jnp.zeros(cout)))

        keys = iter(jax.random.split(key, 64))
        add_conv(next(keys), 3, 64, 3)
        cur = 64
        for (nb, outc, stride) in zip((2, 2, 2, 2), (64, 128, 256, 512),
                                      (1, 2, 2, 2)):
            for b in range(nb):
                s = stride if b == 0 else 1
                add_conv(next(keys), cur, outc, 3)
                add_conv(next(keys), outc, outc, 3)
                if s != 1 or cur != outc:
                    add_conv(next(keys), cur, outc, 1)
                cur = outc
        wfc = jax.random.normal(next(keys), (512, 10), jnp.float32) * 0.05
        params.append((wfc, jnp.zeros(10)))
        return params

    def apply(params, x):
        x = x.astype(cdtype)
        it = iter(params[:-1])

        def cbr(x, stride, relu=True):
            w, s, b = next(it)
            k = w.shape[2]
            out = conv(x, w.astype(cdtype), stride, k // 2)
            out = bn(out, s.astype(cdtype), b.astype(cdtype))
            return jax.nn.relu(out) if relu else out

        x = cbr(x, 1)
        cur = 64
        for (nb, outc, stride) in zip((2, 2, 2, 2), (64, 128, 256, 512),
                                      (1, 2, 2, 2)):
            for b in range(nb):
                s = stride if b == 0 else 1
                h = cbr(x, s)
                h = cbr(h, 1, relu=False)
                if s != 1 or cur != outc:
                    x = cbr(x, s, relu=False)
                x = jax.nn.relu(h + x)
                cur = outc
        x = jnp.mean(x, (2, 3))
        wfc, bfc = params[-1]
        return (x @ wfc.astype(cdtype) + bfc.astype(cdtype)).astype(
            jnp.float32)

    def loss_fn(params, x, y):
        logp = jax.nn.log_softmax(apply(params, x))
        return -jnp.mean(jnp.sum(y * logp, axis=1))

    return init_params, loss_fn


def bench(batch_size=256, dtype="bf16", iters=30, warmup=5, lr=0.1,
          momentum=0.9):
    cdtype = jnp.bfloat16 if dtype in ("bf16", "bfloat16") else jnp.float32
    init_params, loss_fn = make_resnet18(cdtype)
    params = init_params(jax.random.PRNGKey(0))
    mom = jax.tree.map(jnp.zeros_like, params)

    @jax.jit
    def step(params, mom, x, y):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        g = jax.tree.map(lambda v: v.astype(jnp.float32), g)
        mom = jax.tree.map(lambda m, gv: momentum * m + gv, mom, g)
        params = jax.tree.map(lambda p, m: p - lr * m, params, mom)
        return loss, params, mom

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch_size, 3, 32, 32), jnp.float32)
    y = jnp.asarray(np.eye(10)[rng.randint(0, 10, batch_size)], jnp.float32)
    for _ in range(warmup):
        loss, params, mom = step(params, mom, x, y)
    float(np.asarray(loss))  # HARD host roundtrip: on tunneled chips a bare
    t0 = time.time()         # block_until_ready can report early
    for _ in range(iters):
        loss, params, mom = step(params, mom, x, y)
    float(np.asarray(loss))
    dt = (time.time() - t0) / iters
    return batch_size / dt, dt * 1000


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--dtype", default="bf16", choices=["f32", "bf16"])
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    sps, ms = bench(args.batch_size, args.dtype, args.iters)
    print(f"jax twin resnet18 bs={args.batch_size} {args.dtype}: "
          f"{sps:,.1f} samples/s  {ms:.2f} ms/step")


if __name__ == "__main__":
    main()
