"""Sampled-subgraph GCN over a live parameter server + embedding cache —
the reference's GraphMix-style GNN training mode
(``examples/gnn/run_dist.py:17-49``: workers train on sampled subgraphs,
node embeddings pulled through the PS with the cache in front), rebuilt
TPU-native:

- the graph lives host-side; each step a worker samples a FIXED-size 1-hop
  subgraph (static shapes -> ONE jitted program, no retrace per batch),
- trainable node embeddings are a sparse table on the PS fronted by
  ``CacheSparseTable`` (LRU/LFU/LFUOpt, bounded staleness): lookups pull
  only the sampled rows, row gradients push back through the cache,
- the sampler feeds the executor through ``GNNDataLoaderOp`` double
  buffering (reference dataloader.py:98): batch N+1's cache pull is issued
  while step N trains,
- dense GCN weights train on-device with Adam; embedding rows arrive as a
  placeholder and leave as an explicit gradient target (`ht.gradients`).

Standalone (self-provisions a local scheduler + server):
  python examples/gnn/run_sampled.py --num-epoch 10 --cpu
Inside a heturun cluster (DMLC_* env set): the same command, one process
per worker.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


# ---------------------------------------------------------------------------
# synthetic partitioned graph (no-egress stand-in for Reddit/OGB: a planted
# 4-community SBM whose labels are recoverable from graph structure)
# ---------------------------------------------------------------------------

def make_graph(n_nodes, n_classes, avg_degree, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n_nodes)
    p_in = avg_degree / (n_nodes / n_classes) * 0.8
    p_out = avg_degree / n_nodes * 0.2
    adj = [[] for _ in range(n_nodes)]
    for u in range(n_nodes):
        same = np.where(labels == labels[u])[0]
        diff = np.where(labels != labels[u])[0]
        nbr = np.concatenate([
            same[rng.rand(len(same)) < p_in],
            diff[rng.rand(len(diff)) < p_out]])
        for v in nbr:
            if v != u:
                adj[u].append(int(v))
                adj[int(v)].append(u)
    return [np.unique(a) for a in adj], labels


class SubgraphSampler:
    """Fixed-shape 1-hop sampler: NSEED seed nodes + neighbors, capped at
    NMAX total, zero-padded. Padding is inert: padded adjacency rows/cols
    are all-zero (no self-loop), so padded embedding rows get exactly zero
    gradient and their (deduped) pushes are no-ops."""

    def __init__(self, adj, labels, nseed, nmax, fanout, seed=0):
        self.adj, self.labels = adj, labels
        self.nseed, self.nmax, self.fanout = nseed, nmax, fanout
        self.rng = np.random.RandomState(seed)
        self.order = self.rng.permutation(len(adj))
        self.cursor = 0

    def next(self):
        n = len(self.adj)
        if self.cursor + self.nseed > n:
            self.order = self.rng.permutation(n)
            self.cursor = 0
        seeds = self.order[self.cursor:self.cursor + self.nseed]
        self.cursor += self.nseed
        nodes = list(seeds)
        seen = set(seeds.tolist())
        for s in seeds:
            nb = self.adj[s]
            if len(nb) > self.fanout:
                nb = self.rng.choice(nb, self.fanout, replace=False)
            for v in nb:
                if v not in seen and len(nodes) < self.nmax:
                    seen.add(int(v))
                    nodes.append(int(v))
        ids = np.zeros(self.nmax, np.uint64)
        ids[:len(nodes)] = nodes
        pos = {v: i for i, v in enumerate(nodes)}
        a = np.zeros((self.nmax, self.nmax), np.float32)
        a[:len(nodes), :len(nodes)] = np.eye(len(nodes))  # self-loops
        for i, u in enumerate(nodes):
            for v in self.adj[u]:
                j = pos.get(int(v))
                if j is not None:
                    a[i, j] = 1.0
        deg = np.maximum(a.sum(1), 1.0)
        dinv = 1.0 / np.sqrt(deg)
        norm_adj = (a * dinv[:, None]) * dinv[None, :]    # D^-1/2 A D^-1/2
        return {"adj": norm_adj, "ids": ids,
                "y": self.labels[seeds].astype(np.float32)}


class BatchFeed:
    """Two-slot pipeline rotated in lockstep with ``GNNDataLoaderOp.step``:
    the batch being BUILT becomes the op's _next (its cache pull issued
    asynchronously now), the previous _next becomes the current batch."""

    def __init__(self, sampler, table, hidden):
        self.sampler, self.table, self.hidden = sampler, table, hidden
        self.cur = None
        self._next = None

    def handler(self, _graph):
        b = self.sampler.next()
        b["rows"] = np.zeros((self.sampler.nmax, self.hidden), np.float32)
        b["wait"] = self.table.embedding_lookup(b["ids"], b["rows"])
        self.cur, self._next = self._next, b
        return b["adj"]


# ---------------------------------------------------------------------------
# training worker
# ---------------------------------------------------------------------------

def train(client, rank, args):
    import hetu_tpu as ht
    from hetu_tpu.cstable import CacheSparseTable
    from hetu_tpu.dataloader import GNNDataLoaderOp
    from hetu_tpu.graph.gradients import gradients as ht_gradients

    adj, labels = make_graph(args.nodes, args.classes, args.degree)
    sampler = SubgraphSampler(adj, labels, args.nseed, args.nmax,
                              args.fanout, seed=100 + rank)

    client.InitTensor(args.table_id, sparse=2, length=args.nodes,
                      width=args.hidden, init_type="normal", init_a=0.0,
                      init_b=0.1)
    table = CacheSparseTable(args.cache_limit, args.nodes, args.hidden,
                             args.table_id, policy=args.cache_policy,
                             bound=args.bound)
    if args.cache_perf:
        table.perf_enabled(True)
    feed = BatchFeed(sampler, table, args.hidden)

    adj_in = GNNDataLoaderOp(feed.handler)
    x = ht.placeholder_op(name="x")
    y_ = ht.placeholder_op(name="y")
    w1 = ht.init.xavier_uniform((args.hidden, args.hidden), name="w1")
    w2 = ht.init.xavier_uniform((args.hidden, args.classes), name="w2")
    h = ht.relu_op(ht.matmul_op(adj_in, ht.matmul_op(x, w1)))
    logits = ht.slice_op(ht.matmul_op(adj_in, ht.matmul_op(h, w2)),
                         (0, 0), (args.nseed, args.classes))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(logits, ht.one_hot_op(y_, args.classes)),
        [0])
    (grad_x,) = ht_gradients(loss, [x])
    opt = ht.optim.AdamOptimizer(learning_rate=args.learning_rate)
    train_op = opt.minimize(loss, var_list=[w1, w2])
    pred = ht.softmax_op(logits)

    ex = ht.Executor({"train": [loss, grad_x, pred, train_op]},
                     ctx=ht.cpu(0) if args.cpu else ht.tpu(0), seed=rank)

    GNNDataLoaderOp.step(None)   # build batch 1 into _next
    GNNDataLoaderOp.step(None)   # batch 1 -> current; batch 2 building
    # per-epoch step count splits the graph across the LIVE cluster size
    nworld = max(client.nrank, 1)
    steps = max(1, args.nodes // (args.nseed * nworld))
    history = []
    try:
        for epoch in range(args.num_epoch):
            tot_loss = tot_acc = 0.0
            t0 = time.time()
            for _ in range(steps):
                b = feed.cur
                b["wait"].wait()          # this batch's rows have landed
                lv, gx, pv, _ = ex.run("train",
                                       feed_dict={x: b["rows"], y_: b["y"]})
                table.embedding_update(
                    b["ids"], -args.learning_rate * gx.asnumpy())
                GNNDataLoaderOp.step(None)  # rotate; issue next cache pull
                tot_loss += float(np.mean(lv.asnumpy()))
                tot_acc += float(np.mean(np.argmax(pv.asnumpy(), 1)
                                         == b["y"]))
            history.append((tot_loss / steps, tot_acc / steps))
            if rank == 0:
                print(f"[rank {rank}] epoch {epoch}: "
                      f"loss {history[-1][0]:.4f} acc {history[-1][1]:.3f} "
                      f"({time.time() - t0:.2f}s)", flush=True)
        if args.cache_perf and rank == 0:
            print(f"cache miss rate: {table.overall_miss_rate():.3f}",
                  flush=True)
    finally:
        # drain in-flight cache pulls BEFORE anyone calls Finalize — a pull
        # mid-recv when the sockets close wedges the cache worker thread
        for b in (feed.cur, feed._next):
            if b is not None and "wait" in b:
                b["wait"].wait()
        adj_in.close()   # deregister: a later run's step() must not fire us
        ex.close()
    return history


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=512)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--degree", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--nseed", type=int, default=32)
    ap.add_argument("--nmax", type=int, default=128)
    ap.add_argument("--fanout", type=int, default=8)
    ap.add_argument("--num-epoch", type=int, default=10)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--workers", type=int, default=1,
                    help="standalone only: size of the self-provisioned "
                         "cluster (under heturun the live nrank is used)")
    ap.add_argument("--table-id", type=int, default=7)
    ap.add_argument("--cache-limit", type=int, default=128)
    ap.add_argument("--cache-policy", default="LRU",
                    choices=["LRU", "LFU", "LFUOpt"])
    ap.add_argument("--bound", type=int, default=2)
    ap.add_argument("--cache-perf", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (tests / no-TPU hosts)")
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if "DMLC_ROLE" in os.environ:      # launched by heturun: just train
        from hetu_tpu.ps.client import PSClient
        client = PSClient.from_env()
        try:
            train(client, client.rank, args)
        finally:
            client.close()
        return

    from hetu_tpu.ps.local_cluster import local_cluster
    with local_cluster(n_servers=1, n_workers=1):
        from hetu_tpu.ps.client import PSClient
        client = PSClient.from_env()
        try:
            train(client, 0, args)
        finally:
            client.close()


if __name__ == "__main__":
    main()
