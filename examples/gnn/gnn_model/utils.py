"""Graph data helpers (reference ``gnn_model/utils.py`` — get_norm_adj /
prepare_data over graphmix; here self-contained synthetic graphs, since the
reference's GraphMix submodule is an empty stub in the snapshot)."""
import numpy as np


def synthetic_graph(n_nodes=256, n_classes=4, feat_dim=16, avg_deg=6, seed=0):
    """Community-structured random graph: nodes in the same class link with
    higher probability, features are noisy class prototypes — learnable by a
    2-layer GCN."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n_nodes)
    protos = rng.randn(n_classes, feat_dim).astype(np.float32)
    feats = protos[labels] + 0.5 * rng.randn(n_nodes, feat_dim).astype(np.float32)
    p_in = avg_deg / (n_nodes / n_classes) * 0.7
    p_out = avg_deg / n_nodes * 0.3
    rows, cols = [], []
    for i in range(n_nodes):
        same = labels == labels[i]
        prob = np.where(same, p_in, p_out)
        nbrs = np.where(rng.rand(n_nodes) < prob)[0]
        rows.extend([i] * len(nbrs))
        cols.extend(nbrs)
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    # symmetrize + self loops, so the D^-1/2 A D^-1/2 normalization below is
    # the genuine GCN normalization (in-degree == out-degree)
    rows, cols = (np.concatenate([rows, cols, np.arange(n_nodes)]),
                  np.concatenate([cols, rows, np.arange(n_nodes)]))
    return rows, cols, feats, labels


def normalize_adj(rows, cols, n_nodes):
    """Symmetric GCN normalization D^-1/2 (A) D^-1/2 as COO values."""
    deg = np.bincount(rows, minlength=n_nodes).astype(np.float32)
    deg = np.maximum(deg, 1.0)
    vals = 1.0 / np.sqrt(deg[rows] * deg[cols])
    return vals.astype(np.float32)
