"""GNN models on the graph API (reference ``gnn_model/model.py`` dense_model /
sparse_model surface, minus the graphmix sampling client)."""
import numpy as np

import hetu_tpu as ht

from .layer import GCN


def convert_to_one_hot(vals, max_val=0):
    if max_val == 0:
        max_val = vals.max() + 1
    one_hot = np.zeros((vals.size, max_val), np.float32)
    one_hot[np.arange(vals.size), vals] = 1
    return one_hot


def dense_model(feature_dim, hidden_layer_size, num_classes, lr, arch=GCN):
    """Full-batch node classification: feats/labels/mask fed per step,
    normalized adjacency fed as a sparse Variable."""
    y_ = ht.Variable(name="y_", trainable=False)
    mask_ = ht.Variable(name="mask_", trainable=False)
    feat = ht.Variable(name="feat", trainable=False)
    norm_adj_ = ht.Variable(name="message_passing", trainable=False)

    gcn1 = arch(feature_dim, hidden_layer_size, norm_adj_, activation="relu",
                name="gcn1")
    gcn2 = arch(gcn1.output_width, num_classes, norm_adj_, name="gcn2")
    y = gcn2(gcn1(feat))
    loss = ht.softmaxcrossentropy_op(y, y_)
    train_loss = ht.reduce_mean_op(loss * mask_, [0])
    train_op = ht.optim.SGDOptimizer(lr).minimize(train_loss)
    return [train_loss, y, train_op], [feat, y_, mask_, norm_adj_]


def sparse_model(num_int_feature, hidden_layer_size, embedding_idx_max,
                 embedding_width, num_classes, lr):
    """Integer-feature variant: per-node categorical features pass through an
    embedding table before the GCN stack (reference sparse_model)."""
    y_ = ht.Variable(name="y_", trainable=False)
    mask_ = ht.Variable(name="mask_", trainable=False)
    index_ = ht.Variable(name="index_", trainable=False)
    norm_adj_ = ht.Variable(name="message_passing", trainable=False)

    embedding = ht.init.random_normal((embedding_idx_max, embedding_width),
                                      stddev=0.1, name="gnn_embedding")
    embed = ht.embedding_lookup_op(embedding, index_)
    feat = ht.array_reshape_op(embed, (-1, num_int_feature * embedding_width))

    gcn1 = GCN(num_int_feature * embedding_width, hidden_layer_size,
               norm_adj_, activation="relu", name="gcn1")
    gcn2 = GCN(gcn1.output_width, num_classes, norm_adj_, name="gcn2")
    y = gcn2(gcn1(feat))
    loss = ht.softmaxcrossentropy_op(y, y_)
    train_loss = ht.reduce_mean_op(loss * mask_, [0])
    train_op = ht.optim.SGDOptimizer(lr).minimize(train_loss)
    return [train_loss, y, train_op], [index_, y_, mask_, norm_adj_]
