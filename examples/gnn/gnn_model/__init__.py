from .layer import GCN, SageConv
from .model import dense_model, sparse_model, convert_to_one_hot
from .utils import synthetic_graph, normalize_adj
