"""GCN / GraphSAGE layers on the graph API (capability parity with
reference ``examples/gnn/gnn_model/layer.py``: GCN and SageConv over a
sparse normalized adjacency fed at runtime)."""
import hetu_tpu as ht
from hetu_tpu import init


class GCN:
    """h' = act(A_norm @ h @ W + b); ``norm_adj`` is a fed sparse Variable."""

    def __init__(self, in_features, out_features, norm_adj, activation=None,
                 name="gcn"):
        self.output_width = out_features
        self.weight = init.xavier_uniform((in_features, out_features),
                                          name=name + "_weight")
        self.bias = init.zeros((out_features,), name=name + "_bias")
        self.norm_adj = norm_adj
        self.activation = activation

    def __call__(self, x):
        msg = ht.distgcn_15d_op(self.norm_adj, x, self.weight)
        y = msg + ht.broadcastto_op(self.bias, msg)
        if self.activation == "relu":
            y = ht.relu_op(y)
        return y


class SageConv:
    """GraphSAGE mean aggregator: concat(h, A_norm @ h) @ W."""

    def __init__(self, in_features, out_features, norm_adj, activation=None,
                 name="sage"):
        self.output_width = out_features
        self.weight = init.xavier_uniform((2 * in_features, out_features),
                                          name=name + "_weight")
        self.bias = init.zeros((out_features,), name=name + "_bias")
        self.norm_adj = norm_adj
        self.activation = activation

    def __call__(self, x):
        neigh = ht.csrmm_op(self.norm_adj, x)
        h = ht.concat_op(x, neigh, axis=1)
        y = ht.matmul_op(h, self.weight)
        y = y + ht.broadcastto_op(self.bias, y)
        if self.activation == "relu":
            y = ht.relu_op(y)
        return y
