"""Single-device GCN training (reference ``examples/gnn/run_single.py``,
self-contained synthetic graph instead of the graphmix sampling service)."""
import argparse
import sys
import os
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))

import hetu_tpu as ht
from gnn_model import dense_model, convert_to_one_hot, synthetic_graph, \
    normalize_adj


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epoch", type=int, default=30)
    ap.add_argument("--hidden-size", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--learning-rate", type=float, default=0.5)
    args = ap.parse_args()

    rows, cols, feats, labels = synthetic_graph(args.nodes, args.classes)
    vals = normalize_adj(rows, cols, args.nodes)
    onehot = convert_to_one_hot(labels, args.classes)
    mask = (np.random.RandomState(1).rand(args.nodes) < 0.7).astype(np.float32)

    [loss, y, train_op], [feat_, y__, mask_, adj_] = dense_model(
        feats.shape[1], args.hidden_size, args.classes, args.learning_rate)
    ex = ht.Executor([loss, y, train_op], ctx=ht.cpu(0), seed=0)
    adj = ht.sparse_array(vals, (rows, cols), (args.nodes, args.nodes))

    t0 = time.time()
    for epoch in range(args.num_epoch):
        lv, yv, _ = ex.run("default", feed_dict={
            feat_: feats, y__: onehot, mask_: mask, adj_: adj},
            convert_to_numpy_ret_vals=True)
        pred = yv.argmax(1)
        test = mask == 0
        acc = float((pred[test] == labels[test]).mean())
        print(f"epoch {epoch}: train loss {float(np.mean(lv)):.4f} "
              f"test acc {acc:.3f}")
    print(f"total {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
