"""Distributed 1.5D GCN training (reference ``examples/gnn/run_dist.py:17-49``
+ ``tests/test_DistGCN``'s mpirun -np 8 --replication 2 configuration).

TPU-native: instead of mpirun + per-process NCCL groups, one program over a
``(gr, gc)`` device mesh; ``hetu_tpu.parallel.distgcn`` provides the 1.5D
spmm (all_gather over gr = the column-group broadcasts, psum over gc = the
row-group allreduce). Run on 8 virtual devices with:

  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python run_dist.py --replication 2
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..', '..'))

if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
        and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # virtual mesh run: default to 8 devices unless the user already forced
    # a count (last duplicate flag wins, so appending would override theirs)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replication", type=int, default=2)
    ap.add_argument("--num-epoch", type=int, default=30)
    ap.add_argument("--hidden-size", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--learning-rate", type=float, default=0.5)
    args = ap.parse_args()

    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # a sitecustomize may force-register an accelerator backend; the
        # config update after import is authoritative
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from hetu_tpu.parallel import distgcn
    from gnn_model import synthetic_graph, normalize_adj, convert_to_one_hot

    n_dev = len(jax.devices())
    r = args.replication
    assert n_dev % r == 0, (n_dev, r)
    gr = n_dev // r
    mesh = Mesh(np.array(jax.devices()).reshape(gr, r), ("gr", "gc"))
    print(f"mesh: gr={gr} gc={r} on {jax.devices()[0].platform}")

    n = args.nodes - args.nodes % (gr * r)  # divisible by both axes
    rows, cols, feats, labels = synthetic_graph(n, args.classes)
    vals = normalize_adj(rows, cols, n)
    onehot = jnp.asarray(convert_to_one_hot(labels, args.classes))
    mask = jnp.asarray(
        (np.random.RandomState(1).rand(n) < 0.7).astype(np.float32))

    adj, h = distgcn.shard_gcn_inputs(mesh, rows, cols, vals, feats, n)
    rng = np.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(feats.shape[1], args.hidden_size) * 0.2,
                      jnp.float32),
          jnp.asarray(rng.randn(args.hidden_size, args.classes) * 0.2,
                      jnp.float32)]

    def loss_fn(ws):
        logits = distgcn.gcn_forward(mesh, adj, h, ws, n)
        logp = jax.nn.log_softmax(logits)
        per_node = -jnp.sum(onehot * logp, axis=1)
        return jnp.mean(per_node * mask), logits

    @jax.jit
    def step(ws):
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(ws)
        return loss, logits, [w - args.learning_rate * g
                              for w, g in zip(ws, grads)]

    t0 = time.time()
    for epoch in range(args.num_epoch):
        loss, logits, ws = step(ws)
        pred = np.asarray(logits).argmax(1)
        test = np.asarray(mask) == 0
        acc = float((pred[test] == labels[test]).mean())
        print(f"epoch {epoch}: loss {float(loss):.4f} test acc {acc:.3f}")
    print(f"total {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
