"""Data + model parallel MLP on the graph API.

Port of the reference's ``examples/runner/parallel/data_model_pipeline_mlp.py``
(Dispatch.py:35-49): an MLP whose middle matmul is tensor-parallel over a
2-worker x 2-way model-parallel DeviceGroup, with the batch data-parallel
across the workers. The reference runs one MPI rank per GPU and rewrites the
graph into split/concat + P2P sends (context.py:184-274); here the tuple
DeviceGroup becomes a (dp, tp) ``jax.sharding.Mesh`` and each ``ht.dispatch``
marker becomes a GSPMD sharding constraint, so XLA inserts the collectives.

Run (any host — provisions a virtual 4-device CPU mesh if needed):
    python data_model_pipeline_mlp.py --split middle
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..', '..'))
from hetu_tpu.utils import ensure_devices


def fc(x, shape, name, with_relu=True, ctx=None):
    import hetu_tpu as ht
    weight = ht.init.random_normal(
        shape=shape, stddev=0.04, name=name + '_weight', ctx=ctx)
    bias = ht.init.random_normal(
        shape=shape[-1:], stddev=0.04, name=name + '_bias', ctx=ctx)
    x = ht.matmul_op(x, weight)
    x = x + ht.broadcastto_op(bias, x)
    if with_relu:
        x = ht.relu_op(x)
    return x


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--steps', type=int, default=8)
    parser.add_argument('--warmup', type=int, default=2)
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--learning-rate', type=float, default=0.00001)
    parser.add_argument('--split', type=str, default='left',
                        choices=('left', 'middle', 'right'))
    args = parser.parse_args()

    ensure_devices(4)
    import hetu_tpu as ht

    datasets = ht.data.mnist()
    train_set_x, train_set_y = datasets[0]

    # model parallel: 2 workers (dp) x 2-way tensor parallel (tp)
    x = ht.Variable(name="dataloader_x", trainable=False)
    activation = fc(x, (784, 256), 'mlp_fc1', with_relu=True)
    weight = ht.init.random_normal(shape=(256, 512), stddev=0.04,
                                   name='mlp_fc2_weight')
    with ht.context([(ht.tpu(0), ht.tpu(1)), (ht.tpu(2), ht.tpu(3))]):
        if args.split == 'left':
            activation = ht.dispatch(activation, (2, 1))
            weight = ht.dispatch(weight, (1, 1), duplicate=2)
        elif args.split == 'right':
            activation = ht.dispatch(activation, (1, 1), duplicate=2)
            weight = ht.dispatch(weight, (1, 2))
        else:
            activation = ht.dispatch(activation, (1, 2))
            weight = ht.dispatch(weight, (2, 1))
        activation = ht.matmul_op(activation, weight)
        activation = ht.dispatch(activation, (1, 1))

    activation = ht.relu_op(activation)
    y_pred = fc(activation, (512, 10), 'mlp_fc3', with_relu=False)
    y_ = ht.Variable(name="dataloader_y", trainable=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y_pred, y_), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=args.learning_rate)
    train_op = opt.minimize(loss)

    executor = ht.Executor([loss, train_op])
    mesh = executor.config.mesh
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    n = train_set_x.shape[0]
    start = None
    for step in range(args.steps):
        if step == args.warmup:
            start = time.time()
        lo = (step * args.batch_size) % max(1, n - args.batch_size)
        loss_val, _ = executor.run(feed_dict={
            x: train_set_x[lo:lo + args.batch_size],
            y_: train_set_y[lo:lo + args.batch_size]},
            convert_to_numpy_ret_vals=True)
        print('step:', step, 'loss:', float(np.mean(loss_val)))
    if start is not None:
        print("time elapsed for {} steps: {}s".format(
            args.steps - args.warmup, round(time.time() - start, 3)))


if __name__ == "__main__":
    main()
