"""GPipe pipeline-parallel MLP on the graph API.

Port of the reference's ``examples/runner/parallel/gpipe.py``: one MLP layer
per pipeline stage (``with ht.context(...)`` per stage),
``Executor([loss, train_op], gpipe=True)``, and ``run()`` on a list of
microbatch feed_dicts. The reference runs one MPI rank per GPU with NCCL
send/recv between stages (SubExecutor4Gpipe, gpu_ops/executor.py:435-767);
here each stage compiles to jitted XLA programs on its own device and JAX's
async dispatch overlaps the microbatch fill/drain.

Run (any host — provisions a virtual 4-device CPU mesh if needed):
    python gpipe.py --stages 4 --micro-batches-num 8
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..', '..'))
from hetu_tpu.utils import ensure_devices


def fc(x, shape, name, with_relu=True):
    import hetu_tpu as ht
    weight = ht.init.random_normal(shape, stddev=0.04, name=name + '_weight')
    bias = ht.init.random_normal(shape[-1:], stddev=0.04, name=name + '_bias')
    x = ht.matmul_op(x, weight)
    x = x + ht.broadcastto_op(bias, x)
    if with_relu:
        x = ht.relu_op(x)
    return x


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--epochs', type=int, default=4)
    parser.add_argument('--warmup', type=int, default=1)
    parser.add_argument('--stages', type=int, default=4)
    parser.add_argument('--batch-size', type=int, default=256)
    parser.add_argument('--micro-batches-num', type=int, default=8)
    parser.add_argument('--learning-rate', type=float, default=0.1)
    args = parser.parse_args()

    ensure_devices(args.stages)
    import hetu_tpu as ht

    datasets = ht.data.mnist()
    train_set_x, train_set_y = datasets[0]

    # pipeline parallel: one fc layer per stage
    with ht.context(ht.tpu(0)):
        x = ht.Variable(name="dataloader_x", trainable=False)
        activation = fc(x, (784, 512), 'mlp_fc1', with_relu=True)

    for i in range(1, args.stages - 1):
        with ht.context(ht.tpu(i)):
            activation = fc(activation, (512, 512), 'mlp_fc%d' % (i + 1),
                            with_relu=True)

    with ht.context(ht.tpu(args.stages - 1)):
        y_pred = fc(activation, (512, 10), 'mlp_fc_out', with_relu=False)
        y_ = ht.Variable(name="dataloader_y", trainable=False)
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y_pred, y_), [0])
        opt = ht.optim.SGDOptimizer(learning_rate=args.learning_rate)
        train_op = opt.minimize(loss)
        executor = ht.Executor([loss, train_op], gpipe=True)

    M = args.micro_batches_num
    steps = train_set_x.shape[0] // (M * args.batch_size)
    start_time = None
    for epoch in range(args.epochs):
        loss_vals = []
        if epoch == args.warmup:
            start_time = time.time()
        for step in range(steps):
            feed_dicts_list = []
            for i in range(M):
                lo = (step * M + i) * args.batch_size
                hi = lo + args.batch_size
                feed_dicts_list.append({x: train_set_x[lo:hi],
                                        y_: train_set_y[lo:hi]})
            ret = executor.run(feed_dict=feed_dicts_list,
                               convert_to_numpy_ret_vals=True)
            loss_vals.extend(float(np.mean(v)) for v in ret[0])
        print('epoch: {}, mean loss: {:.4f}, min loss: {:.4f}, max loss: '
              '{:.4f}'.format(epoch, np.mean(loss_vals), np.min(loss_vals),
                              np.max(loss_vals)))
    if start_time is not None:
        print("time elapsed for {} epochs: {}s".format(
            args.epochs - args.warmup, round(time.time() - start_time, 3)))


if __name__ == "__main__":
    main()
