#!/usr/bin/env python
"""Sacrificial-window bisect for the bf16 bs>=256 backend wedge.

In two separate hardware sessions (2026-07-30/31) the resnet18 bf16
bs256/bs512 bench cells hung AND left the tunneled TPU unresponsive for
hours, while bf16 bs128 and f32 bs128/256 ran green around them. This
tool spends a DELIBERATELY sacrificial window reproducing and bisecting
that wedge so the bench can either re-enable the cells or delete them
with a post-mortem (round-5 directive #1).

Protocol — escalating risk, one experiment per killed process group, a
probe after every step, stop-and-wait on any wedge:

  1. probe                       - is the backend up at all
  2. resnet bf16 bs192           - the midpoint: does the wedge start
                                   between 128 and 256?
  3. resnet bf16 bs256 no-donate - HETU_NO_DONATE=1: donation changes
                                   XLA buffer assignment (suspect #1)
  4. twin bf16 bs512             - raw-JAX resnet twin: same shapes, no
                                   define-then-run executor -> splits
                                   framework-trace vs XLA/backend fault
  5. resnet bf16 bs256 COLD      - the reproducer with a FRESH compile
                                   cache: a wedge here is compile-or-
                                   execute (ambiguous alone)
  6. resnet bf16 bs256 WARM      - same cell again against the persistent
                                   cache 5 populated: green-after-cold-
                                   wedge => the wedge is COMPILE; a wedge
                                   with a warm cache => EXECUTE
  7. resnet bf16 bs512 WARM-able - the second risky cell, same split

Every result lands in WEDGE_BISECT.json as it happens (ledger-style: a
tunnel death mid-bisect loses nothing). Run on the bench host when the
tunnel is healthy:  python tools/wedge_bisect.py [--quick]

The matching "done" criterion: either the risky cells run green here
(re-enable them in bench.py), or this file's JSON names the guilty stage
(compile vs execute, donation, framework vs raw-XLA) and the cells get
deleted with docs/WEDGE_POSTMORTEM.md citing it.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

REPORT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "WEDGE_BISECT.json")


def record(report, key, result):
    report[key] = result
    tmp = REPORT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, REPORT)
    status = "WEDGE" if result.get("hang") else (
        "error" if "error" in result else "green")
    print(f"[{time.strftime('%H:%M:%S')}] {key}: {status} "
          f"{result.get('error', '')[:120]}", flush=True)


def wait_for_backend(report, budget_s=3600):
    t0 = time.time()
    while time.time() - t0 < budget_s:
        time.sleep(240)
        probe = bench._section_subprocess("probe", 180)
        if "error" not in probe:
            record(report, f"recovery_probe_{int(time.time() - t0)}s",
                   {"ok": True})
            return True
    return False


def experiment(report, key, name, timeout, env=None, budget_s=3600):
    """One killed-process-group experiment + post-probe; on a wedge,
    wait out the recovery before letting the next experiment run."""
    saved = {}
    for k, v in (env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        out = bench._section_subprocess(name, timeout)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    record(report, key, out)
    probe = bench._section_subprocess("probe", 180)
    record(report, key + "_postprobe", probe)
    if probe.get("hang"):
        print(f"# backend wedged by {key}; waiting for recovery "
              f"(budget {budget_s}s)", flush=True)
        if not wait_for_backend(report, budget_s):
            record(report, "aborted", {"error": f"backend never recovered "
                                                f"after {key}"})
            return False
    return True


def main():
    quick = "--quick" in sys.argv
    report = {"started": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "host_note": "sacrificial window; see tools/wedge_bisect.py"}
    if os.path.exists(REPORT):
        with open(REPORT) as f:
            report.update(json.load(f))

    probe = bench._section_subprocess("probe", 180)
    record(report, "initial_probe", probe)
    if "error" in probe:
        print("backend down at start; nothing to bisect", flush=True)
        return 1

    fresh_cache = tempfile.mkdtemp(prefix="hetu_wedge_cache_")
    try:
        steps = [
            ("bf16_bs192", "resnet:192:bf16", 420, None),
            ("bf16_bs256_no_donate", "resnet:256:bf16", 600,
             {"HETU_NO_DONATE": "1"}),
            ("twin_bf16_bs512", "twin", 600, None),
            ("bf16_bs256_cold_cache", "resnet:256:bf16", 900,
             {"JAX_COMPILATION_CACHE_DIR": fresh_cache}),
            ("bf16_bs256_warm_cache", "resnet:256:bf16", 600,
             {"JAX_COMPILATION_CACHE_DIR": fresh_cache}),
        ]
        if not quick:
            steps.append(("bf16_bs512_warm_cache", "resnet:512:bf16", 900,
                          {"JAX_COMPILATION_CACHE_DIR": fresh_cache}))
        for key, name, timeout, env in steps:
            if key in report and "error" not in report[key]:
                print(f"skip {key}: already green in {REPORT}", flush=True)
                continue
            if not experiment(report, key, name, timeout, env):
                return 2
    finally:
        shutil.rmtree(fresh_cache, ignore_errors=True)

    # verdict synthesis; ``green`` is the STRUCTURED field bench.py keys
    # its quarantine lift on (the text is for humans)
    cold = report.get("bf16_bs256_cold_cache", {})
    warm = report.get("bf16_bs256_warm_cache", {})
    green = False
    if cold.get("hang") and not warm.get("hang") and "error" not in warm:
        verdict = ("COMPILE-side wedge: cold-cache run hung, warm-cache "
                   "run green — the server-side compile is the fault")
    elif warm.get("hang"):
        verdict = ("EXECUTE-side wedge: the cell hangs even with a warm "
                   "compile cache")
    elif "error" not in cold and "error" not in warm:
        verdict = ("no wedge reproduced this window — re-enable the "
                   "risky cells and watch the next driver run")
        green = True
    else:
        verdict = "inconclusive — see per-experiment entries"
    record(report, "verdict", {"text": verdict, "green": green})
    print(f"\nVERDICT: {verdict}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
