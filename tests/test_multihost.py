"""Multi-host distributed backend: a REAL 2-process world over the
coordination service (Gloo collectives on CPU), data-parallel training with
per-host batch feeding, vs a single-process full-batch oracle.

This is the reference's multi-node story (MPI bootstrap + NCCL world,
``communicator/mpi_nccl_comm.py:54-152``, launched by ``runner.py:204``)
rebuilt on jax.distributed — tested the way the reference tests clusters:
spawn actual local processes (SURVEY.md §4).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_world(nproc=2, timeout=180, ckpt_dir=None, script="mh_worker.py",
               extra_env=None, per_worker_env=None):
    """Launch ``nproc`` jax.distributed worker processes and collect one
    JSON result line from each. Shared by the plain multihost test and the
    hybrid (PS + Gloo) test — worker scripts take (pid, nproc, coord_port,
    [extra argv]) and print their result as a JSON object line."""
    from hetu_tpu.runner import _get_available_port
    port = _get_available_port("127.0.0.1")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # worker configures its own platform
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    extra = [str(ckpt_dir)] if ckpt_dir else []
    procs = []
    try:
        for pid in range(nproc):
            wenv = dict(env)
            wenv.update((per_worker_env or (lambda _: {}))(pid))
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "tests", script),
                 str(pid), str(nproc), str(port)] + extra,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=wenv,
                text=True))
    except Exception:
        for q in procs:   # a failed launch must not leak live peers
            q.kill()
        raise
    # collect every worker's output even when one crashes or hangs — the
    # FIRST crash is the diagnosis, and a surviving peer blocks in
    # jax.distributed.initialize far longer than our timeout
    outs = [None] * nproc
    deadline = timeout
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=deadline)
            outs[i] = (p.returncode, out, err)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            outs[i] = ("timeout", out, err)
            deadline = 10   # peers are dead; just drain them
    results = []
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (
            f"worker {i} failed rc={rc}\n" + "\n".join(
                f"--- worker {j} rc={o[0]}\nstdout:{o[1]}\nstderr:{o[2]}"
                for j, o in enumerate(outs) if o is not None))
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        results.append(json.loads(line))
    return results


def _full_batch_gd_oracle(steps=20, dout=2):
    """Replay the mh_worker*.py training loop on the full batch in numpy
    (X ~ RandomState(0), W_true from the same stream, lr 0.1, W0 = 0).
    Returns (losses, final W)."""
    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype(np.float32)
    W_true = rng.randn(4, dout).astype(np.float32)
    Y = X @ W_true
    W = np.zeros((4, dout), np.float32)
    losses = []
    for _ in range(steps):
        err = X @ W - Y
        losses.append(float(np.mean(err ** 2)))
        W -= 0.1 * (2.0 / err.size) * (X.T @ err)
    return losses, W


def test_four_process_dp_training_matches_full_batch_oracle():
    """dp=4 over four REAL processes (8 global devices): per-host batch
    slicing and the world build must survive beyond nproc=2 — rank
    arithmetic that two processes cannot expose."""
    results = _run_world(nproc=4, timeout=300)
    oracle, _ = _full_batch_gd_oracle(steps=20)
    for r in results:
        assert sorted(r["gathered_pids"]) == [0, 1, 2, 3]
        assert r["final_loss"] == pytest.approx(oracle[-1], rel=1e-3)
        assert r["first_loss"] == pytest.approx(oracle[0], rel=1e-4)
        assert r["w_sum"] == pytest.approx(results[0]["w_sum"], rel=1e-5)


def test_four_process_dp2_tp2_spans_processes():
    """(dp=2, tp=2) mesh over four 1-device processes: the tp groups span
    process boundaries, so weight-sharded matmul grads ride cross-process
    collectives; must match the numpy GD oracle."""
    results = _run_world(nproc=4, timeout=300, script="mh_worker_dptp.py")
    losses, W = _full_batch_gd_oracle(steps=10, dout=8)
    for r in results:
        assert sorted(r["gathered_pids"]) == [0, 1, 2, 3]
        assert r["first_loss"] == pytest.approx(losses[0], rel=1e-4)
        assert r["final_loss"] == pytest.approx(losses[-1], rel=1e-3)
        assert r["w_sum"] == pytest.approx(float(np.sum(W)), rel=1e-3)


def test_two_process_dp_training_matches_full_batch_oracle(tmp_path):
    ckpt = tmp_path / "mh_ckpt"
    results = _run_world(ckpt_dir=ckpt)
    r0 = next(r for r in results if r["pid"] == 0)
    r1 = next(r for r in results if r["pid"] == 1)

    # both processes observed the same (global) loss and ended with the same
    # replicated weights
    assert r0["final_loss"] == pytest.approx(r1["final_loss"], rel=1e-5)
    assert r0["w_sum"] == pytest.approx(r1["w_sum"], rel=1e-5)
    # data-parallel mean over the dp axis == full-batch GD
    losses, _ = _full_batch_gd_oracle(steps=20)
    assert r0["first_loss"] == pytest.approx(losses[0], rel=1e-4)
    assert r0["final_loss"] == pytest.approx(losses[-1], rel=1e-3)
    assert r0["final_loss"] < r0["first_loss"] * 0.05  # actually trained

    # host-level collectives: allgather saw both processes, chief broadcast
    # won (value is chief's 1234, not 1235)
    assert sorted(r0["gathered_pids"]) == [0, 1]
    assert r0["chief_seed"] == 1234 and r1["chief_seed"] == 1234

    # the distributed checkpoint the two processes wrote (each only its own
    # shards) restores whole in THIS single process, values intact
    from hetu_tpu import checkpoint
    state = checkpoint.restore(str(ckpt))
    assert float(np.sum(state["W"])) == pytest.approx(r0["w_sum"], rel=1e-5)
    # exact shard layout: pid 0's rows land at [0:4], pid 1's at [4:8]
    assert state["xsh"].shape == (8, 2)
    np.testing.assert_array_equal(state["xsh"][:4],
                                  np.full((4, 2), 1.0, np.float32))
    np.testing.assert_array_equal(state["xsh"][4:],
                                  np.full((4, 2), 2.0, np.float32))
