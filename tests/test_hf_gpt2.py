"""HuggingFace GPT-2 numerical parity (models/hf_gpt2.py).

Random-weight ``transformers`` GPT-2 (no network) -> imported flagship
params -> logits pinned against the torch forward; then the same imported
checkpoint rides the flagship machinery: the one-scan KV-cache decode
(incremental logits == torch logits) and a dp/tp mesh forward on the
virtual 8-device CPU mesh (== torch logits). The reference has no
checkpoint interop (its nlp example trains from scratch only).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from hetu_tpu.models import generate as gen
from hetu_tpu.models import transformer as tfm
from hetu_tpu.models.hf_gpt2 import (config_from_hf, export_to_hf,
                                     params_from_hf)


@pytest.fixture(scope="module")
def gpt2_pair():
    torch.manual_seed(0)
    # vocab divisible by tp=2 so the mesh test can shard the head/embed
    model = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=48, n_layer=3,
        n_head=4)).eval()
    params, cfg = params_from_hf(model)
    import dataclasses
    cfg = dataclasses.replace(cfg, remat=False, attn_impl="dot",
                              fused_lm_ce=False)
    return model, params, cfg


def hf_logits(model, ids):
    with torch.no_grad():
        return model(input_ids=torch.tensor(ids)).logits.numpy()


def test_logits_match_hf(gpt2_pair):
    model, params, cfg = gpt2_pair
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (3, 24))
    ours, _ = tfm.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_kv_cache_decode_matches_hf(gpt2_pair):
    """The imported checkpoint through the one-scan KV-cache decode:
    teacher-forced incremental logits equal the torch full forward."""
    model, params, cfg = gpt2_pair
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, (2, 16))
    fn = gen.make_generate_fn(cfg, max_len=16)
    toks, inc_logits = fn(params, jnp.asarray(ids, jnp.int32),
                          jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), ids)
    np.testing.assert_allclose(np.asarray(inc_logits),
                               hf_logits(model, ids), atol=3e-4, rtol=3e-4)


def test_mesh_forward_matches_hf(gpt2_pair):
    """The imported checkpoint sharded dp2/tp2 on the virtual mesh."""
    model, params, cfg = gpt2_pair
    from hetu_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    sharded = tfm.shard_params(params, cfg, mesh)
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, (4, 24))
    ours, _ = jax.jit(
        lambda p, t: tfm.forward(p, t, cfg, mesh))(
            sharded, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_greedy_generation_matches_hf_generate(gpt2_pair):
    """Whole-loop equality: our one-scan KV-cache greedy decode produces
    the same tokens as transformers' generate() (explicit all-ones
    attention mask — HF would otherwise mask prompt tokens that happen to
    equal pad_token_id)."""
    model, params, cfg = gpt2_pair
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, (3, 8)).astype(np.int32)
    ours = gen.generate(params, cfg, prompt, max_len=18)
    with torch.no_grad():
        ref = model.generate(
            torch.tensor(prompt, dtype=torch.long),
            attention_mask=torch.ones((3, 8), dtype=torch.long),
            max_new_tokens=10, do_sample=False, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(ours), ref.numpy())


def test_import_refuses_mismatched_config(gpt2_pair):
    model, _, _ = gpt2_pair
    truncated = config_from_hf(model.config, n_layers=2)
    with pytest.raises(ValueError, match="n_layers"):
        params_from_hf(model, truncated)


def test_import_refuses_attention_variants():
    cfg = transformers.GPT2Config(vocab_size=96, n_positions=32, n_embd=48,
                                  n_layer=1, n_head=4,
                                  scale_attn_by_inverse_layer_idx=True)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    with pytest.raises(NotImplementedError, match="inverse_layer_idx"):
        params_from_hf(model)


def test_imported_head_is_tied(gpt2_pair):
    """No separate head param: fine-tuning updates one embedding, exactly
    HF's tied-weight dynamics, and the checkpoint stays exportable."""
    _, params, cfg = gpt2_pair
    assert cfg.tied_head and "head" not in params


def test_train_then_export_roundtrip(gpt2_pair):
    """Train a step on imported GPT-2 weights, export into a fresh torch
    GPT2LMHeadModel (tied lm_head follows wte), logits must match ours."""
    model, params, cfg = gpt2_pair
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)), jnp.int32)
    step = tfm.make_train_step(cfg, lr=1e-3)
    trained = jax.tree.map(jnp.array, params)
    _, trained, _ = step(trained, tfm.init_opt_state(trained),
                         toks[:, :-1], toks[:, 1:])

    fresh = transformers.GPT2LMHeadModel(model.config).eval()
    export_to_hf(trained, cfg, fresh)
    ids = rng.integers(0, cfg.vocab_size, (3, 20))
    ours, _ = tfm.forward(trained, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(fresh, ids),
                               atol=3e-4, rtol=3e-4)


def test_export_refuses_layer_mismatch(gpt2_pair):
    # exporting 3-layer params into a 2-layer model must raise, not
    # silently deploy a truncated network
    model, params, cfg = gpt2_pair
    small = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=48, n_layer=2,
        n_head=4)).eval()
    with pytest.raises(ValueError, match="no slot"):
        export_to_hf(params, cfg, small)


def test_export_refuses_untied_head(gpt2_pair):
    import dataclasses
    model, params, cfg = gpt2_pair
    untied = dataclasses.replace(cfg, tied_head=False)
    with pytest.raises(ValueError, match="tied_head"):
        export_to_hf(params, untied, model)


def test_imported_gpt2_trains_a_step(gpt2_pair):
    model, params, cfg = gpt2_pair
    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)), jnp.int32)
    step = tfm.make_train_step(cfg, lr=1e-3)
    p2 = jax.tree.map(jnp.array, params)
    opt = tfm.init_opt_state(p2)
    l1, p2, opt = step(p2, opt, toks[:, :-1], toks[:, 1:])
    l2, p2, opt = step(p2, opt, toks[:, :-1], toks[:, 1:])
    assert float(l2) < float(l1)
