"""hetusave — coordinated job-wide consistent checkpoints + exactly-once
whole-job crash recovery (docs/FAULT_TOLERANCE.md "Coordinated job
snapshots").

The cluster tests are the acceptance proofs: the ``kSnapshotNow`` PSF
publishes a durable epoch-stamped snapshot whose ``LATEST_s<rank>``
pointer flip is atomic (a server killed BETWEEN the directory publish
and the pointer write must leave restore on the previous complete
snapshot — the satellite regression), and the CLI soak runs a whole-job
kill inside a coordinated snapshot phase, restores from the newest
committed manifest only, and proves the restored run loss-bit-identical
to a fault-free twin under exactly-once update accounting. The unit
tests pin the one-atomic-commit manifest contract (torn epochs of every
shape are never restore-eligible), the checkpointer's retention policy,
the ``job_kill@S[:PHASE]`` fault grammar + arming, and the dataloader's
exact-sample-sequence resume across an epoch wrap with shuffle on.
"""
import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# manifest: ONE atomic commit per epoch; newest COMMITTED wins
# ---------------------------------------------------------------------------

def test_commit_manifest_atomic_no_tmp_left(tmp_path):
    from hetu_tpu import recovery
    jobdir = str(tmp_path)
    m = recovery._fake_epoch(jobdir, 1, step=5)
    path = recovery.manifest_path(jobdir, 1)
    assert os.path.isfile(path)
    # the commit is tmp+rename: no .tmp survives a successful commit
    assert not os.path.exists(path + ".tmp")
    got, edir = recovery.latest_committed_manifest(jobdir)
    assert got["epoch"] == 1 and got["step"] == m["step"]
    assert edir == os.path.join(jobdir, recovery.epoch_dir_name(1))


def test_torn_epochs_of_every_shape_never_restore_eligible(tmp_path):
    """A manifest that exists but references missing pieces — or never
    finished its own write — is torn, and restore must fall back to the
    newest epoch whose EVERY piece is on disk."""
    from hetu_tpu import recovery
    jobdir = str(tmp_path)
    recovery._fake_epoch(jobdir, 1, step=4)                    # committed
    recovery._fake_epoch(jobdir, 2, step=8, commit=False,
                         torn="tmp_manifest")                  # died mid-commit
    recovery._fake_epoch(jobdir, 3, step=12, torn="manifest.bin")
    recovery._fake_epoch(jobdir, 4, step=16, torn="worker")
    recovery._fake_epoch(jobdir, 5, step=20, torn="pointer")
    got, _ = recovery.latest_committed_manifest(jobdir)
    assert got["epoch"] == 1, "every torn shape must be skipped"
    rows = {r["epoch"]: r["status"] for r in recovery.list_epochs(jobdir)}
    assert rows[1] == "committed"
    for e in (2, 3, 4, 5):
        assert rows[e].startswith("torn"), (e, rows[e])
    # a later healthy commit immediately takes over
    recovery._fake_epoch(jobdir, 6, step=24)
    got, _ = recovery.latest_committed_manifest(jobdir)
    assert got["epoch"] == 6
    # new epochs never collide with torn leftovers
    assert recovery.next_epoch(jobdir) == 7


def test_checkpointer_prunes_committed_keeps_fresh_torn(tmp_path):
    """Retention: newest ``keep`` committed epochs survive; older ones
    (committed or torn) are swept; a torn epoch NEWER than the newest
    committed one is crash evidence and must be left for post-mortems."""
    from hetu_tpu import recovery
    jobdir = str(tmp_path)
    for e in (1, 2, 3):
        recovery._fake_epoch(jobdir, e, step=4 * e)
    recovery._fake_epoch(jobdir, 4, step=16, torn="pointer")   # fresh torn
    ck = recovery.JobCheckpointer(jobdir, keep=2)
    ck._prune()
    left = {r["epoch"] for r in recovery.list_epochs(jobdir)}
    assert left == {2, 3, 4}, left
    got, _ = recovery.latest_committed_manifest(jobdir)
    assert got["epoch"] == 3


# ---------------------------------------------------------------------------
# job_kill fault kind: grammar + phase arming
# ---------------------------------------------------------------------------

def test_job_kill_spec_grammar():
    from hetu_tpu.recovery import PHASES
    from hetu_tpu.resilience import FaultInjector
    fi = FaultInjector("job_kill@3:server_write,job_kill@7")
    assert fi.entries[0]["kind"] == "job_kill"
    assert fi.entries[0]["step"] == 3
    assert fi.entries[0]["arg"] == "server_write"
    assert fi.entries[1]["arg"] is None
    for phase in PHASES:
        FaultInjector(f"job_kill@1:{phase}")  # every real phase parses
    with pytest.raises(ValueError, match="job_kill phase"):
        FaultInjector("job_kill@2:mid_flight")
    with pytest.raises(ValueError, match="fault-kind catalogue"):
        FaultInjector("job_nuke@2")


def test_job_kill_phase_arming_and_single_consumption(monkeypatch):
    from hetu_tpu import recovery
    from hetu_tpu.resilience import FaultInjector
    fired = []
    monkeypatch.setattr(recovery, "kill_whole_job",
                        lambda step=None, phase=None:
                        fired.append((step, phase)))
    fi = FaultInjector("job_kill@3:pre_commit,job_kill@5")
    fi.inject_host(2)
    assert recovery.armed_kill_phase() is None
    fi.inject_host(3)  # phase-targeted: arms the NEXT snapshot's window
    assert recovery.armed_kill_phase() == "pre_commit"
    assert fired == []
    recovery._maybe_kill("server_write")     # wrong phase: no fire
    assert fired == [] and recovery.armed_kill_phase() == "pre_commit"
    recovery._maybe_kill("pre_commit")       # fires, consumed once
    assert fired == [(None, "pre_commit")]
    recovery._maybe_kill("pre_commit")
    assert fired == [(None, "pre_commit")]
    fi.inject_host(5)                        # bare job_kill: dies NOW
    assert fired[-1] == (5, None)


def test_kill_whole_job_gated_on_test_mode(monkeypatch):
    from hetu_tpu import recovery
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    with pytest.raises(RuntimeError, match="HETU_TEST_MODE"):
        recovery.kill_whole_job(0)


# ---------------------------------------------------------------------------
# dataloader: exact sample sequence across an epoch wrap with shuffle
# ---------------------------------------------------------------------------

def test_dataloader_resume_exact_sequence_across_epoch_wrap():
    """Snapshot mid-epoch-1, then consume through the epoch-2 reshuffle:
    the restored twin must replay the IDENTICAL batch sequence — cursor,
    permutation, and the RNG state that generates the NEXT permutation
    all have to survive the round trip."""
    import hetu_tpu as ht
    data = np.arange(40, dtype=np.float32).reshape(20, 2)   # 5 batches/epoch

    def mk():
        return ht.Dataloader(data, 4, "train", shuffle=True, seed=3)

    a = mk()
    for _ in range(3):          # park mid-epoch-1
        a.get_arr()
    sd = a.state_dict()
    # reference: 12 more batches crosses the epoch-1→2 wrap (reshuffle)
    # and the 2→3 wrap — two RNG-consuming events past the snapshot
    ref = [np.array(a.get_arr(), copy=True) for _ in range(12)]
    b = mk()
    b.load_state_dict(sd)
    got = [np.array(b.get_arr(), copy=True) for _ in range(12)]
    for i, (r, g) in enumerate(zip(ref, got)):
        np.testing.assert_array_equal(r, g, err_msg=f"batch {i} diverged")
    # the wrap actually reshuffled (epoch 2 is a different permutation
    # than the tail of epoch 1 re-read in order) — otherwise this test
    # would pass with a loader that never shuffles again after restore
    epoch2 = np.concatenate(ref[2:7])
    assert not np.array_equal(np.sort(epoch2.ravel()),
                              epoch2.ravel()), "epoch 2 never shuffled"
    # …while still covering every sample exactly once per epoch
    np.testing.assert_array_equal(np.sort(epoch2, axis=0), data)


# ---------------------------------------------------------------------------
# kSnapshotNow PSF: durable epoch-stamped snapshots on a live server
# ---------------------------------------------------------------------------

def test_snapshot_now_psf_publishes_durable_versions(tmp_path, monkeypatch):
    from hetu_tpu.ps.local_cluster import local_cluster
    from hetu_tpu import ps as ps_pkg
    snapdir = str(tmp_path / "snap")
    monkeypatch.setenv("DMLC_PS_SNAPSHOT_DIR", snapdir)
    with local_cluster(n_servers=1, n_workers=1):
        ps_pkg.worker_init()
        try:
            comm = ps_pkg.get_worker_communicate()
            comm.InitTensor(0, sparse=False, length=32, width=1,
                            init_type="constant", init_a=1.5)
            comm.Push(0, np.ones(32, np.float32))
            comm.Wait(0)
            r1 = comm.SnapshotNow(0, epoch=7)
            # quiesced (Wait drained the push): the snapshot covers the
            # live counter exactly — hetusave's consistency proof
            assert r1["version"] == 1
            assert r1["epoch"] == 7
            assert r1["counter"] == r1["updates"] == 1, r1
            name = f"snap_s0_v{r1['version']}"
            d = os.path.join(snapdir, name)
            assert os.path.isdir(d), "returned version must be durable"
            with open(os.path.join(d, "manifest.bin"), "rb") as f:
                (magic,) = struct.unpack("<q", f.read(8))
                head = struct.unpack("<4Q", f.read(32))
            assert magic == -7001 and head[0] == 1 and head[1] == 1, (
                magic, head)
            with open(os.path.join(snapdir, "LATEST_s0")) as f:
                assert f.read().strip() == name
            comm.Push(0, np.ones(32, np.float32))
            comm.Wait(0)
            r2 = comm.SnapshotNow(0, epoch=8)
            assert r2["version"] == 2 and r2["counter"] == 2, r2
            with open(os.path.join(snapdir, "LATEST_s0")) as f:
                assert f.read().strip() == f"snap_s0_v{r2['version']}"
        finally:
            ps_pkg.worker_finish()


def test_snapshot_now_concurrent_with_periodic_snapshots(tmp_path,
                                                         monkeypatch):
    """Regression (ABBA deadlock): the kSnapshotNow dispatch thread used
    to hold the requester's dedup-slot mutex while waiting on
    snap_take_mu_, while the periodic snapshot thread held snap_take_mu_
    and locked that same slot during its ledger walk. With the periodic
    snapshotter spinning at a 1ms interval and every push dirtying state,
    this loop deadlocked within a few iterations; now the dispatch path
    drops the slot across handle() and every RPC snapshot completes."""
    from hetu_tpu.ps.local_cluster import local_cluster
    from hetu_tpu import ps as ps_pkg
    snapdir = str(tmp_path / "snap")
    monkeypatch.setenv("DMLC_PS_SNAPSHOT_DIR", snapdir)
    monkeypatch.setenv("DMLC_PS_SNAPSHOT_MS", "1")
    with local_cluster(n_servers=1, n_workers=1):
        ps_pkg.worker_init()
        try:
            comm = ps_pkg.get_worker_communicate()
            comm.InitTensor(0, sparse=False, length=8, width=1,
                            init_type="constant", init_a=0.0)
            last = None
            for i in range(30):
                comm.Push(0, np.ones(8, np.float32))
                comm.Wait(0)
                last = comm.SnapshotNow(0, epoch=i)
            assert last["updates"] == 30
            # quiesced between pushes: the RPC snapshot covers the live
            # counter exactly, periodic-thread races notwithstanding
            assert last["counter"] == 30
        finally:
            ps_pkg.worker_finish()


def test_kill_between_publish_and_pointer_restores_previous(tmp_path,
                                                            monkeypatch):
    """Satellite regression: the server dies AFTER publishing the v2
    snapshot directory but BEFORE flipping LATEST_s0. The pointer must
    still name v1, and a fresh server restoring from the directory must
    land on v1's state and counter — never on the unpointed v2."""
    from hetu_tpu.ps.local_cluster import get_live_cluster, local_cluster
    from hetu_tpu import ps as ps_pkg
    snapdir = str(tmp_path / "snap")
    monkeypatch.setenv("DMLC_PS_SNAPSHOT_DIR", snapdir)
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_PS_TEST_KILL_BEFORE_POINTER", "2")

    with local_cluster(n_servers=1, n_workers=1):
        ps_pkg.worker_init()
        try:
            comm = ps_pkg.get_worker_communicate()
            comm.InitTensor(0, sparse=False, length=16, width=1,
                            init_type="constant", init_a=0.0)
            comm.Push(0, np.full(16, 1.0, np.float32))
            comm.Wait(0)
            r1 = comm.SnapshotNow(0, epoch=1)
            assert r1["version"] == 1 and r1["counter"] == 1
            val_v1 = comm.Pull(0, np.empty(16, np.float32)).copy()
            comm.Wait(0)
            comm.Push(0, np.full(16, 1.0, np.float32))
            comm.Wait(0)
            val_later = comm.Pull(0, np.empty(16, np.float32)).copy()
            comm.Wait(0)
            assert not np.array_equal(val_v1, val_later)
            # v2: dir publishes, then std::_Exit(137) before the pointer
            with pytest.raises(Exception):
                comm.SnapshotNow(0, epoch=2)
            assert os.path.isdir(os.path.join(snapdir, "snap_s0_v2")), \
                "v2 dir must have been published before the death"
            with open(os.path.join(snapdir, "LATEST_s0")) as f:
                assert f.read().strip() == "snap_s0_v1", \
                    "pointer must still name the last COMPLETE flip"
        finally:
            # the server is gone — put the rest of the cluster out of its
            # misery so finalize fails fast instead of waiting on a barrier
            for p in get_live_cluster().get("procs", []):
                try:
                    p.kill()
                except Exception:  # noqa: BLE001
                    pass
            try:
                ps_pkg.worker_finish()
            except Exception:  # noqa: BLE001 — dead cluster
                pass

    # restore leg: a fresh incarnation follows LATEST_s0 → v1
    monkeypatch.delenv("HETU_PS_TEST_KILL_BEFORE_POINTER")
    monkeypatch.delenv("DMLC_PS_SNAPSHOT_DIR", raising=False)
    monkeypatch.setenv("DMLC_PS_RESTORE_DIR", snapdir)
    with local_cluster(n_servers=1, n_workers=1):
        ps_pkg.worker_init()
        try:
            comm = ps_pkg.get_worker_communicate()
            # idempotent re-init: a restored (sized) param is untouched
            comm.InitTensor(0, sparse=False, length=16, width=1,
                            init_type="constant", init_a=0.0)
            stats = comm.ServerStats(0)
            assert stats["restored_updates"] == 1, stats
            got = comm.Pull(0, np.empty(16, np.float32)).copy()
            comm.Wait(0)
            np.testing.assert_array_equal(got, val_v1)
            assert not np.array_equal(got, val_later)
        finally:
            ps_pkg.worker_finish()


# ---------------------------------------------------------------------------
# coordinator guards: multi-worker refusal + grace-budget barrier timeout
# ---------------------------------------------------------------------------

def test_take_job_snapshot_refuses_multi_worker(tmp_path, monkeypatch):
    """Regression: the coordinator captures only its own rank's worker
    state, so a multi-worker job must be refused BEFORE the barrier is
    even proposed — a committed epoch missing ranks would pass every
    completeness check yet be unrestorable for every other rank."""
    from hetu_tpu import elastic, recovery
    from hetu_tpu import ps as ps_pkg

    class Rt:
        def drain(self):
            pass

    class Ex:
        ps_runtime = Rt()
        state = {"step": 3}

    jobdir = str(tmp_path / "job")
    monkeypatch.setenv("DMLC_PS_SNAPSHOT_DIR", str(tmp_path / "snap"))
    monkeypatch.setattr(ps_pkg, "get_worker_communicate", lambda: object())
    monkeypatch.setattr(elastic, "resize_state",
                        lambda host, port: {"n_workers": 2, "n_servers": 1})

    def no_propose(*a, **k):
        raise AssertionError("barrier proposed for an unrestorable epoch")

    monkeypatch.setattr(elastic, "propose_resize", no_propose)
    with pytest.raises(recovery.RecoveryError, match="2 workers"):
        recovery.take_job_snapshot(Ex(), jobdir)
    assert recovery.latest_committed_manifest(jobdir) is None


def test_job_checkpointer_grace_budget_barrier_timeout(tmp_path,
                                                       monkeypatch):
    """Regression: the SIGTERM-grace coordinated save must bound its
    drain barrier BELOW the preemption grace period (grace_s /
    HETU_PREEMPT_GRACE_S), leaving headroom for the worker-local
    fallback — take_job_snapshot's 120s default would ride a 30s grace
    window straight into the SIGKILL and cost BOTH saves."""
    from hetu_tpu import recovery
    jd = str(tmp_path)
    monkeypatch.delenv("HETU_PREEMPT_GRACE_S", raising=False)
    ck = recovery.JobCheckpointer(jd)
    assert ck.grace_s == 30.0                    # heturun's default window
    assert ck.grace_timeout() == 25.0
    assert recovery.JobCheckpointer(jd, grace_s=4).grace_timeout() == 2.0
    monkeypatch.setenv("HETU_PREEMPT_GRACE_S", "60")
    assert recovery.JobCheckpointer(jd).grace_timeout() == 55.0
    # an explicit barrier_timeout below the grace bound wins
    assert recovery.JobCheckpointer(
        jd, barrier_timeout=7.5, grace_s=60).grace_timeout() == 7.5

    # save_preempt threads the bound into take_job_snapshot; a cadence
    # save keeps the 120s default
    seen = []

    def fake_take(ex, jobdir, *, on_phase=None, timeout=120.0):
        seen.append(timeout)
        return {"epoch": 1}

    monkeypatch.setattr(recovery, "take_job_snapshot", fake_take)
    ck = recovery.JobCheckpointer(jd, grace_s=30)
    ck.save_preempt(None, 5)
    ck.save(None, 6)
    assert seen == [25.0, 120.0]


# ---------------------------------------------------------------------------
# CLI: jax-free self-test, inventory, and the live whole-job-kill soak
# ---------------------------------------------------------------------------

def test_hetusave_check_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetusave"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "newest-committed" in out.stdout, out.stdout


def test_hetusave_list_cli(tmp_path):
    from hetu_tpu import recovery
    jobdir = str(tmp_path)
    recovery._fake_epoch(jobdir, 1, step=4)
    recovery._fake_epoch(jobdir, 2, step=8, torn="pointer")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetusave"),
         "--list", jobdir], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    rows = [json.loads(line) for line in out.stdout.splitlines()]
    assert {r["epoch"]: r["status"].split(" ")[0] for r in rows} == \
        {1: "committed", 2: "torn"}


def test_hetusave_soak_cli():
    """The CI soak: whole-job kill at pre_commit inside a coordinated
    snapshot, restore from the newest committed manifest, exactly-once
    accounting, and the restored run's losses + final params
    bit-identical to a fault-free twin — end to end through the real
    CLI. The timeout is a hang bound, not a verdict."""
    env = dict(os.environ, HETU_TEST_MODE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetusave"),
         "--seed", "1", "--steps", "6", "--phase", "pre_commit"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "checks green" in out.stdout, out.stdout


@pytest.mark.slow
def test_hetusave_full_phase_matrix_with_resize():
    """The acceptance matrix: five seeds, the kill rotating through every
    snapshot phase (pre_barrier, server_write, pre_commit, post_commit),
    the last seed restoring into a DIFFERENT world size (2 → 1 servers)
    with re-split counter algebra and optimizer state bit-equality."""
    env = dict(os.environ, HETU_TEST_MODE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetusave"),
         "--seeds", "1,2,3,4,5", "--steps", "9", "--resize", "1"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr + out.stdout
    assert out.stdout.count("checks green") == 5, out.stdout
