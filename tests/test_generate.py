"""KV-cache decode correctness: teacher-forced incremental logits must
equal the full training forward's logits position by position (the cache
path and the batch path are the same function or one of them is wrong),
plus greedy self-consistency and sampling-shape checks.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hetu_tpu.models import generate as gen
from hetu_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=3, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32, remat=False)


def test_incremental_logits_match_full_forward():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 16)), jnp.int32)

    full_logits, _ = tfm.forward(params, prompt, CFG)          # (B, T, V)
    fn = gen.make_generate_fn(CFG, max_len=16)
    toks, inc_logits = fn(params, prompt, jax.random.PRNGKey(1))

    np.testing.assert_array_equal(np.asarray(toks), np.asarray(prompt))
    np.testing.assert_allclose(np.asarray(inc_logits),
                               np.asarray(full_logits), atol=2e-4)


def _spec_cfgs():
    target = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                   n_layers=3, d_ff=64, max_seq_len=40,
                                   dtype=jnp.float32, remat=False)
    draft = tfm.TransformerConfig(vocab_size=64, d_model=16, n_heads=2,
                                  n_layers=1, d_ff=32, max_seq_len=40,
                                  dtype=jnp.float32, remat=False)
    return target, draft


@pytest.mark.parametrize("k,P", [(1, 3), (3, 5), (4, 1), (6, 9)])
def test_speculative_equals_plain_greedy(k, P):
    """The exactness contract: speculative output == plain greedy decode
    with the target, for any draft — here an unrelated random model, so
    rejections happen constantly."""
    target, draft = _spec_cfgs()
    tp = tfm.init_params(jax.random.PRNGKey(0), target)
    dp = tfm.init_params(jax.random.PRNGKey(99), draft)
    rng = np.random.RandomState(P * 7 + k)
    prompt = jnp.asarray(rng.randint(0, 64, (1, P)), jnp.int32)
    max_len = 24
    plain = gen.generate(tp, target, np.asarray(prompt), max_len=max_len)
    fn = gen.make_speculative_generate_fn(target, draft, max_len, k=k)
    spec, rounds = fn(tp, dp, prompt)
    np.testing.assert_array_equal(np.asarray(spec), plain)
    assert int(rounds) >= 1


def test_speculative_self_draft_accepts_everything():
    """draft == target: every proposal is accepted, so the loop advances
    k+1 tokens per round — rounds == ceil(generated / (k+1))."""
    target, _ = _spec_cfgs()
    tp = tfm.init_params(jax.random.PRNGKey(1), target)
    P, max_len, k = 4, 25, 4
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (1, P)), jnp.int32)
    fn = gen.make_speculative_generate_fn(target, target, max_len, k=k)
    spec, rounds = fn(tp, tp, prompt)
    plain = gen.generate(tp, target, np.asarray(prompt), max_len=max_len)
    np.testing.assert_array_equal(np.asarray(spec), plain)
    generated_after_prefill = max_len - P - 1
    assert int(rounds) == -(-generated_after_prefill // (k + 1))


def test_chunked_prefill_matches_tokenwise():
    """_chunk_logits over a whole prompt equals the token-by-token cache
    build (the chunked path is new; the scan path is the oracle)."""
    cfg, _ = _spec_cfgs()
    params = tfm.init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.RandomState(3)
    toks = jnp.asarray(rng.randint(0, 64, (2, 9)), jnp.int32)
    L, B, nh, hd, M = cfg.n_layers, 2, cfg.n_heads, cfg.head_dim, 16
    kc = jnp.zeros((L, B, nh, M, hd), cfg.dtype)
    vc = jnp.zeros_like(kc)
    chunk_logits, kc_c, vc_c = gen._chunk_logits(params, cfg, toks,
                                                 kc, vc, 0)
    kc2, vc2 = jnp.zeros_like(kc), jnp.zeros_like(vc)
    steps = []
    for t in range(9):
        lg, kc2, vc2 = gen._one_token_logits(params, cfg, toks[:, t],
                                             kc2, vc2, t)
        steps.append(lg)
    np.testing.assert_allclose(np.asarray(chunk_logits),
                               np.stack([np.asarray(s) for s in steps], 1),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(kc_c), np.asarray(kc2),
                               atol=2e-6, rtol=2e-6)


def test_incremental_logits_match_forward_postln_bias_dialect():
    """The decode path must honor the canonical-architecture knobs
    (post-LN blocks, projection biases, non-default LN eps, erf gelu) —
    a config trained with them must decode through the SAME network."""
    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=3, d_ff=64, max_seq_len=16,
                                dtype=jnp.float32, remat=False,
                                post_ln=True, attn_proj_bias=True,
                                ln_eps=1e-12, gelu_exact=True)
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    # non-zero biases so a dropped bias add would be caught
    params["blocks"]["bqkv"] = jax.random.normal(
        jax.random.PRNGKey(6), params["blocks"]["bqkv"].shape) * 0.1
    params["blocks"]["bo"] = jax.random.normal(
        jax.random.PRNGKey(7), params["blocks"]["bo"].shape) * 0.1
    rng = np.random.RandomState(2)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (2, 16)), jnp.int32)
    full_logits, _ = tfm.forward(params, prompt, cfg)
    fn = gen.make_generate_fn(cfg, max_len=16)
    toks, inc_logits = fn(params, prompt, jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(prompt))
    np.testing.assert_allclose(np.asarray(inc_logits),
                               np.asarray(full_logits), atol=2e-4)


def test_greedy_continuation_is_self_consistent():
    """Greedy tokens re-fed through the full forward must be argmax-stable:
    feeding the generated sequence reproduces its own continuations."""
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, CFG.vocab_size, (3, 4)).astype(np.int32)
    out = gen.generate(params, CFG, prompt, max_len=12)
    assert out.shape == (3, 12)
    np.testing.assert_array_equal(out[:, :4], prompt)

    logits, _ = tfm.forward(params, jnp.asarray(out), CFG)
    pred = np.argmax(np.asarray(logits), -1)
    # positions 4..11 were generated greedily from the prefix
    np.testing.assert_array_equal(out[:, 4:], pred[:, 3:11])


def test_temperature_sampling_shapes_and_determinism():
    params = tfm.init_params(jax.random.PRNGKey(3), CFG)
    prompt = np.zeros((2, 2), np.int32)
    a = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(7))
    b = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(7))
    c = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(8))
    assert a.shape == (2, 8)
    np.testing.assert_array_equal(a, b)      # same key -> same sample
    assert (a != c).any()                    # different key -> different


def test_top_k_sampling_restricts_support():
    """top_k=1 sampling must equal greedy decoding exactly."""
    params = tfm.init_params(jax.random.PRNGKey(4), CFG)
    prompt = np.zeros((2, 2), np.int32)
    fn_k1 = gen.make_generate_fn(CFG, max_len=10, sample=True, top_k=1)
    toks_k1, _ = fn_k1(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
                       1.0)
    greedy = gen.generate(params, CFG, prompt, max_len=10)
    np.testing.assert_array_equal(np.asarray(toks_k1), greedy)


def test_ragged_prompts_match_per_row_decode():
    """prompt_lens decodes a ragged batch in ONE call: each row must be
    token-exact vs decoding that row alone with its true length (greedy),
    for both the scan and the EOS while_loop paths."""
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    rng = np.random.RandomState(4)
    lens = [3, 7, 5]
    Pmax, M = max(lens), 12
    prompt = np.zeros((len(lens), Pmax), np.int32)
    for b, ln in enumerate(lens):
        prompt[b, :ln] = rng.randint(1, CFG.vocab_size, ln)
    prompt = jnp.asarray(prompt)

    fn = gen.make_generate_fn(CFG, max_len=M)
    toks, _ = fn(params, prompt, jax.random.PRNGKey(0),
                 prompt_lens=jnp.asarray(lens, jnp.int32))
    for b, ln in enumerate(lens):
        # solo rows pass prompt_lens too: the exactness guarantee is
        # scoped to the SAME prefill mechanism (the ragged batch
        # teacher-forces in-loop; a bare rectangular call would use the
        # chunked prefill, whose tilings may tie-break differently)
        solo, _ = fn(params, prompt[b:b + 1, :ln], jax.random.PRNGKey(0),
                     prompt_lens=jnp.asarray([ln], jnp.int32))
        np.testing.assert_array_equal(np.asarray(toks[b]),
                                      np.asarray(solo[0]),
                                      err_msg=f"row {b} (len {ln})")
        # on the CPU test backend the chunked prefill is additionally
        # bit-identical to the tokenwise path (TPU tilings may not be)
        chunked, _ = fn(params, prompt[b:b + 1, :ln], jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(chunked[0]),
                                      np.asarray(solo[0]))

    # EOS path: same ragged semantics (greedy rows match the scan path up
    # to each row's first eos; after it the tail is eos-filled)
    eos = int(np.asarray(toks[0, lens[0]]))  # a token row 0 actually emits
    efn = gen.make_eos_generate_fn(CFG, max_len=M, eos_id=eos)
    etoks, _ = efn(params, prompt, jax.random.PRNGKey(0),
                   prompt_lens=jnp.asarray(lens, jnp.int32))
    for b, ln in enumerate(lens):
        row, erow = np.asarray(toks[b]), np.asarray(etoks[b])
        gen_slice = slice(ln, M)
        first_eos = np.where(row[gen_slice] == eos)[0]
        stop = (ln + int(first_eos[0]) + 1) if len(first_eos) else M
        np.testing.assert_array_equal(erow[:stop], row[:stop],
                                      err_msg=f"row {b}")
        assert np.all(erow[stop:] == eos), erow


def test_tp_sharded_decode_matches_single_device():
    """Greedy decode on a dp2 x tp2 mesh: params stay Megatron-sharded, the
    KV cache is dp/tp-sharded, tokens match the unsharded decode exactly."""
    from hetu_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(8, tp=2)
    params = tfm.init_params(jax.random.PRNGKey(5), CFG)
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, CFG.vocab_size, (4, 4)).astype(np.int32)

    ref = gen.generate(params, CFG, prompt, max_len=12)

    sharded = tfm.shard_params(params, CFG, mesh)
    fn = gen.make_generate_fn(CFG, max_len=12, mesh=mesh)
    toks, _ = fn(sharded, jnp.asarray(prompt), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), ref)
    # the weights really stayed distributed through decode: the Megatron
    # layout holds shards on multiple devices (not GSPMD-replicated away)
    wqkv = sharded["blocks"]["wqkv"]
    assert len({s.device for s in wqkv.addressable_shards}) == 8


def test_mqa_sharded_decode_replicates_undivisible_kv_heads():
    """MQA (1 kv head) under tp=2: the cache stores nkv UNBROADCAST heads,
    which tp cannot divide — the head axis must fall back to replication
    (regression guard for the round-5 GQA cache change) while tokens still
    match the unsharded decode."""
    from hetu_tpu.parallel.mesh import auto_mesh

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_kv_heads=1, n_layers=2, d_ff=64,
                                max_seq_len=16, dtype=jnp.float32,
                                remat=False)
    mesh = auto_mesh(8, tp=2)
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, cfg.vocab_size, (4, 4)).astype(np.int32)

    ref = gen.generate(params, cfg, prompt, max_len=12)
    sharded = tfm.shard_params(params, cfg, mesh)
    fn = gen.make_generate_fn(cfg, max_len=12, mesh=mesh)
    toks, _ = fn(sharded, jnp.asarray(prompt), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), ref)


def test_beam_size_one_equals_greedy():
    params = tfm.init_params(jax.random.PRNGKey(6), CFG)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, CFG.vocab_size, (3, 3)).astype(np.int32)
    greedy = gen.generate(params, CFG, prompt, max_len=10)
    fn = gen.make_beam_search_fn(CFG, max_len=10, beam_size=1)
    toks, scores = fn(params, jnp.asarray(prompt))
    np.testing.assert_array_equal(np.asarray(toks[:, 0]), greedy)
    assert np.all(np.isfinite(np.asarray(scores[:, 0])))


def test_beam_search_finds_global_optimum_when_exhaustive():
    """With beam_size >= V^(n_generated), beam search IS exhaustive search:
    its best sequence must equal the brute-force argmax over all
    continuations scored by the full forward."""
    cfg = tfm.TransformerConfig(vocab_size=5, d_model=16, n_heads=2,
                                n_layers=2, d_ff=32, max_seq_len=8,
                                dtype=jnp.float32, remat=False)
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    prompt = np.array([[1, 2]], np.int32)
    P, M, V = 2, 4, 5                       # generate 2 tokens -> 25 seqs

    fn = gen.make_beam_search_fn(cfg, max_len=M, beam_size=V * V)
    toks, scores = fn(params, jnp.asarray(prompt))

    # brute force: score every continuation with the full forward
    best, best_score = None, -np.inf
    for a in range(V):
        for b in range(V):
            seq = np.array([[1, 2, a, b]], np.int32)
            logits, _ = tfm.forward(params, jnp.asarray(seq), cfg)
            lp = np.asarray(jax.nn.log_softmax(
                np.asarray(logits, np.float64), -1))
            s = lp[0, 1, a] + lp[0, 2, b]   # logp of a after pos1, b after 2
            if s > best_score:
                best, best_score = (a, b), s
    assert tuple(np.asarray(toks[0, 0, P:])) == best
    assert float(scores[0, 0]) == pytest.approx(best_score, abs=1e-3)


def test_beam_scores_are_consistent_and_sorted():
    """Each returned beam's score must equal the forward-recomputed
    log-probability of its own generated suffix, and beams come back
    best-first. (A wider beam is NOT guaranteed to beat greedy — beam
    search can prune the greedy path — so that is deliberately not
    asserted.)"""
    params = tfm.init_params(jax.random.PRNGKey(8), CFG)
    rng = np.random.RandomState(4)
    prompt = rng.randint(0, CFG.vocab_size, (2, 3)).astype(np.int32)
    P, M = 3, 9
    fn = gen.make_beam_search_fn(CFG, max_len=M, beam_size=4)
    toks, scores = fn(params, jnp.asarray(prompt))
    s = np.asarray(scores)
    assert np.all(s[:, :-1] >= s[:, 1:] - 1e-6)   # sorted best-first
    for b in range(2):
        for k in range(4):
            seq = np.asarray(toks[b, k])[None]
            logits, _ = tfm.forward(params, jnp.asarray(seq), CFG)
            lp = np.asarray(jax.nn.log_softmax(
                np.asarray(logits, np.float64), -1))
            want = sum(lp[0, t - 1, seq[0, t]] for t in range(P, M))
            assert s[b, k] == pytest.approx(want, abs=1e-3), (b, k)


def test_eos_decode_matches_scan_and_exits_early():
    """EOS while_loop decode must equal the fixed-length scan decode up to
    each row's first generated EOS (then pad with EOS), and must execute
    FEWER steps than max_len when every row finishes early."""
    params = tfm.init_params(jax.random.PRNGKey(9), CFG)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, CFG.vocab_size, (4, 3)).astype(np.int32)
    M = 16

    full = gen.generate(params, CFG, prompt, max_len=M)   # greedy scan
    # choose as EOS the most common token greedy emits -> early finishes
    gen_part = full[:, 3:]
    eos = int(np.bincount(gen_part.ravel()).argmax())

    fn = gen.make_eos_generate_fn(CFG, max_len=M, eos_id=eos)
    toks, steps = fn(params, jnp.asarray(prompt), jax.random.PRNGKey(0))
    toks = np.asarray(toks)

    for b in range(4):
        row_full = full[b]
        hit = np.where(row_full[3:] == eos)[0]
        end = (3 + hit[0] + 1) if len(hit) else M
        np.testing.assert_array_equal(toks[b, :end], row_full[:end])
        assert np.all(toks[b, end:] == eos)
    if all(np.any(full[b, 3:] == eos) for b in range(4)):
        last_eos = max((3 + np.where(full[b, 3:] == eos)[0][0])
                       for b in range(4))
        assert int(steps) <= last_eos + 1 < M   # genuinely exited early
