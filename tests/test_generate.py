"""KV-cache decode correctness: teacher-forced incremental logits must
equal the full training forward's logits position by position (the cache
path and the batch path are the same function or one of them is wrong),
plus greedy self-consistency and sampling-shape checks.
"""
import numpy as np
import jax
import jax.numpy as jnp

from hetu_tpu.models import generate as gen
from hetu_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=3, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32, remat=False)


def test_incremental_logits_match_full_forward():
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, CFG.vocab_size, (2, 16)), jnp.int32)

    full_logits, _ = tfm.forward(params, prompt, CFG)          # (B, T, V)
    fn = gen.make_generate_fn(CFG, max_len=16)
    toks, inc_logits = fn(params, prompt, jax.random.PRNGKey(1))

    np.testing.assert_array_equal(np.asarray(toks), np.asarray(prompt))
    np.testing.assert_allclose(np.asarray(inc_logits),
                               np.asarray(full_logits), atol=2e-4)


def test_greedy_continuation_is_self_consistent():
    """Greedy tokens re-fed through the full forward must be argmax-stable:
    feeding the generated sequence reproduces its own continuations."""
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, CFG.vocab_size, (3, 4)).astype(np.int32)
    out = gen.generate(params, CFG, prompt, max_len=12)
    assert out.shape == (3, 12)
    np.testing.assert_array_equal(out[:, :4], prompt)

    logits, _ = tfm.forward(params, jnp.asarray(out), CFG)
    pred = np.argmax(np.asarray(logits), -1)
    # positions 4..11 were generated greedily from the prefix
    np.testing.assert_array_equal(out[:, 4:], pred[:, 3:11])


def test_temperature_sampling_shapes_and_determinism():
    params = tfm.init_params(jax.random.PRNGKey(3), CFG)
    prompt = np.zeros((2, 2), np.int32)
    a = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(7))
    b = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(7))
    c = gen.generate(params, CFG, prompt, max_len=8, temperature=1.0,
                     rng=jax.random.PRNGKey(8))
    assert a.shape == (2, 8)
    np.testing.assert_array_equal(a, b)      # same key -> same sample
    assert (a != c).any()                    # different key -> different


def test_top_k_sampling_restricts_support():
    """top_k=1 sampling must equal greedy decoding exactly."""
    params = tfm.init_params(jax.random.PRNGKey(4), CFG)
    prompt = np.zeros((2, 2), np.int32)
    fn_k1 = gen.make_generate_fn(CFG, max_len=10, sample=True, top_k=1)
    toks_k1, _ = fn_k1(params, jnp.asarray(prompt), jax.random.PRNGKey(0),
                       1.0)
    greedy = gen.generate(params, CFG, prompt, max_len=10)
    np.testing.assert_array_equal(np.asarray(toks_k1), greedy)


def test_tp_sharded_decode_matches_single_device():
    """Greedy decode on a dp2 x tp2 mesh: params stay Megatron-sharded, the
    KV cache is dp/tp-sharded, tokens match the unsharded decode exactly."""
    from hetu_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(8, tp=2)
    params = tfm.init_params(jax.random.PRNGKey(5), CFG)
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, CFG.vocab_size, (4, 4)).astype(np.int32)

    ref = gen.generate(params, CFG, prompt, max_len=12)

    sharded = tfm.shard_params(params, CFG, mesh)
    fn = gen.make_generate_fn(CFG, max_len=12, mesh=mesh)
    toks, _ = fn(sharded, jnp.asarray(prompt), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), ref)
    # the weights really stayed distributed through decode: the Megatron
    # layout holds shards on multiple devices (not GSPMD-replicated away)
    wqkv = sharded["blocks"]["wqkv"]
    assert len({s.device for s in wqkv.addressable_shards}) == 8
