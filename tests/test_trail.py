"""hetutrail — cross-process PS-wire tracing, critical-path attribution,
straggler detection (docs/OBSERVABILITY.md pillar 5).

The two cluster tests are the acceptance proofs: client↔server spans join
by (client_id, req_id) at ≥90% under a live multi-process cluster, and a
``ps_slow``-injected apply makes ``hetutrail --step N`` name the PS leg as
the dominant critical-path phase AND the slowed server. The rest are the
satellites: straggler detector/SkewMonitor/ScalePolicy visibility,
off-mode zero-work, JSONL rotation, monotonic re-anchoring, run_summary
enrichment, and the --check CLI smoke.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from test_ps import run_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# span join + slow-server attribution under a live multi-process cluster
# ---------------------------------------------------------------------------

def _span_join_worker(client, rank, tmpdir):
    from hetu_tpu.telemetry import trail
    td = os.environ["HETU_TRAIL_DIR"]
    client.InitTensor(1, 0, 64, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    w = trail.TrailWriter(os.path.join(td, f"trail-client-r{rank}.jsonl"),
                          rank)
    for step in range(6):
        client.SetTrailStep(step)
        if step == 3:
            # deterministic slow leg: server 1's next apply sleeps
            client.TestSlowApply(server=1, ms=250)
        client.Push(1, np.ones(64, np.float32))
        client.Wait(1)
        out = np.zeros(64, np.float32)
        client.Pull(1, out)
        client.Wait(1)
    assert trail.drain_client_spans(client, w) > 0
    assert client.TrailDropped() == 0
    w.close()


def test_span_join_and_slow_server(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TRAIL_DIR", str(tmp_path))
    run_cluster(_span_join_worker, tmp_path, n_workers=1, n_servers=2)
    from hetu_tpu.telemetry import trail
    loaded = trail.load_dir(str(tmp_path))
    assert loaded["client"] and loaded["server"]
    joined, rate = trail.join_spans(loaded["client"], loaded["server"])
    # acceptance: >= 90% of client-side PS RPC spans join to a server span
    assert rate is not None and rate >= 0.9, rate
    # the slowed server dominates the blocking time around step 3, and the
    # joined server span carries the apply time itself
    by_server, by_tensor = trail._ps_attribution(joined, 3)
    assert by_server[1] > by_server.get(0, 0) + 200_000, by_server
    assert by_tensor.get(1, 0) > 200_000, by_tensor
    slow = [c for c in joined if c["server"] == 1 and c["dur_us"] > 200_000]
    assert slow and slow[0]["srv"] is not None
    assert slow[0]["srv"]["apply_us"] > 200_000


# ---------------------------------------------------------------------------
# executor integration: ps_slow fault -> hetutrail --step names the PS-pull
# leg and the slowed server
# ---------------------------------------------------------------------------

def _executor_ps_slow_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.resilience import FaultInjector, Supervisor
    embed = ht.init.random_normal((40, 8), stddev=0.1, name="embed",
                                  is_embed=True)
    idx = ht.Variable(name="idx", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    vec = ht.embedding_lookup_op(embed, idx)
    flat = ht.array_reshape_op(vec, (-1, 32))
    w = ht.init.xavier_uniform((32, 1), name="w")
    prob = ht.sigmoid_op(ht.matmul_op(flat, w))
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    # BSP + prefetch: the pull stream IS the push stream, so the step-4
    # pull queues behind step 3's slowed push — the deterministic
    # pull-blocked-on-apply shape the critical path must attribute
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="Hybrid", bsp=True, prefetch=True,
                     telemetry="metrics", seed=0)
    sup = Supervisor(fault_injector=FaultInjector("ps_slow@3:400"))
    ex.attach_supervisor(sup)
    rng = np.random.RandomState(0)
    # 12 steps, not the minimal 9: the one-shot delay consumes the
    # server's NEXT apply after the boundary-3 arming, and a loaded box
    # can slide that apply a step or two — the extra steps guarantee
    # consumers remain
    for _ in range(12):
        bidx = rng.randint(0, 40, (16, 4)).astype(np.float32)
        by = rng.randint(0, 2, (16, 1)).astype(np.float32)
        ex.run("train", feed_dict={idx: bidx, y_: by})
    ex.close()
    telemetry.shutdown()   # flush metrics-r0.jsonl before the parent reads


def test_executor_ps_slow_critical_path(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TRAIL_DRAIN_EVERY", "1")
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    from hetu_tpu.telemetry import trail

    def drive(d):
        """One cluster run into dir ``d``; returns (loaded, entry, step)
        with entry=None when no pull blocked >300 ms — usually step 4,
        but the one-shot delay hits the server's NEXT apply, and a
        loaded box can slide that apply (and the pull that queues behind
        it) a step or two later, so scan a window instead of pinning."""
        os.makedirs(d, exist_ok=True)
        monkeypatch.setenv("HETU_TRAIL_DIR", str(d))
        monkeypatch.setenv("HETU_TELEMETRY_DIR", str(d))
        run_cluster(_executor_ps_slow_worker, d, n_workers=1,
                    n_servers=2)
        loaded = trail.load_dir(str(d))
        for s in (4, 5, 6, 7, 8):
            cand = trail.attribute_step(loaded, s)["ranks"][0]
            if cand["legs"]["ps_pull"] > 300.0:
                return loaded, cand, s
        return loaded, None, None

    tdir = str(tmp_path / "run1")
    loaded, entry, blocked_step = drive(tdir)
    if entry is None:
        # rare under load: the injected delay was consumed somewhere no
        # pull waited on; one retry in a fresh dir (the resnet-flake
        # retry-once precedent)
        tdir = str(tmp_path / "run2")
        loaded, entry, blocked_step = drive(tdir)
    assert entry is not None, "no step 4-8 blocked >300ms in its pull"
    joined, rate = trail.join_spans(loaded["client"], loaded["server"])
    assert rate is not None and rate >= 0.9, rate
    assert entry["dominant"] == "ps_pull", entry
    assert entry["fraction"] > 0.5, entry
    # ...and the verdict names the slowed server (HETU_PS_SLOW_SERVER
    # default: 0)
    assert entry.get("server") == 0, entry
    # the CLI says the same thing, jax-free
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetutrail"),
         tdir, "--step", str(blocked_step)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "dominant leg ps_pull" in out.stdout, out.stdout
    assert "server 0" in out.stdout, out.stdout
    # whole-run report works on the same dir
    rep_all = trail.analyze(tdir)
    assert rep_all["join_rate"] >= 0.9
    # critical-path gauges rode the metrics snapshots
    snap = {}
    recs = [json.loads(line) for line in
            open(os.path.join(tdir, "metrics-r0.jsonl")) if line.strip()]
    for r in recs:
        if isinstance(r.get("metrics"), dict):
            snap = r["metrics"]
    assert any(k.startswith("hetu_critical_path_ms") for k in snap), \
        sorted(snap)[:20]
    assert 0 < snap.get("hetu_cp_fraction", 0) <= 1


# ---------------------------------------------------------------------------
# off-mode: zero trail work without HETU_TRAIL_DIR
# ---------------------------------------------------------------------------

def _off_mode_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu.telemetry import trail
    assert trail.armed() is None
    client.InitTensor(1, 0, 16, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    client.Push(1, np.ones(16, np.float32))
    client.Wait(1)
    # the native ring never armed: nothing recorded, nothing dropped
    assert len(client.DrainTrailSpans()) == 0
    assert client.TrailDropped() == 0
    # an executor in the same process wires no trail writer and the step
    # boundary is a single attribute check
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.zeros((4, 1), name="w")
    err = ht.matmul_op(x, w) - y_
    loss = ht.reduce_mean_op(ht.mul_op(err, err), [0])
    train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="PS")
    assert ex.ps_runtime.trail_writer is None
    for _ in range(2):
        ex.run("train", feed_dict={x: np.ones((4, 4), np.float32),
                                   y_: np.ones((4, 1), np.float32)})
    assert len(client.DrainTrailSpans()) == 0
    ex.close()
    import glob
    assert not glob.glob(os.path.join(str(tmpdir), "trail-*.jsonl"))


def test_trail_off_mode_zero_work(tmp_path, monkeypatch):
    monkeypatch.delenv("HETU_TRAIL_DIR", raising=False)
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    run_cluster(_off_mode_worker, tmp_path, n_workers=1, n_servers=1)
    import glob
    assert not glob.glob(os.path.join(str(tmp_path), "trail-*.jsonl"))


# ---------------------------------------------------------------------------
# straggler detection + ScalePolicy visibility
# ---------------------------------------------------------------------------

def test_straggler_detector():
    from hetu_tpu.telemetry.trail import StragglerDetector
    det = StragglerDetector(k=3, ratio=1.5, min_ms=1.0)
    # two clean steps, then rank 1 goes slow for 3 consecutive steps
    assert det.observe(0, {0: 10.0, 1: 10.5}) is None
    assert det.observe(1, {0: 10.0, 1: 11.0}) is None
    assert det.observe(2, {0: 10.0, 1: 30.0}) is None
    assert det.observe(3, {0: 10.0, 1: 31.0}) is None
    ev = det.observe(4, {0: 10.0, 1: 32.0})
    assert ev is not None and ev["rank"] == 1 and ev["streak"] == 3
    # after firing, the streak restarts (re-fires every k steps)
    assert det.observe(5, {0: 10.0, 1: 33.0}) is None
    # a recovery resets the streak
    assert det.observe(6, {0: 10.0, 1: 10.0}) is None
    assert det.observe(7, {0: 10.0, 1: 40.0}) is None
    # sub-min_ms skew on fast steps never fires, whatever the ratio
    fast = StragglerDetector(k=1, ratio=1.5, min_ms=1.0)
    assert fast.observe(0, {0: 0.01, 1: 0.10}) is None
    # single-rank worlds have no skew to detect
    assert fast.observe(1, {0: 5.0}) is None


def test_skew_monitor_and_scale_policy(tmp_path):
    from hetu_tpu.elastic import ScalePolicy
    from hetu_tpu.telemetry.trail import SkewMonitor, StragglerDetector
    # rank 1 straggles from step 1 on, and its blocking chain is
    # PS-pull-dominated — the SkewMonitor must attribute the server
    for rank in (0, 1):
        with open(tmp_path / f"metrics-r{rank}.jsonl", "w") as f:
            for step in range(6):
                slow = rank == 1 and step >= 1
                ms = 40.0 if slow else 8.0
                pull = 35.0 if slow else 1.0
                f.write(json.dumps(
                    {"ts": step * 0.1, "rank": rank, "kind": "step",
                     "sub": "train", "step": step, "step_ms": ms,
                     "phases": {"prestep_ms": pull + 0.5,
                                "dispatch_ms": 3.0, "poststep_ms": 0.5,
                                "ps_pull_ms": pull,
                                "ps_push_ms": 0.2}}) + "\n")
    # rank 1's client spans: server 1 carries the blocking time
    with open(tmp_path / "trail-client-r1.jsonl", "w") as f:
        for step in range(6):
            for server in (0, 1):
                f.write(json.dumps(
                    {"kind": "rpc", "rank": 1, "req_id": 100 + step,
                     "client": 2, "server": server, "psf": 21, "tensor": 5,
                     "step": step, "t0_us": step * 1000,
                     "dur_us": 34_000 if server == 1 else 500,
                     "req_bytes": 64, "rsp_bytes": 640}) + "\n")
    seen = []
    mon = SkewMonitor(str(tmp_path), detector=StragglerDetector(k=3),
                      on_event=seen.append)
    fired = mon.poll()
    assert fired and fired[0]["rank"] == 1
    # PS-dominated straggler carries the blocking server + world size
    assert fired[0]["server"] == 1 and fired[0]["n_servers"] == 2
    assert seen == fired
    assert mon.last_skew_ms == pytest.approx(32.0)
    assert mon.last_slowest == 1
    # the events landed next to the rank files for post-mortems
    evs = [json.loads(line) for line in
           open(tmp_path / "trail-events.jsonl")]
    assert evs and evs[0]["kind"] == "straggler" and evs[0]["rank"] == 1
    # a second poll with no new data fires nothing
    assert mon.poll() == []

    # ScalePolicy visibility: rank-level stragglers are recorded but don't
    # grow the PS tier; the server-attributed event above does (bounded +
    # cooldown) — the full SkewMonitor -> ScalePolicy chain. The cluster
    # size for the cap check comes from the policy's OWN stats view
    # (observe()), never from the event's lower-bound n_servers.
    two_servers = [[0] * 8, [0] * 8]
    pol = ScalePolicy(max_servers=3, cooldown_s=0.0)
    pol.observe(two_servers, now=99.0)
    assert pol.note_straggler({"kind": "straggler", "rank": 1, "step": 3},
                              now=100.0) is None
    assert pol.stragglers_seen == 1
    rec = pol.note_straggler(fired[0], now=101.0)
    assert rec == {"action": "grow_server", "n_servers": 3,
                   "reason": "straggler server 1"}
    # without a stats view there is no trustworthy size: no recommendation
    # (an event's span-derived n_servers could undercount past the cap)
    blind = ScalePolicy(max_servers=3, cooldown_s=0.0)
    assert blind.note_straggler(fired[0], now=100.0) is None
    # at the bound: no recommendation
    pol3 = ScalePolicy(max_servers=2, cooldown_s=0.0)
    pol3.observe(two_servers, now=199.0)
    assert pol3.note_straggler({"kind": "straggler", "server": 1},
                               now=200.0) is None
    # cooldown respected
    pol2 = ScalePolicy(max_servers=4, cooldown_s=30.0)
    pol2.observe(two_servers, now=999.0)
    assert pol2.note_straggler({"kind": "straggler", "server": 0},
                               now=1000.0) is not None
    assert pol2.note_straggler({"kind": "straggler", "server": 0},
                               now=1001.0) is None


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_jsonl_rotation(tmp_path):
    """HETU_TELEMETRY_MAX_MB: atomic rollover to one .1 backup; offset
    readers observe size < offset and restart (hetutop Follower/
    SkewMonitor contract)."""
    from hetu_tpu.telemetry.registry import JsonlSink
    path = str(tmp_path / "metrics-r0.jsonl")
    sink = JsonlSink(path, base_fields={"rank": 0}, max_mb=0.002)  # 2 KB
    for i in range(100):
        sink.write({"kind": "step", "step": i, "step_ms": 1.0})
    sink.close()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) < 2500
    # both generations parse, and together they cover the tail
    recs = [json.loads(line) for line in open(path) if line.strip()]
    assert recs and recs[-1]["step"] == 99
    old = [json.loads(line) for line in open(path + ".1") if line.strip()]
    assert old
    # default-off: no cap -> no rotation (test stability contract)
    p2 = str(tmp_path / "m2.jsonl")
    s2 = JsonlSink(p2, max_mb=None)
    assert s2._max_bytes == 0 or os.environ.get("HETU_TELEMETRY_MAX_MB")
    s2.close()
    # the trail client writer is bounded the same way (HETU_TRAIL_MAX_MB),
    # and each generation re-anchors
    from hetu_tpu.telemetry.trail import TrailWriter
    tw = TrailWriter(str(tmp_path / "trail-client-r0.jsonl"), 0,
                     max_mb=0.002)
    row = (1, 0, 0, 21, 5, 0, 1000, 50, 64, 640)
    for _ in range(10):
        tw.write_rows([row] * 10)
    tw.close()
    assert os.path.exists(str(tmp_path / "trail-client-r0.jsonl") + ".1")
    live = [json.loads(line) for line in
            open(tmp_path / "trail-client-r0.jsonl") if line.strip()]
    assert live and live[0]["kind"] == "anchor"   # fresh generation anchor


def test_trace_merge_prefers_mono_anchor(tmp_path):
    """An NTP step between ranks moves the wall anchors but not the
    monotonic ones; the merge must align on mono when the ranks share a
    kernel boot (same boot_id — hostnames can collide across machines)
    and fall back to unix across boots."""
    from hetu_tpu.telemetry.hetutrace import merge

    def write(path, rank, unix, mono, boot):
        doc = {"displayTimeUnit": "ms",
               "otherData": {"clock_anchor_unix_s": unix,
                             "clock_anchor_mono_s": mono,
                             "host": "hostA", "boot_id": boot,
                             "rank": rank},
               "traceEvents": [{"name": "step", "cat": "step", "ph": "X",
                                "ts": 0.0, "dur": 5.0, "pid": rank,
                                "tid": 1}]}
        with open(path, "w") as f:
            json.dump(doc, f)

    # rank 1 started 1s later (mono +1.0) but its wall clock was
    # NTP-stepped +1000s: unix anchoring would shift its lane by 1000s
    write(tmp_path / "trace-r0.json", 0, 1000.0, 50.0, "boot-a")
    write(tmp_path / "trace-r1.json", 1, 2000.0, 51.0, "boot-a")
    out = str(tmp_path / "merged.json")
    merge([str(tmp_path)], out)
    doc = json.load(open(out))
    assert doc["otherData"]["anchor_clock"] == "monotonic"
    ts_by_pid = {e["pid"]: e["ts"] for e in doc["traceEvents"]}
    assert ts_by_pid[0] == 0.0
    assert ts_by_pid[1] == pytest.approx(1e6)   # 1s, not 1000s
    # different kernel boots (identical hostnames — container images):
    # mono origins are not comparable -> unix fallback
    write(tmp_path / "trace-r1.json", 1, 2000.0, 51.0, "boot-b")
    merge([str(tmp_path)], out)
    doc = json.load(open(out))
    assert doc["otherData"]["anchor_clock"] == "unix"
    # a real Tracer doc advertises both identity fields
    from hetu_tpu.telemetry.tracing import Tracer
    tr = Tracer(str(tmp_path / "trace-r9.json"), rank=9)
    with tr.span("s"):
        pass
    tr.flush()
    od = json.load(open(tmp_path / "trace-r9.json"))["otherData"]
    assert "clock_anchor_mono_s" in od and "boot_id" in od


def test_run_summary_final_steps_and_resizes(tmp_path, monkeypatch):
    from hetu_tpu import runner
    with open(tmp_path / "metrics-r0.jsonl", "w") as f:
        for step in range(5):
            f.write(json.dumps({"ts": step, "rank": 0, "kind": "step",
                                "step": step, "step_ms": 1.0}) + "\n")
        f.write(json.dumps({"ts": 9.0, "rank": 0, "kind": "event",
                            "name": "resize_commit", "step": 4,
                            "world_version": 2, "n_workers": 1,
                            "n_servers": 2, "duration_ms": 12.5}) + "\n")
    with open(tmp_path / "metrics-r1.jsonl", "w") as f:
        for step in range(3):
            f.write(json.dumps({"ts": step, "rank": 1, "kind": "step",
                                "step": step, "step_ms": 1.0}) + "\n")
    monkeypatch.setattr(runner, "_tel_dir", str(tmp_path))
    runner._write_telemetry_summary(0, False, 2)
    s = json.loads(open(tmp_path / "run_summary.json").read())
    assert s["final_steps"] == {"0": 4, "1": 2}
    assert s["world_versions"] == [2]
    assert s["resizes"][0]["name"] == "resize_commit"
    assert s["resizes"][0]["world_version"] == 2


def test_fault_spec_ps_slow_parses():
    from hetu_tpu.resilience import FaultInjector
    fi = FaultInjector("ps_slow@5:250")
    assert fi.entries == [{"kind": "ps_slow", "step": 5, "arg": 250.0,
                           "fired": False}]
    assert FaultInjector("ps_slow@2").entries[0]["arg"] is None


def test_export_critical_path_gauges():
    from hetu_tpu.telemetry.registry import MetricsRegistry
    from hetu_tpu.telemetry import trail
    reg = MetricsRegistry()
    cache = {}
    legs = trail.step_legs({"prestep_ms": 5.0, "dispatch_ms": 2.0,
                            "poststep_ms": 1.0, "ps_pull_ms": 4.0,
                            "ps_push_ms": 0.5})
    assert legs == {"feed": 1.0, "ps_pull": 4.0, "compute": 2.0,
                    "ps_push": 0.5, "poststep": 0.5}
    dom, frac = trail.export_critical_path(reg, legs, cache=cache)
    assert dom == "ps_pull" and frac == pytest.approx(0.5)
    snap = reg.snapshot()
    assert snap['hetu_critical_path_ms{leg="ps_pull"}'] == 4.0
    assert snap["hetu_cp_fraction"] == pytest.approx(0.5)
    # cached handles are reused across steps
    assert trail.export_critical_path(reg, legs, cache=cache)[0] == \
        "ps_pull"


def test_profiler_cp_fraction_column():
    from hetu_tpu.telemetry import profiler
    means = {"step_ms": 10.0, "prestep_ms": 5.0, "dispatch_ms": 2.0,
             "poststep_ms": 1.0, "ps_pull_ms": 4.0, "ps_push_ms": 0.5,
             "ps_comm_ms": 4.5, "n_steps": 3}
    b = profiler.step_breakdown(means)
    assert b["cp_dominant"] == "ps_pull"
    assert b["cp_fraction"] == pytest.approx(0.5)
    assert b["cp_legs_ms"]["compute"] == 2.0


def test_hetutop_trail_panel():
    from hetu_tpu.telemetry.hetutop import render_frame
    m = {'hetu_critical_path_ms{leg="feed"}': 1.0,
         'hetu_critical_path_ms{leg="ps_pull"}': 4.0,
         'hetu_critical_path_ms{leg="compute"}': 2.0,
         'hetu_critical_path_ms{leg="ps_push"}': 0.5,
         'hetu_critical_path_ms{leg="poststep"}': 0.5,
         "hetu_cp_fraction": 0.5,
         'hetu_events_total{event="straggler"}': 2}

    def rank(p50):
        return {"last_step": 9, "sub": "train", "steps_per_s": 10.0,
                "examples_per_s": None, "p50": p50, "p90": p50, "p99": p50,
                "max": p50, "metrics": m, "last_ts": 1.0}

    state = {"ranks": {0: rank(8.0), 1: rank(40.0)}, "events": [],
             "ps": {}, "run_info": {}, "model": {}, "scope": {}}
    frame = render_frame(state)
    assert "trail:" in frame
    assert "dominant ps_pull 50%" in frame
    assert "slowest r1" in frame
    assert "stragglers 4" in frame   # summed across both ranks' snapshots


def test_hetutrail_check_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetutrail"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "pipeline ok" in out.stdout
