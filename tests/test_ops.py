"""Op-level parity tests vs numpy oracle (reference tests/test_gpu_op.py).

Each op is evaluated through the full Executor path (graph -> trace -> jit)
and compared against a numpy reference implementation.
"""
import numpy as np
import pytest

import hetu_tpu as ht

RTOL, ATOL = 1e-5, 1e-5


from conftest import run_graph_helper as run_graph, feed_helper as feed


def test_add_mul_div():
    a, av = feed((4, 5), seed=1, name="a")
    b, bv = feed((4, 5), seed=2, name="b")
    out = run_graph((a + b) * a / b, {a: av, b: bv})
    np.testing.assert_allclose(out, (av + bv) * av / bv, rtol=RTOL, atol=ATOL)


def test_const_ops():
    a, av = feed((3, 3), seed=3, name="a")
    out = run_graph(2.0 * a + 1.5 - a / 2.0, {a: av})
    np.testing.assert_allclose(out, 2.0 * av + 1.5 - av / 2.0, rtol=RTOL, atol=ATOL)


def test_matmul_trans():
    a, av = feed((4, 6), seed=4, name="a")
    b, bv = feed((5, 6), seed=5, name="b")
    out = run_graph(ht.matmul_op(a, b, trans_B=True), {a: av, b: bv})
    np.testing.assert_allclose(out, av @ bv.T, rtol=1e-4, atol=1e-4)


def test_batch_matmul():
    a, av = feed((2, 4, 6), seed=6, name="a")
    b, bv = feed((2, 6, 3), seed=7, name="b")
    out = run_graph(ht.batch_matmul_op(a, b), {a: av, b: bv})
    np.testing.assert_allclose(out, av @ bv, rtol=1e-4, atol=1e-4)


def test_activations():
    a, av = feed((4, 5), seed=8, name="a")
    np.testing.assert_allclose(run_graph(ht.relu_op(a), {a: av}),
                               np.maximum(av, 0), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.sigmoid_op(a), {a: av}),
                               1 / (1 + np.exp(-av)), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.tanh_op(a), {a: av}),
                               np.tanh(av), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.leaky_relu_op(a, 0.1), {a: av}),
                               np.where(av > 0, av, 0.1 * av), rtol=RTOL, atol=ATOL)


def test_softmax():
    a, av = feed((4, 7), seed=9, name="a")
    e = np.exp(av - av.max(-1, keepdims=True))
    np.testing.assert_allclose(run_graph(ht.softmax_op(a), {a: av}),
                               e / e.sum(-1, keepdims=True), rtol=RTOL, atol=ATOL)


def test_softmax_cross_entropy():
    logits, lv = feed((8, 10), seed=10, name="logits")
    labels_v = np.eye(10, dtype=np.float32)[np.random.RandomState(0).randint(0, 10, 8)]
    labels = ht.Variable(name="labels", trainable=False)
    out = run_graph(ht.softmaxcrossentropy_op(logits, labels),
                    {logits: lv, labels: labels_v})
    e = np.exp(lv - lv.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    expect = -np.sum(labels_v * np.log(p), axis=-1)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)


def test_reduce_ops():
    a, av = feed((4, 5, 6), seed=11, name="a")
    np.testing.assert_allclose(run_graph(ht.reduce_sum_op(a, [0, 2]), {a: av}),
                               av.sum((0, 2)), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(run_graph(ht.reduce_mean_op(a, [1], keepdims=True), {a: av}),
                               av.mean(1, keepdims=True), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.reducesumaxiszero_op(a), {a: av}),
                               av.sum(0), rtol=1e-4, atol=1e-4)


def test_shape_ops():
    a, av = feed((4, 6), seed=12, name="a")
    np.testing.assert_allclose(run_graph(ht.array_reshape_op(a, (2, 12)), {a: av}),
                               av.reshape(2, 12))
    np.testing.assert_allclose(run_graph(ht.transpose_op(a, (1, 0)), {a: av}), av.T)
    np.testing.assert_allclose(run_graph(ht.slice_op(a, (1, 2), (2, 3)), {a: av}),
                               av[1:3, 2:5])
    np.testing.assert_allclose(run_graph(ht.slice_op(a, (1, 0), (-1, -1)), {a: av}),
                               av[1:, :])


def test_concat_split_pad():
    a, av = feed((4, 6), seed=13, name="a")
    b, bv = feed((4, 6), seed=14, name="b")
    np.testing.assert_allclose(run_graph(ht.concat_op(a, b, axis=1), {a: av, b: bv}),
                               np.concatenate([av, bv], 1))
    np.testing.assert_allclose(run_graph(ht.split_op(a, [1], [1], [3]), {a: av}),
                               av[:, 2:4])
    np.testing.assert_allclose(
        run_graph(ht.pad_op(a, [[1, 1], [2, 2]]), {a: av}),
        np.pad(av, [[1, 1], [2, 2]]))


def test_broadcast():
    a, av = feed((6,), seed=15, name="a")
    b, bv = feed((4, 6), seed=16, name="b")
    np.testing.assert_allclose(run_graph(ht.broadcastto_op(a, b), {a: av, b: bv}),
                               np.broadcast_to(av, (4, 6)))
    np.testing.assert_allclose(
        run_graph(ht.broadcast_shape_op(a, (4, 6), add_axes=(0,)), {a: av}),
        np.broadcast_to(av[None], (4, 6)))


def test_where_onehot():
    c = ht.Variable(name="c", trainable=False)
    a, av = feed((4, 5), seed=17, name="a")
    b, bv = feed((4, 5), seed=18, name="b")
    cv = (np.random.RandomState(1).rand(4, 5) > 0.5).astype(np.float32)
    np.testing.assert_allclose(run_graph(ht.where_op(c, a, b), {c: cv, a: av, b: bv}),
                               np.where(cv != 0, av, bv))
    idx = ht.Variable(name="idx", trainable=False)
    iv = np.array([0, 2, 1], dtype=np.int32)
    np.testing.assert_allclose(run_graph(ht.one_hot_op(idx, 4), {idx: iv}),
                               np.eye(4, dtype=np.float32)[iv])


def test_conv2d_pool():
    x, xv = feed((2, 3, 8, 8), seed=19, name="x")
    w, wv = feed((4, 3, 3, 3), seed=20, name="w")
    out = run_graph(ht.conv2d_op(x, w, padding=1, stride=1), {x: xv, w: wv})
    assert out.shape == (2, 4, 8, 8)
    # oracle via scipy-style direct loop on one element
    import itertools
    n, co, i, j = 1, 2, 3, 4
    patch = np.pad(xv, ((0, 0), (0, 0), (1, 1), (1, 1)))[n, :, i:i + 3, j:j + 3]
    np.testing.assert_allclose(out[n, co, i, j], np.sum(patch * wv[co]),
                               rtol=1e-4, atol=1e-4)
    pooled = run_graph(ht.max_pool2d_op(x, 2, 2, 0, 2), {x: xv})
    np.testing.assert_allclose(
        pooled, xv.reshape(2, 3, 4, 2, 4, 2).max((3, 5)), rtol=RTOL, atol=ATOL)
    avg = run_graph(ht.avg_pool2d_op(x, 2, 2, 0, 2), {x: xv})
    np.testing.assert_allclose(
        avg, xv.reshape(2, 3, 4, 2, 4, 2).mean((3, 5)), rtol=RTOL, atol=ATOL)


def test_layer_norm():
    x, xv = feed((4, 10), seed=21, name="x")
    scale = ht.init.ones((10,), name="ln_scale")
    bias = ht.init.zeros((10,), name="ln_bias")
    out = run_graph(ht.layer_normalization_op(x, scale, bias, eps=1e-5), {x: xv})
    mu = xv.mean(-1, keepdims=True)
    var = xv.var(-1, keepdims=True)
    np.testing.assert_allclose(out, (xv - mu) / np.sqrt(var + 1e-5),
                               rtol=1e-4, atol=1e-4)


def test_embedding_lookup():
    table = ht.init.random_normal((20, 8), stddev=1.0, name="emb")
    idx = ht.Variable(name="idx", trainable=False)
    iv = np.array([[1, 3], [5, 7]], dtype=np.int32)
    ex = ht.Executor([ht.embedding_lookup_op(table, idx)], ctx=ht.cpu(0))
    (res,) = ex.run("default", feed_dict={idx: iv})
    tval = np.asarray(ex.state["params"][id(table)])
    np.testing.assert_allclose(res.asnumpy(), tval[iv], rtol=RTOL, atol=ATOL)


def test_csr_ops():
    import scipy.sparse as sp
    rng = np.random.RandomState(2)
    dense = (rng.rand(6, 8) > 0.6).astype(np.float32) * rng.randn(6, 8).astype(np.float32)
    coo = sp.coo_matrix(dense)
    spv = ht.sparse_array(coo.data, (coo.row, coo.col), dense.shape, ctx=ht.cpu(0))
    a = ht.Variable(name="sparse_a", trainable=False)
    x, xv = feed((8,), seed=22, name="x")
    out = run_graph(ht.csrmv_op(a, x), {a: spv, x: xv})
    np.testing.assert_allclose(out, dense @ xv, rtol=1e-4, atol=1e-4)
    m, mv = feed((8, 5), seed=23, name="m")
    out2 = run_graph(ht.csrmm_op(a, m), {a: spv, m: mv})
    np.testing.assert_allclose(out2, dense @ mv, rtol=1e-4, atol=1e-4)


def test_infer_shape():
    a = ht.Variable(name="a", trainable=False)
    node = ht.matmul_op(a, a, trans_B=True)
    assert node.infer_shape([(3, 5), (4, 5)]) == (3, 4)
