"""Sampled-subgraph GCN through a LIVE multi-worker PS cluster with the
embedding cache in front — the reference's GraphMix training mode
(``examples/gnn/run_dist.py``), validated the reference's way: spawn real
scheduler/server/worker processes (SURVEY.md §4), assert learning happens
on every worker sharing the one PS embedding table.
"""
import os
import sys

from test_ps import run_cluster

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "gnn"))


def _worker(client, rank, tmpdir):
    import run_sampled
    args = run_sampled.parse_args([
        "--nodes", "256", "--nseed", "16", "--nmax", "64", "--hidden", "16",
        "--num-epoch", "6", "--workers", "2", "--cpu", "--cache-perf",
        "--learning-rate", "0.08"])
    history = run_sampled.train(client, rank, args)
    first_loss, first_acc = history[0]
    last_loss, last_acc = history[-1]
    assert last_loss < first_loss * 0.8, (first_loss, last_loss)
    assert last_acc > max(0.5, first_acc), (first_acc, last_acc)


def test_sampled_gcn_two_workers_shared_table(tmp_path):
    run_cluster(_worker, tmpdir=tmp_path, n_workers=2, n_servers=1,
                timeout=300)
