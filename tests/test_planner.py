"""hetuplan (ISSUE 14): cost-model unit algebra vs hand-computed wire
formulas, golden plans for the bundled builders (the CTR-PS cell must pick
Hybrid with quantized sparse legs without hand hints), the HBM gate
(an infeasible mesh is never the chosen plan; the ZeRO-1/remat fallback is
exercised), calibration direction, the rows-route abstract tracing, the
``--plan --json`` CI smoke, and executor plan adoption."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import analysis
from hetu_tpu.analysis import cost_model as cm
from hetu_tpu.analysis import planner as pl
from hetu_tpu.analysis import examples
from hetu_tpu.analysis.cli import _builder_result

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# cost-model unit algebra vs hand-computed formulas
# ---------------------------------------------------------------------------

def test_ring_allreduce_bytes_hand_computed():
    # dp=4, n=1024 f32: each leg moves (dp-1)/dp of the payload
    n, dp = 1024, 4
    got = cm.ring_allreduce_bytes(n, dp)
    frac = 3 / 4
    assert got["raw"] == pytest.approx(2 * 4 * n * frac)
    assert got["wire"] == got["raw"] and got["ratio"] == 1.0
    # quantized: reduce-scatter stays f32 (exact sum), all-gather is
    # 1 byte/elem + one f32 scale per 256-block
    q = cm.ring_allreduce_bytes(n, dp, quant="int8", block=256)
    nb = 1024 // 256
    assert q["wire"] == pytest.approx((4 * n + n + 4 * nb) * frac)
    # the PR-8 analytic DP ratio at large n: ~1.6x
    big = cm.ring_allreduce_bytes(1 << 20, 8, quant="int8", block=256)
    assert 1.55 < big["ratio"] < 1.65
    # degenerate dp: no wire at all
    assert cm.ring_allreduce_bytes(n, 1)["wire"] == 0.0


def test_ps_dense_bytes_hand_computed():
    n = 4096
    raw = cm.ps_dense_bytes(n)
    assert raw["raw"] == raw["wire"] == 2 * 4 * n   # push + pull, f32
    q = cm.ps_dense_bytes(n, quant="kQI8", block=256)
    nb = n // 256
    assert q["wire"] == pytest.approx(2 * (n + 4 * nb))
    assert 3.5 < q["ratio"] < 4.0                    # kQI8 dense ~3.88x


def test_ps_sparse_bytes_hand_computed():
    rows, dim = 100, 32
    raw = cm.ps_sparse_bytes(rows, dim)
    assert raw["wire"] == 2 * (4 * rows * dim + 8 * rows)
    q = cm.ps_sparse_bytes(rows, dim, quant="kQI8")
    # row-wise: 1 byte/elem + one f32 scale per row + the int64 ids
    assert q["wire"] == pytest.approx(2 * (rows * dim + 4 * rows + 8 * rows))
    assert q["ratio"] == pytest.approx((4 * dim + 8) / (dim + 4 + 8))


def test_expected_unique_and_bubble():
    # 128 uniform draws from 10k rows: ~127 distinct
    assert cm.expected_unique(10_000, 128) == pytest.approx(127.2, abs=0.5)
    # all rows touched in the limit
    assert cm.expected_unique(50, 10_000) == pytest.approx(50, abs=1e-6)
    assert cm.pipeline_bubble(1, 4) == 0.0
    assert cm.pipeline_bubble(4, 4) == pytest.approx(3 / 7)


# ---------------------------------------------------------------------------
# parameter profiles: structural sparse classification
# ---------------------------------------------------------------------------

def test_param_profiles_classify_sparse_structurally():
    graph, _ = _builder_result(examples.build_ctr_ps)
    plan = analysis.plan_graph(graph, devices=8)
    by_name = {d.name: d for d in plan.params}
    assert by_name["ctr_embed"].sparse          # no is_embed read needed
    assert 0 < by_name["ctr_embed"].density < 0.05
    assert not by_name["ctr_w1"].sparse


# ---------------------------------------------------------------------------
# golden plans (the ISSUE 14 acceptance)
# ---------------------------------------------------------------------------

def test_golden_plan_ctr_hybrid_with_quantized_sparse_legs():
    """The reference-style Hybrid assignment, chosen not declared:
    dense -> AllReduce, sparse embedding -> PS with kQI8."""
    graph, _ = _builder_result(examples.build_ctr_ps)
    plan = analysis.plan_graph(graph, devices=8)
    assert plan.comm_mode == "Hybrid"
    table = next(d for d in plan.params if d.sparse)
    assert table.mode == "PS" and table.quant == "kQI8"
    assert table.wire_ratio > 1.5
    dense = [d for d in plan.params if not d.sparse]
    assert dense and all(d.mode == "AllReduce" for d in dense)


def test_golden_plan_mlp_allreduce():
    graph, _ = _builder_result(examples.build_mlp)
    plan = analysis.plan_graph(graph, devices=8)
    assert plan.comm_mode == "AllReduce"
    assert plan.mesh == {"dp": 8, "tp": 1, "pp": 1}
    assert plan.memory["feasible"]
    # quantization respects the hetuq size exemption
    for d in plan.params:
        if d.size_elems < 2048:
            assert d.quant is None
        else:
            assert d.quant == "int8"
    assert plan.comm_quant == "int8"


def test_golden_plan_transformer_builds_and_is_feasible():
    graph, _ = _builder_result(examples.build_transformer)
    plan = analysis.plan_graph(graph, devices=8)
    assert plan.mesh is not None and plan.comm_mode == "AllReduce"
    assert plan.predicted_step_ms > 0


def test_single_device_plans_local():
    graph, _ = _builder_result(examples.build_mlp)
    plan = analysis.plan_graph(graph, devices=1)
    assert plan.comm_mode is None
    assert all(d.mode == "local" for d in plan.params)


# ---------------------------------------------------------------------------
# HBM gate: infeasible mesh never chosen; ZeRO-1/remat fallback
# ---------------------------------------------------------------------------

def _big_graph():
    x = ht.Variable(name="big_x", value=np.zeros((32, 4096), np.float32),
                    trainable=False)
    w = ht.init.random_normal((4096, 65536), stddev=0.02, name="big_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    train = ht.optim.AdamOptimizer(1e-3).minimize(loss)
    return {"train": [loss, train]}


def test_hbm_overflow_adopts_zero1():
    # param 1.07 GiB + Adam slots 2.15 + grad 1.07: plain layout ~4.3 GiB
    # overflows a 3 GiB budget; ZeRO-1 shards slots /8 -> fits
    plan = analysis.plan_graph(
        _big_graph(), devices=8,
        cost_config=cm.CostModelConfig(hbm_budget_gb=3.0))
    assert plan.mesh is not None
    assert plan.zero1
    assert plan.memory["feasible"]
    assert plan.memory["peak_gib"] <= 3.0
    assert any(f.lint == "plan-memory" for f in plan.findings())


def test_hbm_overflow_adopts_remat_after_zero1():
    # squeeze the budget just below the ZeRO-1-only peak (read from the
    # model's own projection) so remat must join to fit
    g = _big_graph()
    with_zero1 = analysis.plan_graph(
        g, devices=8, cost_config=cm.CostModelConfig(hbm_budget_gb=3.0))
    assert with_zero1.zero1 and not with_zero1.remat
    z_peak = with_zero1.memory["peak_gib"]
    plan = analysis.plan_graph(
        g, devices=8,
        cost_config=cm.CostModelConfig(hbm_budget_gb=z_peak - 1e-4))
    assert plan.mesh is not None and plan.zero1 and plan.remat
    assert plan.memory["feasible"]


def test_hbm_infeasible_never_chosen():
    plan = analysis.plan_graph(
        _big_graph(), devices=8,
        cost_config=cm.CostModelConfig(hbm_budget_gb=0.5))
    assert plan.mesh is None
    assert all(not c.feasible for c in plan.candidates)
    fs = plan.findings()
    assert any(f.lint == "plan-infeasible" and f.severity == "error"
               for f in fs)


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def test_calibration_shifts_prediction_toward_measured():
    graph, _ = _builder_result(examples.build_mlp)
    base = analysis.plan_graph(graph, devices=1)
    base_ms = base.predicted_step_ms
    measured = base_ms * 3.0 + 0.5
    cal = analysis.Calibration(
        legs_ms={"compute": measured, "feed": 0.2, "poststep": 0.1})
    shifted = analysis.plan_graph(graph, devices=1, calibrate=cal)
    assert shifted.predicted_step_ms > base_ms
    # the calibrated prediction lands at measured work + measured host
    assert shifted.predicted_step_ms == pytest.approx(measured + 0.3,
                                                      rel=1e-6)


def test_load_calibration_from_roofline_json(tmp_path):
    doc = {"kind": "roofline", "peak_tflops": 197.0, "peak_gbs": 819.0,
           "rows": [{"family": "MatMul", "predicted_us": 10.0,
                     "measured_us": 30.0, "residual": 3.0},
                    {"family": "Relu", "residual": None}]}
    p = tmp_path / "roofline.json"
    p.write_text(json.dumps(doc))
    cal = analysis.load_calibration(str(p))
    assert cal.family_residual == {"MatMul": 3.0}
    # and a telemetry DIR containing the same file also picks it up
    d = tmp_path / "tel"
    d.mkdir()
    (d / "roofline_mlp.json").write_text(json.dumps(doc))
    cal2 = analysis.load_calibration(str(d))
    assert cal2.family_residual == {"MatMul": 3.0}


def test_calibration_baseline_makes_residual_a_ratio():
    graph, _ = _builder_result(examples.build_mlp)
    base = analysis.plan_graph(graph, devices=1)
    comp = base.breakdown["compute_ms"]
    cal = analysis.Calibration(legs_ms={"compute": comp * 2.0},
                               baseline_compute_ms=comp)
    plan = analysis.plan_graph(graph, devices=1, calibrate=cal)
    assert plan.breakdown["compute_ms"] == pytest.approx(comp * 2.0,
                                                         rel=1e-6)


# ---------------------------------------------------------------------------
# satellite: rows-route abstract tracing (PR-12 IndexedRows)
# ---------------------------------------------------------------------------

def test_rows_route_abstract_eval_end_to_end():
    from hetu_tpu.analysis.abstract import AbstractGraph
    from hetu_tpu.graph.node import find_topo_sort
    from hetu_tpu.graph.ops.embedding import IndexedRows

    graph, _ = _builder_result(examples.build_ctr_ps_rows)
    nodes = [n for ns in graph.values() for n in ns]
    topo = find_topo_sort(nodes)
    grad = next(n for n in topo
                if getattr(n, "opname", None) == "EmbeddingLookUpGradient")
    # dense mode: table-shaped struct
    ag = AbstractGraph(topo, target="train").evaluate()
    assert tuple(ag.meta[id(grad)].shape) == (10000, 8)
    # rows mode (the executor's PS rewire): IndexedRows of structs, the
    # downstream push still evaluates (meta None), no failures anywhere
    grad.to_rows()
    try:
        ag2 = AbstractGraph(topo, target="train").evaluate()
        meta = ag2.meta[id(grad)]
        assert isinstance(meta, IndexedRows)
        n = int(meta.rows.shape[0])
        assert meta.grads.shape == (n, 8)
        assert not ag2.failures
        push = next(n2 for n2 in topo if getattr(n2, "ps_id", None))
        assert id(push) in ag2.meta and ag2.meta[id(push)] is None
    finally:
        grad.to_dense()


def test_rows_route_plans_ps():
    graph, _ = _builder_result(examples.build_ctr_ps_rows)
    plan = analysis.plan_graph(graph, devices=8)
    assert plan.comm_mode == "PS"
    d = plan.params[0]
    assert d.mode == "PS" and d.quant == "kQI8"
    # the lookup and the explicit grad push share ONE index tensor: the
    # 128 lookups/step must not double-count to 256
    assert d.touched_rows == pytest.approx(
        cm.expected_unique(10_000, 128), rel=1e-6)


def test_ps_offload_rescues_hbm_at_dp_gt_1():
    """A dense-ish sparse table AllReduce would keep on-device still
    offloads to PS when that is the only way the candidate fits the HBM
    gate — the escalation is not a no-op at dp>1."""
    # high-density table (vocab 4096 fully touched) + budget sized so the
    # layout only fits with the table server-side
    x_idx = ht.Variable(name="off_idx",
                        value=np.zeros((4096, 8), np.int64),
                        trainable=False)
    table = ht.init.random_normal((4096, 65536), stddev=0.02,
                                  name="off_table")
    look = ht.embedding_lookup_op(table, x_idx)
    loss = ht.reduce_mean_op(look, [0, 1, 2])
    train = ht.optim.SGDOptimizer(0.1).minimize(loss)
    graph = {"train": [loss, train]}
    generous = analysis.plan_graph(
        graph, devices=8, cost_config=cm.CostModelConfig(hbm_budget_gb=64))
    d = next(p for p in generous.params if p.sparse)
    assert d.density == pytest.approx(1.0, abs=0.01)
    assert d.mode == "AllReduce"        # wire-wise AR wins at density 1
    tight = analysis.plan_graph(
        graph, devices=8,
        cost_config=cm.CostModelConfig(hbm_budget_gb=0.8))
    assert tight.mesh is not None
    d2 = next(p for p in tight.params if p.sparse)
    assert d2.mode == "PS" and "offload" in d2.reason
    assert tight.memory["feasible"]


def test_hetu_plan_env_off_values_disable(monkeypatch):
    x = ht.Variable(name="pe_x", trainable=False)
    w = ht.init.random_normal((4, 2), stddev=0.1, name="pe_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    monkeypatch.setenv("HETU_PLAN", "off")
    ex = ht.Executor([loss])
    assert ex.plan is None
    monkeypatch.setenv("HETU_PLAN", "auto")
    ex2 = ht.Executor([loss])
    assert ex2.plan is not None


# ---------------------------------------------------------------------------
# divergence + apply
# ---------------------------------------------------------------------------

def test_plan_divergence_warn_fires_on_contradicting_config():
    graph, _ = _builder_result(examples.build_ctr_ps)
    cfg = analysis.AnalysisConfig(comm_mode="AllReduce")
    plan = analysis.plan_graph(graph, config=cfg, devices=8)
    fs = plan.findings(config=cfg)
    divs = [f for f in fs if f.lint == "plan-divergence"]
    assert divs and divs[0].severity == "warn"
    assert "AllReduce" in divs[0].message and "Hybrid" in divs[0].message
    # and a matching config stays silent
    ok_cfg = analysis.AnalysisConfig(comm_mode="Hybrid")
    assert not [f for f in plan.findings(config=ok_cfg)
                if f.lint == "plan-divergence"]


def test_plan_apply_fills_unset_fields_only():
    graph, _ = _builder_result(examples.build_ctr_ps)
    plan = analysis.plan_graph(graph, devices=8)
    cfg = analysis.AnalysisConfig()           # nothing declared
    plan.apply(cfg)
    assert cfg.comm_mode == "Hybrid"
    assert cfg.comm_quant_policy.active
    assert cfg.plan_adopted is plan
    declared = analysis.AnalysisConfig(comm_mode="PS")
    plan.apply(declared)
    assert declared.comm_mode == "PS"         # never overridden


def test_plan_device_group_tuple_syntax():
    from hetu_tpu.context import mesh_device_group
    g = mesh_device_group(2, 2, device="cpu")
    assert g.is_mp and g.worker_num == 2 and g.mp_device_num == 4
    flat = mesh_device_group(4, 1, device="cpu")
    assert not flat.is_mp and len(flat) == 4
    with pytest.raises(ValueError):
        mesh_device_group(0, 1)


def test_executor_adopts_auto_plan_and_trains():
    x = ht.Variable(name="pa_x", trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.1, name="pa_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    train = ht.optim.SGDOptimizer(0.1).minimize(loss)
    import jax
    ex = ht.Executor([loss, train], plan="auto")
    assert ex.plan is not None
    assert ex.plan.mesh is not None
    if len(jax.devices()) > 1:
        # the test matrix's virtual CPU mesh: dp sync adopted
        assert ex.config.comm_mode == "AllReduce"
        assert ex.plan.mesh["dp"] == len(jax.devices())
    else:
        # one device: nothing to synchronize
        assert ex.config.comm_mode is None
    out = ex.run("default", feed_dict={x: np.ones((4, 8), np.float32)})
    assert np.isfinite(float(np.asarray(out[0].asnumpy())))


def test_executor_rejects_bad_plan_value():
    x = ht.Variable(name="pb_x", trainable=False)
    w = ht.init.random_normal((4, 2), stddev=0.1, name="pb_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    with pytest.raises(ValueError, match="plan"):
        ht.Executor([loss], plan="frobnicate")


# ---------------------------------------------------------------------------
# satellite: replicated-threshold resolution
# ---------------------------------------------------------------------------

def test_replicated_threshold_resolution(monkeypatch):
    from hetu_tpu.analysis.lowered import resolve_replicated_threshold
    assert resolve_replicated_threshold(None) == 64 << 20
    cfg = analysis.AnalysisConfig(replicated_threshold_bytes=1234)
    assert resolve_replicated_threshold(cfg) == 1234
    monkeypatch.setenv("HETU_REPLICATED_THRESHOLD_BYTES", "4096")
    assert resolve_replicated_threshold(None) == 4096
    # explicit config still wins over env
    assert resolve_replicated_threshold(cfg) == 1234


# ---------------------------------------------------------------------------
# CLI smokes
# ---------------------------------------------------------------------------

def _cli_env():
    return dict(os.environ, JAX_PLATFORMS="cpu",
                PYTHONPATH=REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""))


def test_hetulint_plan_json_ci_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--plan",
         "--json", "--devices", "8",
         "hetu_tpu.analysis.examples:build_ctr_ps",
         "hetu_tpu.analysis.examples:build_mlp"],
        capture_output=True, text=True, env=_cli_env(), cwd=REPO,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] and len(report["results"]) == 2
    ctr = report["results"][0]["plan"]
    assert ctr["comm_mode"] == "Hybrid"
    assert ctr["mesh"] == {"dp": 8, "tp": 1, "pp": 1}
    table = next(p for p in ctr["params"] if p["sparse"])
    assert table["mode"] == "PS" and table["quant"] == "kQI8"
    # the declared PS config contradicts the Hybrid choice: divergence
    # warn present in the findings, but default --fail-on error passes
    assert any(f["lint"] == "plan-divergence"
               for f in report["results"][0]["findings"])


def test_hetulint_plan_check_ci_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--plan",
         "--check"],
        capture_output=True, text=True, env=_cli_env(), cwd=REPO,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok" in proc.stdout and "FAIL" not in proc.stdout


def test_hetuprof_roofline_json_is_calibration_input(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuprof"),
         "--roofline", "--json", "hetu_tpu.analysis.examples:build_mlp"],
        capture_output=True, text=True, env=_cli_env(), cwd=REPO,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["kind"] == "roofline"
    assert doc["peak_tflops"] > 0
    fams = {r["family"] for r in doc["rows"]}
    assert "MatMul" in fams
    for r in doc["rows"]:
        assert {"family", "predicted_us", "measured_us",
                "residual"} <= set(r)
    # the document round-trips as a --calibrate input (no measured run
    # here, so no residuals — an empty calibration, not an error)
    p = tmp_path / "roofline.json"
    p.write_text(proc.stdout)
    cal = analysis.load_calibration(str(p))
    assert cal.family_residual == {}
