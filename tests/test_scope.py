"""hetuscope (hetu_tpu/telemetry/scope.py + bin/hetuscope,
docs/OBSERVABILITY.md "numeric health"):

- in-graph stats: fused grad norms / update ratios / activation stats
  returned as one extra fetch on the cadence, numerically verified
- NaN/Inf provenance: a seeded ``nan_op`` fault is localized to the exact
  poisoned op (and only it) in the JSONL event AND the hetuscope report —
  the acceptance demo
- introspect off (the default) performs ZERO scope work (mutator-patch
  pattern from test_telemetry) and compiles no stats variant
- flight recorder: valid + complete after a SIGTERM'd child run
- satellites: clip_grad_norm (shared global-norm reduction), nan_op spec
  parsing, hetuscope --check CI smoke, hetutop numeric-health panel
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import resilience as rs  # noqa: E402
from hetu_tpu.telemetry import scope as scope_mod  # noqa: E402
from hetu_tpu.graph.executor import _op_scope  # noqa: E402


@pytest.fixture
def fresh(tmp_path, monkeypatch):
    """Isolated telemetry + scope singletons and a tmp output dir."""
    from hetu_tpu import telemetry
    telemetry.shutdown()
    scope_mod.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_INTROSPECT", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    yield str(tmp_path / "tel")
    telemetry.shutdown()
    scope_mod.shutdown()


def build_job(tmp=None, seed=0, introspect=5, telemetry=None,
              anomaly_guard=True, clip=None, lr=0.1):
    """Feed-fed 2-layer softmax job (deterministic); returns
    (executor, run_closure, feed arrays)."""
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.5, name="w")
    b = ht.init.zeros((4,), name="b")
    h = ht.matmul_op(x, w)
    logits = h + ht.broadcastto_op(b, h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    opt = ht.optim.SGDOptimizer(lr, clip_grad_norm=clip)
    train_op = opt.minimize(loss)
    kw = {}
    if telemetry is not None:
        kw["telemetry"] = telemetry
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=seed,
                     anomaly_guard=anomaly_guard, introspect=introspect,
                     **kw)
    rng = np.random.RandomState(7)
    bx = rng.randn(16, 8).astype(np.float32)
    by = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 16)]

    def run():
        return ex.run("train", feed_dict={x: bx, y_: by})

    return ex, run, (bx, by)


# ---------------------------------------------------------------------------
# config resolution + spec parsing
# ---------------------------------------------------------------------------

def test_resolve_introspect_modes(monkeypatch):
    monkeypatch.delenv("HETU_INTROSPECT", raising=False)
    monkeypatch.delenv("HETU_INTROSPECT_EVERY", raising=False)
    assert scope_mod.resolve_introspect(None) == 0
    assert scope_mod.resolve_introspect(False) == 0
    assert scope_mod.resolve_introspect("off") == 0
    assert scope_mod.resolve_introspect(True) == scope_mod.DEFAULT_CADENCE
    assert scope_mod.resolve_introspect("on") == scope_mod.DEFAULT_CADENCE
    assert scope_mod.resolve_introspect(3) == 3
    assert scope_mod.resolve_introspect("7") == 7
    assert scope_mod.resolve_introspect(1) == 1   # int 1 = every step
    monkeypatch.setenv("HETU_INTROSPECT", "1")    # env "1" = on @ default
    assert scope_mod.resolve_introspect(None) == scope_mod.DEFAULT_CADENCE
    monkeypatch.setenv("HETU_INTROSPECT_EVERY", "4")
    assert scope_mod.resolve_introspect("on") == 4
    with pytest.raises(ValueError):
        scope_mod.resolve_introspect("sometimes")
    for bad in (-5, "-5"):   # the string path validates like the int path
        with pytest.raises(ValueError):
            scope_mod.resolve_introspect(bad)


def test_json_num_strict_serialization():
    assert scope_mod.json_num(float("nan")) == "NaN"
    assert scope_mod.json_num(float("inf")) == "Infinity"
    assert scope_mod.json_num(float("-inf")) == "-Infinity"
    assert scope_mod.json_num(1.5) == 1.5
    assert scope_mod.json_num("MatMul_4") == "MatMul_4"   # non-numeric kept
    safe = scope_mod.json_safe({"a": [float("nan"), 2.0],
                                "b": {"c": float("inf")}})
    assert safe == {"a": ["NaN", 2.0], "b": {"c": "Infinity"}}
    assert float(scope_mod.json_num(float("nan"))) != \
        float(scope_mod.json_num(float("nan")))   # float() round-trip = NaN


def test_nan_op_fault_spec_keeps_string_arg():
    fi = rs.FaultInjector("nan_op@3:MatMul_4, nan_op@5, stall@7:2.5")
    e = fi.take("nan_op", 3)
    assert e["arg"] == "MatMul_4"          # op name stays a string
    e2 = fi.take("nan_op", 5)
    assert e2["arg"] is None               # default op
    assert fi.take("stall", 7)["arg"] == 2.5   # numeric args unchanged


def test_supervisor_poison_op_consumes_entry():
    sup = rs.Supervisor(fault_injector=rs.FaultInjector("nan_op@2:Foo"))
    assert sup.poison_op(1) is None
    assert sup.poison_op(2) == "Foo"
    assert sup.poison_op(2) is None        # one-shot
    sup2 = rs.Supervisor(fault_injector=rs.FaultInjector("nan_op@0"))
    assert sup2.poison_op(0) == ""         # "" = executor default op


# ---------------------------------------------------------------------------
# in-graph stats
# ---------------------------------------------------------------------------

def test_stats_numerically_consistent(fresh):
    """grad_norm is the root-sum-square of the per-param norms, and the
    SGD update/param ratio equals lr * grad_norm(w) / ||w|| exactly."""
    lr = 0.1
    ex, run, _ = build_job(introspect=1, lr=lr)
    w_node = [n for n in ex.param_nodes if n.name == "w"][0]
    w_pre = np.asarray(ex.state["params"][id(w_node)]).copy()
    run()
    stats = ex.introspector.last_stats
    assert stats is not None
    params = stats["params"]
    assert set(params) == {"w", "b"}
    rss = np.sqrt(sum(d["grad_norm"] ** 2 for d in params.values()))
    assert stats["grad_norm"] == pytest.approx(rss, rel=1e-5)
    # SGD: ||delta w|| = lr * ||grad w||
    expect = lr * params["w"]["grad_norm"] / np.linalg.norm(w_pre)
    assert params["w"]["update_ratio"] == pytest.approx(expect, rel=1e-4)
    # zero-init bias: ratio is NaN (undefined), not a 1e10 artifact
    assert np.isnan(params["b"]["update_ratio"])
    # activation table keyed by named_scope identity, all finite
    assert any(k.startswith("MatMul") for k in stats["ops"])
    assert all(d["nonfinite"] == 0.0 for d in stats["ops"].values())
    assert stats["loss"] == pytest.approx(
        float(np.asarray(run()[0].asnumpy())), rel=0.5)  # same ballpark


def test_cadence_gates_stats_and_variants(fresh):
    """Stats ride only every Nth step; the stats program is a second
    compile of the SAME shape signature (no recompile churn)."""
    ex, run, _ = build_job(introspect=3)
    sub = ex.subexecutors["train"]
    fr = ex.introspector.flight
    for _ in range(7):   # steps 0..6; stats at 0, 3, 6
        run()
    # cadence fetches are deferred one boundary; reading last_stats
    # resolves the final pending one into its ring record
    assert ex.introspector.last_stats is not None
    recs = fr.records()
    assert [r["step"] for r in recs] == list(range(7))
    assert [("stats" in r) for r in recs] == [
        True, False, False, True, False, False, True]
    assert len(sub._compiled) == 2       # plain + stats variant
    assert len(sub._base_sigs) == 1      # ONE shape signature
    from hetu_tpu.analysis.lowered import recompile_findings
    assert recompile_findings(sub, budget=1) == []   # variants != churn


def test_clip_grad_norm_bounds_the_update(fresh):
    """With clip C << grad norm, the global update norm is exactly lr*C,
    and the introspection grad_norm reuses the clip's PRE-clip reduction."""
    lr, C = 0.1, 0.05
    ex, run, _ = build_job(introspect=1, clip=C, lr=lr)
    pre = {n.name: np.asarray(ex.state["params"][id(n)]).copy()
           for n in ex.param_nodes}
    run()
    post = {n.name: np.asarray(ex.state["params"][id(n)])
            for n in ex.param_nodes}
    upd = np.sqrt(sum(np.sum((post[k] - pre[k]) ** 2) for k in pre))
    gnorm = ex.introspector.last_stats["grad_norm"]
    assert gnorm > C                      # clip engaged
    assert upd == pytest.approx(lr * C, rel=1e-4)
    # unclipped twin from the same seed: same direction, scaled grads
    from hetu_tpu import telemetry
    telemetry.shutdown()
    scope_mod.shutdown()
    ex2, run2, _ = build_job(introspect=0, lr=lr)
    pre2 = {n.name: np.asarray(ex2.state["params"][id(n)]).copy()
            for n in ex2.param_nodes}
    run2()
    post2 = {n.name: np.asarray(ex2.state["params"][id(n)])
             for n in ex2.param_nodes}
    scale = C / gnorm
    for k in pre:
        np.testing.assert_allclose(post[k] - pre[k],
                                   (post2[k] - pre2[k]) * scale,
                                   rtol=1e-4, atol=1e-7)


def test_clip_rejects_nonpositive():
    with pytest.raises(ValueError, match="clip_grad_norm"):
        ht.optim.SGDOptimizer(0.1, clip_grad_norm=0.0)


# ---------------------------------------------------------------------------
# NaN/Inf provenance (the acceptance demo)
# ---------------------------------------------------------------------------

def test_provenance_localizes_poisoned_op(fresh):
    """nan_op poisons one mid-graph op; the guard trips, the replay names
    exactly that op in intro.last_provenance, the JSONL nan_provenance
    event, and the bin/hetuscope report — and the anomaly event carries
    the at-trip loss (satellite: enriched payload)."""
    ex, run, _ = build_job(introspect=5, telemetry="metrics")
    sub = ex.subexecutors["train"]
    target = _op_scope([n for n in sub.topo if "MatMul" in n.name][0])
    sup = ex.attach_supervisor(rs.Supervisor(
        fault_injector=rs.FaultInjector(f"nan_op@2:{target}")))
    with sup:
        for step in range(4):
            pre = {n.name: np.asarray(ex.state["params"][id(n)]).copy()
                   for n in ex.param_nodes}
            run()
            if step == 2:   # guard skipped the poisoned step bit-identically
                for n in ex.param_nodes:
                    np.testing.assert_array_equal(
                        pre[n.name], np.asarray(ex.state["params"][id(n)]))
    prov = ex.introspector.last_provenance
    assert prov is not None and prov["op"] == target
    assert prov["step"] == 2
    assert prov["output"]["nonfinite"] == 1.0
    assert all(v["nonfinite"] == 0.0 for v in prov["inputs"].values())
    assert prov["nonfinite_ops"] > 1      # downstream propagation seen...
    # ...but ONLY the poisoned op is named as the culprit
    # step 2 was off-cadence -> the debug replay (no donation) ran
    assert len(sub._replay_compiled) == 1

    from hetu_tpu import telemetry
    telemetry.get().flush()
    recs = [json.loads(l) for l in
            open(os.path.join(fresh, "metrics-r0.jsonl"))]
    evs = [r for r in recs if r.get("kind") == "event"
           and r.get("name") == "nan_provenance"]
    assert len(evs) == 1 and evs[0]["op"] == target
    anomalies = [r for r in recs if r.get("kind") == "event"
                 and r.get("name") == "anomaly"]
    assert anomalies and "loss" in anomalies[0]   # enriched payload
    # non-finite values serialize as strings so the JSONL stays STRICT
    # JSON (jq-parseable) — float() round-trips them
    assert anomalies[0]["loss"] == "NaN"
    assert np.isnan(float(anomalies[0]["loss"]))
    # every line of the whole stream parses under a strict decoder
    import math as _math
    strict = json.JSONDecoder(parse_constant=lambda c: (_ for _ in ()).throw(
        ValueError(f"non-strict constant {c}")))
    for l in open(os.path.join(fresh, "metrics-r0.jsonl")):
        strict.decode(l)

    # the CLI report names the op (real subprocess, jax-free load path)
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuscope"), fresh],
        env=env, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert f"first non-finite op (topological order): {target}" in out.stdout


def test_provenance_on_cadence_step_skips_replay(fresh):
    """A trip on a stats step localizes from that step's own fused table —
    no debug replay compile."""
    ex, run, _ = build_job(introspect=1)
    sub = ex.subexecutors["train"]
    target = _op_scope([n for n in sub.topo if "MatMul" in n.name][0])
    sup = ex.attach_supervisor(rs.Supervisor(
        fault_injector=rs.FaultInjector(f"nan_op@1:{target}")))
    with sup:
        run()
        run()
    assert ex.introspector.last_provenance["op"] == target
    assert sub._replay_compiled == {}


def test_nan_grads_injection_has_no_op_culprit(fresh):
    """The update-level nan_grads poison never flows through an op output:
    provenance reports op=None with the explanatory note."""
    ex, run, _ = build_job(introspect=5)
    sup = ex.attach_supervisor(rs.Supervisor(
        fault_injector=rs.FaultInjector("nan_grads@1")))
    with sup:
        run()
        run()
    prov = ex.introspector.last_provenance
    assert prov is not None and prov["op"] is None
    assert "no op-level culprit" in prov["note"]


# ---------------------------------------------------------------------------
# off-mode: zero scope work
# ---------------------------------------------------------------------------

def test_off_mode_adds_zero_scope_work(fresh, monkeypatch):
    """With introspect off (the default), a training step performs no
    flight-ring appends, no stats builds, no exports — counted by patching
    every scope-layer mutator — and compiles no stats variant."""
    calls = []
    monkeypatch.setattr(scope_mod.FlightRecorder, "record",
                        lambda self, rec: calls.append(("flight", rec)))
    monkeypatch.setattr(scope_mod.FlightRecorder, "flush",
                        lambda self, reason, provenance=None:
                        calls.append(("flush", reason)))
    monkeypatch.setattr(scope_mod, "traced_stats",
                        lambda *a, **k: calls.append(("stats",)) or ())
    monkeypatch.setattr(scope_mod, "host_stats",
                        lambda *a: calls.append(("host",)) or {})
    ex, run, _ = build_job(introspect=None)   # env cleared by fixture
    assert ex.introspector is None
    assert ex.config.introspect == 0
    for _ in range(3):
        run()
    assert calls == []
    assert len(ex.subexecutors["train"]._compiled) == 1


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_bounded_and_flush_atomic(tmp_path):
    fr = scope_mod.FlightRecorder(str(tmp_path / "flight"), rank=0, k=4)
    for i in range(10):
        fr.record({"step": i})
    recs = fr.records()
    assert [r["step"] for r in recs] == [6, 7, 8, 9]   # last K only
    path = fr.flush("test")
    doc = json.load(open(path))
    assert doc["schema"] == scope_mod.FLIGHT_SCHEMA
    assert doc["reason"] == "test" and len(doc["records"]) == 4
    assert not os.path.exists(path + ".tmp")


def test_flight_recorder_complete_after_sigterm_child(tmp_path):
    """A SIGTERM'd supervised run leaves a valid, complete flight dir: the
    preemption path flushes the ring before Preempted exits the process
    (exit 75)."""
    tel_dir = str(tmp_path / "tel")
    script = textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        os.environ.update({"HETU_TEST_MODE": "1",
                           "HETU_TELEMETRY_DIR": %r})
        import numpy as np
        import hetu_tpu as ht
        from hetu_tpu import resilience as rs
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        w = ht.init.random_normal((6, 3), stddev=0.5, name="w")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         seed=0, introspect=2)
        sup = ex.attach_supervisor(rs.Supervisor(
            preemption=rs.PreemptionHandler(),
            fault_injector=rs.FaultInjector("sigterm@3")))
        rng = np.random.RandomState(0)
        bx = rng.randn(8, 6).astype(np.float32)
        by = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 8)]

        def loop(state, start):
            with sup:
                for _ in range(start, 10):
                    ex.run("train", feed_dict={x: bx, y_: by})
        rs.supervise(loop, None)
        print("FINISHED")   # must never be reached
    """ % (REPO, tel_dir))
    p = tmp_path / "sigterm_job.py"
    p.write_text(script)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    proc = subprocess.run([sys.executable, str(p)], capture_output=True,
                          text=True, timeout=240, env=env,
                          cwd=str(tmp_path))
    assert proc.returncode == rs.EXIT_PREEMPTED, (proc.stdout, proc.stderr)
    assert "FINISHED" not in proc.stdout
    fpath = os.path.join(tel_dir, "flight", "flight-r0.json")
    doc = json.load(open(fpath))
    assert doc["reason"] == "preempted"
    steps = [r for r in doc["records"] if "step" in r]
    # steps 0..3 all recorded (step 3 ran; the signal fired at its boundary)
    assert [r["step"] for r in steps] == [0, 1, 2, 3]
    for r in steps:
        assert "batch_crc32" in r and "finite" in r and "step_ms" in r
    assert "stats" in steps[0] and "stats" in steps[2]   # cadence 2
    # the directory validates under the CI checker
    assert scope_mod.check_dir(tel_dir) == 0


# ---------------------------------------------------------------------------
# CLI + dashboards
# ---------------------------------------------------------------------------

def test_hetuscope_check_smoke():
    """bin/hetuscope --check with no dir runs the built-in self-test
    (record -> flush -> validate -> render), exit 0; an empty dir is
    invalid, exit 1 — the hetutop/hetutrace CI pattern."""
    env = {**os.environ, "PYTHONPATH": REPO}
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuscope"), "--check"],
        env=env, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stderr + ok.stdout
    assert "self-test ok" in ok.stdout
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuscope"), "--check",
         "/tmp/definitely-not-a-telemetry-dir"],
        env=env, capture_output=True, text=True)
    assert bad.returncode == 1


def test_scope_metrics_and_hetutop_panel(fresh):
    """Cadence exports land as hetu_scope_* gauges + kind:"scope" JSONL
    rows; hetutop validates them and renders the numeric-health panel."""
    from hetu_tpu import telemetry
    from hetu_tpu.telemetry import hetutop
    ex, run, _ = build_job(introspect=2, telemetry="metrics")
    for _ in range(5):
        run()
    assert ex.introspector.last_stats  # resolve the deferred final fetch
    tel = telemetry.get()
    snap = tel.metrics.snapshot()
    assert snap["hetu_scope_grad_norm"] > 0
    assert snap["hetu_scope_act_absmax"] > 0
    assert snap["hetu_scope_nonfinite_ops"] == 0
    assert snap["hetu_scope_update_ratio_max"] > 0
    tel.flush()
    assert hetutop.check_dir(fresh) == 0
    frame = hetutop.render_frame(hetutop.gather(fresh))
    assert "numeric health (hetuscope)" in frame
    assert "grad_norm" in frame and "nonfinite ops: 0" in frame
    recs = [json.loads(l) for l in
            open(os.path.join(fresh, "metrics-r0.jsonl"))]
    scopes = [r for r in recs if r.get("kind") == "scope"]
    assert len(scopes) == 3               # steps 0, 2, 4
    assert all("params" in r and "ops" in r for r in scopes)


def test_find_culprit_orders_and_notes():
    order = ["a", "b", "c"]
    inputs = {"b": ["a"], "c": ["b"]}
    stats = {"grad_norm": 1.0,
             "ops": {"a": {"absmax": 1.0, "rms": 0.5, "nonfinite": 0.0},
                     "b": {"absmax": 0.0, "rms": 0.0, "nonfinite": 1.0},
                     "c": {"absmax": 0.0, "rms": 0.0, "nonfinite": 0.3}}}
    prov = scope_mod.find_culprit(order, inputs, stats, step=7)
    assert prov["op"] == "b" and prov["nonfinite_ops"] == 2
    assert prov["inputs"]["a"]["nonfinite"] == 0.0
    clean = scope_mod.find_culprit(
        order, inputs, {"ops": {k: {"absmax": 1, "rms": 1, "nonfinite": 0.0}
                                for k in order}}, step=7)
    assert clean["op"] is None and "no op-level culprit" in clean["note"]
