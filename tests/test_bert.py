"""BERT encoder pretraining: bidirectionality, padding-mask correctness,
MLM+NSP training, data-pipeline integration, and dp/tp sharding parity
(single-device oracle vs 8-device mesh — SURVEY.md §4's oracle strategy).
"""
import os
import sys

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hetu_tpu.models import bert
from hetu_tpu.parallel.mesh import auto_mesh

TINY = bert.BertConfig(vocab_size=96, d_model=32, n_heads=4, n_layers=2,
                       d_ff=64, max_seq_len=32, dtype=jnp.float32,
                       remat=False)


def _rand_batch(rng, cfg, B=4, T=16, P=4, pad_from=None):
    ids = rng.randint(3, cfg.vocab_size, (B, T)).astype(np.int32)
    mask = np.ones((B, T), np.int32)
    if pad_from is not None:
        mask[:, pad_from:] = 0
    pos = np.stack([rng.choice(np.arange(1, T if pad_from is None else
                                         pad_from), P, replace=False)
                    for _ in range(B)]).astype(np.int32)
    return {"input_ids": ids, "input_mask": mask,
            "segment_ids": (np.arange(T)[None, :] >= T // 2)
                           .astype(np.int32).repeat(B, 0),
            "mlm_positions": pos,
            "mlm_ids": rng.randint(3, cfg.vocab_size, (B, P)).astype(np.int32),
            "mlm_weights": np.ones((B, P), np.float32),
            "nsp_label": rng.randint(0, 2, (B,)).astype(np.int32)}


def test_encoder_is_bidirectional():
    """A LATER token must change the hidden state at an EARLIER position —
    the defining difference from the causal flagship trunk."""
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    rng = np.random.RandomState(0)
    b = _rand_batch(rng, TINY)
    h1 = bert.encode(params, b["input_ids"], b["segment_ids"], TINY)
    ids2 = b["input_ids"].copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % TINY.vocab_size
    h2 = bert.encode(params, ids2, b["segment_ids"], TINY)
    # earlier positions see the change
    assert float(jnp.max(jnp.abs(h1[:, 0] - h2[:, 0]))) > 1e-6


def test_padding_mask_blocks_pad_keys():
    """Garbage in padded slots must not leak into real positions' outputs."""
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    rng = np.random.RandomState(1)
    b = _rand_batch(rng, TINY, pad_from=10)
    h1 = bert.encode(params, b["input_ids"], b["segment_ids"], TINY,
                     input_mask=b["input_mask"])
    ids2 = b["input_ids"].copy()
    ids2[:, 10:] = 7   # different pad garbage
    h2 = bert.encode(params, ids2, b["segment_ids"], TINY,
                     input_mask=b["input_mask"])
    np.testing.assert_allclose(np.asarray(h1[:, :10]),
                               np.asarray(h2[:, :10]), atol=1e-5)
    # and WITHOUT the mask the garbage does leak (the test is non-vacuous)
    h3 = bert.encode(params, b["input_ids"], b["segment_ids"], TINY)
    h4 = bert.encode(params, ids2, b["segment_ids"], TINY)
    assert float(jnp.max(jnp.abs(h3[:, :10] - h4[:, :10]))) > 1e-6


def test_mlm_nsp_pretrain_loss_decreases():
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    opt = bert.init_opt_state(params)
    step = bert.make_pretrain_step(TINY, lr=3e-3)
    rng = np.random.RandomState(2)
    b = _rand_batch(rng, TINY)   # one fixed batch: must be memorizable
    first = None
    for i in range(40):
        loss, (mlm, nsp), params, opt = step(params, opt, b)
        if i == 0:
            first = float(loss)
    assert np.isfinite(first)
    assert float(loss) < 0.3 * first, (first, float(loss))
    assert float(mlm) >= 0 and float(nsp) >= 0


def test_pipeline_to_pretrain_step():
    """End-to-end: WordPiece tokenizer -> sentence-pair instances -> batch ->
    one fused pretrain step."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "nlp"))
    import processBertData as pbd
    from hetu_tpu.tokenizers import BertTokenizer

    words = ["the", "cat", "sat", "on", "mat", "dog", "ran", "fast",
             "##s", "a"]
    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + words)}
    tok = BertTokenizer(vocab)
    sentences = ["the cat sat on a mat", "a dog ran fast",
                 "the dog sat", "a cat ran", "the mat ran fast"]
    inst = pbd.create_instances_from_document(
        sentences, tok, max_seq_length=24, max_predictions_per_seq=4)
    assert len(inst) >= 2
    cfg = bert.BertConfig(vocab_size=len(vocab), d_model=16, n_heads=2,
                          n_layers=2, d_ff=32, max_seq_len=24,
                          dtype=jnp.float32, remat=False)
    batch = bert.batch_from_instances(inst)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    step = bert.make_pretrain_step(cfg, lr=1e-3)
    loss, (mlm, nsp), params, _ = step(params, bert.init_opt_state(params),
                                       batch)
    assert np.isfinite(float(loss)) and float(mlm) > 0


def test_bert_trainer_example_end_to_end(tmp_path, capsys):
    """examples/nlp/train_hetu_bert.py: corpus -> tokenizer -> instances ->
    pretrain loop -> checkpoint -> RESUME, losses improving."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "nlp"))
    import train_hetu_bert
    ck = str(tmp_path / "ck")
    first = train_hetu_bert.main(["--num-epoch", "3", "--cpu",
                                  "--ckpt-dir", ck])
    resumed = train_hetu_bert.main(["--num-epoch", "6", "--cpu",
                                    "--ckpt-dir", ck, "--resume"])
    out = capsys.readouterr().out
    # the restore branch actually fired and only epochs 3-5 were trained
    assert "resumed from epoch 2" in out
    assert out.count("epoch 0:") == 1   # first run only
    assert np.isfinite(first) and np.isfinite(resumed)
    assert resumed < first   # kept learning across the resume


def test_dp_tp_sharded_step_matches_single_device():
    """BERT-base-shaped step on a dp4 x tp2 mesh == unsharded oracle."""
    mesh = auto_mesh(8, tp=2)
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    opt = bert.init_opt_state(params)
    rng = np.random.RandomState(3)
    b = _rand_batch(rng, TINY, B=8)

    ref_step = bert.make_pretrain_step(TINY, lr=1e-3)
    ref_loss, _, ref_params, _ = ref_step(
        jax.tree.map(jnp.copy, params), jax.tree.map(jnp.copy, opt), b)

    step = bert.make_pretrain_step(TINY, mesh=mesh, lr=1e-3)
    loss, _, new_params, _ = step(params, opt, b)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    # dense packed batch (no input_mask key) must work sharded too — the
    # prefix sharding covers whatever keys the batch has
    dense = {k: v for k, v in _rand_batch(
        np.random.RandomState(4), TINY, B=8).items() if k != "input_mask"}
    dp = bert.init_params(jax.random.PRNGKey(1), TINY)
    dloss, _, _, _ = step(dp, bert.init_opt_state(dp), dense)
    assert np.isfinite(float(dloss))
    for k in ("embed", "mlm_dense", "nsp_w"):
        np.testing.assert_allclose(np.asarray(new_params[k]),
                                   np.asarray(ref_params[k]), atol=1e-5)


def test_fused_mlm_ce_matches_materializing_form():
    """The fused Pallas linear+CE MLM loss (default on the single-program
    path) must equal the logits-materializing einsum form — loss AND
    gradients."""
    import dataclasses
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    b = _rand_batch(np.random.RandomState(11), TINY, B=4)
    on = dataclasses.replace(TINY, fused_mlm_ce=True)   # force off-TPU
    off = dataclasses.replace(TINY, fused_mlm_ce=False)

    lf, (mf, _) = bert.pretrain_loss(params, b, on)
    lo, (mo, _) = bert.pretrain_loss(params, b, off)
    assert float(lf) == pytest.approx(float(lo), rel=1e-5)
    assert float(mf) == pytest.approx(float(mo), rel=1e-5)

    gf = jax.grad(lambda p: bert.pretrain_loss(p, b, on)[0])(params)
    go = jax.grad(lambda p: bert.pretrain_loss(p, b, off)[0])(params)
    for k in ("embed", "mlm_dense", "mlm_bias", "mlm_ln_scale"):
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(go[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_dp_sp_masked_step_matches_single_device():
    """Sequence-parallel BERT: on a dp2 x sp2 x tp2 mesh 'auto' resolves to
    RING attention, and a PADDED batch rides the ring as a rotating per-key
    bias — the sharded masked step must equal the unsharded oracle."""
    from hetu_tpu.models import transformer as tfm

    mesh = auto_mesh(8, sp=2, tp=2)
    assert tfm._resolve_attn_impl(TINY.trunk(), mesh, 16,
                                  jnp.zeros((1, 1, 1, 16))) == "ring"
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    opt = bert.init_opt_state(params)
    rng = np.random.RandomState(7)
    T = 16
    b = _rand_batch(rng, TINY, B=8, T=T, pad_from=12)  # padded tail

    ref_step = bert.make_pretrain_step(TINY, lr=1e-3)
    ref_loss, _, ref_params, _ = ref_step(
        jax.tree.map(jnp.copy, params), jax.tree.map(jnp.copy, opt), b)

    step = bert.make_pretrain_step(TINY, mesh=mesh, lr=1e-3)
    loss, _, new_params, _ = step(params, opt, b)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-4)
    for k in ("embed", "mlm_dense", "nsp_w"):
        np.testing.assert_allclose(np.asarray(new_params[k]),
                                   np.asarray(ref_params[k]), atol=1e-4)


def test_finetune_classifier_from_pretrained_trunk():
    """Pretrain briefly, transplant the trunk into a classifier, fine-tune
    on a separable task (label = does the sequence contain token 5): the
    classifier must fit it; the MLM/NSP heads are gone from the task
    params."""
    params = bert.init_params(jax.random.PRNGKey(0), TINY)
    opt = bert.init_opt_state(params)
    pstep = bert.make_pretrain_step(TINY, lr=1e-3)
    rng = np.random.RandomState(5)
    for _ in range(3):
        _, _, params, opt = pstep(params, opt, _rand_batch(rng, TINY))

    cparams = bert.init_classifier_params(jax.random.PRNGKey(1), TINY,
                                          n_classes=2, pretrained=params)
    assert "mlm_bias" not in cparams and "nsp_w" not in cparams
    assert "cls_w" in cparams and "blocks" in cparams

    B, T = 16, 16
    ids = rng.randint(6, TINY.vocab_size, (B, T)).astype(np.int32)
    ids[: B // 2, rng.randint(1, T)] = 5          # positives contain token 5
    labels = (ids == 5).any(1).astype(np.int32)
    batch = {"input_ids": ids,
             "segment_ids": np.zeros((B, T), np.int32),
             "label": labels}
    fstep = bert.make_finetune_step(TINY, lr=3e-3)
    copt = bert.init_opt_state(cparams)
    for i in range(60):
        loss, acc, cparams, copt = fstep(cparams, copt, batch)
    assert float(acc) == 1.0, (float(loss), float(acc))
    # donation of the task params must NOT have invalidated the pretrained
    # tree (init_classifier_params deep-copies reused leaves)
    h = bert.encode(params, batch["input_ids"], batch["segment_ids"], TINY)
    assert np.isfinite(float(jnp.sum(h)))
