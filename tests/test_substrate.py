"""hetucheck (Tier D substrate analysis): seeded-defect tests — one per
check family, each asserting the lint fires on a counterfactual tree and
stays silent on the shipped one — plus the `bin/hetucheck` CLI smoke that
doubles as the tier-1 guard that the working tree is drift-free.

The flagship fixtures reproduce real history: the pre-fix PR 16 ABBA
deadlock (dispatch held ClientSlot::mu across handle() into take_snapshot,
which takes PsServer::snap_take_mu_ then re-locks slots) must be detected
with both mutexes and both acquisition sites named, and a kServerStats
slot-count change must be caught before any Python unpacker mis-slices."""
import json
import os
import subprocess
import sys

import pytest

from hetu_tpu import faults
from hetu_tpu.analysis.substrate import (analyze_drift, analyze_locks,
                                         analyze_surface, build_model)
from hetu_tpu.analysis.substrate import cli as subcli
from hetu_tpu.ps import wire_constants as wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVER_H = "hetu_tpu/csrc/ps/server.h"


def lints_of(findings, lint):
    return [f for f in findings if f.lint == lint]


def read(rel):
    with open(os.path.join(REPO, rel), "r", encoding="utf-8") as f:
        return f.read()


# --------------------------------------------------------------------------
# lock-order family


def test_abba_fixture_detected_with_both_mutexes_and_sites():
    """PR 16's pre-fix deadlock: the cycle error must name BOTH mutexes
    and BOTH acquisition sites so the report is actionable."""
    model = build_model([("fixture/server_prefix.h", subcli._ABBA_FIXTURE)])
    cycles = lints_of(analyze_locks(model), "lock-order-cycle")
    assert len(cycles) == 1
    msg = cycles[0].message
    assert cycles[0].severity == "error"
    assert "ClientSlot::mu" in msg
    assert "PsServer::snap_take_mu_" in msg
    assert msg.count("server_prefix.h:") >= 2          # both sites
    assert "take_snapshot" in msg and "serve_conn" in msg


def test_release_across_call_fixture_is_clean():
    """The shipped fix (drop the slot lock before handle()) must NOT be
    flagged — the analyzer models the release-across-call pattern."""
    model = build_model([("fixture/server_fixed.h", subcli._FIXED_FIXTURE)])
    assert not lints_of(analyze_locks(model), "lock-order-cycle")


_BLOCKING_FIXTURE = """
#include <mutex>
class Conn {
 public:
  void send(int fd) {
    std::lock_guard<std::mutex> g(send_mu_);
    send_msg(fd);
  }
 private:
  std::mutex send_mu_;
};
"""


def test_lock_across_blocking_fixture():
    model = build_model([("fixture/conn.h", _BLOCKING_FIXTURE)])
    warns = lints_of(analyze_locks(model), "lock-across-blocking")
    assert len(warns) == 1
    assert "Conn::send_mu_" in warns[0].message
    assert "send_msg" in warns[0].message


_ATOMIC_FIXTURE = """
#include <atomic>
#include <mutex>
class Store {
 public:
  void bump_unlocked() {
    version_ = 1;
  }
  void bump_locked() {
    std::lock_guard<std::mutex> g(mu_);
    version_ = 2;
  }
 private:
  std::mutex mu_;
  std::atomic<long> version_{0};
};
"""


def test_atomic_mixed_guard_fixture():
    model = build_model([("fixture/store.h", _ATOMIC_FIXTURE)])
    notes = lints_of(analyze_locks(model), "atomic-mixed-guard")
    assert len(notes) == 1
    assert "Store::version_" in notes[0].message


def test_shipped_headers_have_no_lock_order_cycle():
    """The post-PR16 tree must be deadlock-free under the analyzer."""
    paths = [os.path.join(REPO, h) for h in subcli.HEADERS]
    model = build_model(paths)
    assert not lints_of(analyze_locks(model), "lock-order-cycle")


# --------------------------------------------------------------------------
# cross-language drift family (all via overlay — disk is never touched)


def test_slot_count_drift_fixture():
    """Growing kServerStats by one slot in C++ must fail the mirror."""
    text = read(SERVER_H)
    assert "int64_t stats[11]" in text
    overlay = {SERVER_H: text.replace("int64_t stats[11]",
                                      "int64_t stats[12]")}
    errs = lints_of(analyze_drift(REPO, overlay=overlay),
                    "slot-count-drift")
    assert any("kServerStats" in f.message and "12" in f.message
               for f in errs)


def test_enum_drift_fixture():
    net = read("hetu_tpu/csrc/ps/net.h")
    assert "kTestSlowApply = 70" in net
    overlay = {"hetu_tpu/csrc/ps/net.h":
               net.replace("kTestSlowApply = 70", "kTestSlowApply = 71")}
    errs = lints_of(analyze_drift(REPO, overlay=overlay), "enum-drift")
    assert any("kTestSlowApply" in f.message for f in errs)


def test_dispatch_drift_fixture():
    server = read(SERVER_H)
    assert "case PsfType::kSnapshotNow:" in server
    overlay = {SERVER_H: server.replace("case PsfType::kSnapshotNow:", "")}
    errs = lints_of(analyze_drift(REPO, overlay=overlay),
                    "psf-dispatch-drift")
    assert any("kSnapshotNow" in f.message for f in errs)


def test_capi_unbound_fixture():
    rel = "hetu_tpu/ps/client.py"
    overlay = {rel: read(rel) + "\n_lib.DefinitelyMissingSymbol(0)\n"}
    errs = lints_of(analyze_drift(REPO, overlay=overlay), "capi-unbound")
    assert any("DefinitelyMissingSymbol" in f.message for f in errs)


def test_wire_import_drift_fixture():
    rel = "hetu_tpu/elastic.py"
    gutted = read(rel).replace("wire_constants", "wire_consts_gone")
    errs = lints_of(analyze_drift(REPO, overlay={rel: gutted}),
                    "wire-import-drift")
    assert any(f.message.startswith(rel) or rel in f.message for f in errs)


def test_mirror_pair_drift_fixture():
    rel = "hetu_tpu/comm_quant.py"
    gutted = read(rel).replace("def np_quantize_blocks(",
                               "def np_qb_renamed(")
    errs = lints_of(analyze_drift(REPO, overlay={rel: gutted}),
                    "mirror-pair-drift")
    assert any("np_quantize_blocks" in f.message for f in errs)


# --------------------------------------------------------------------------
# surface family


def test_fault_kind_undocumented_fixture():
    errs = lints_of(
        analyze_surface(REPO,
                        overlay={"docs/FAULT_TOLERANCE.md": "# empty\n"}),
        "fault-kind-undocumented")
    names = {f.op_name for f in errs}
    assert set(faults.STEP_FAULT_NAMES) <= names


def test_fault_parser_drift_fixture():
    rel = "hetu_tpu/chaos.py"
    gutted = read(rel).replace("CHAOS_PROB_KEYS", "PRIVATE_KEYS") \
                      .replace("chaos_catalogue", "private_catalogue") \
                      .replace("CHAOS_SPEC_KEYS", "PRIVATE_SPEC")
    errs = lints_of(analyze_surface(REPO, overlay={rel: gutted}),
                    "fault-parser-drift")
    assert any(f.op_name == rel for f in errs)


def test_chaos_grammar_drift_fixture():
    rel = "hetu_tpu/csrc/ps/chaos.h"
    gutted = read(rel).replace('"droprsp"', '"dropRSP"')
    errs = lints_of(analyze_surface(REPO, overlay={rel: gutted}),
                    "chaos-grammar-drift")
    assert any(f.op_name == "droprsp" for f in errs)


def test_knob_undocumented_fixture():
    rel = "hetu_tpu/runner.py"
    seeded = read(rel) + '\n_X = os.environ.get("HETU_NOT_IN_ANY_DOC")\n'
    warns = lints_of(analyze_surface(REPO, overlay={rel: seeded}),
                     "knob-undocumented")
    assert any(f.op_name == "HETU_NOT_IN_ANY_DOC" for f in warns)


def test_gauge_undocumented_fixture():
    rel = "hetu_tpu/recovery.py"
    seeded = read(rel) + (
        '\ndef _seed(reg):\n'
        '    reg.gauge("hetu_gauge_nobody_documented").set(1.0)\n')
    warns = lints_of(analyze_surface(REPO, overlay={rel: seeded}),
                     "gauge-undocumented")
    assert any(f.op_name == "hetu_gauge_nobody_documented" for f in warns)


# --------------------------------------------------------------------------
# shipped tree + registries + CLI contract


def test_shipped_tree_is_drift_free():
    """Satellite acceptance: every true drift was fixed in this PR, so
    the full Tier D run has zero errors on the working tree."""
    errors = [f for f in subcli.analyze(REPO) if f.severity == "error"]
    assert not errors, [f.message for f in errors]


def test_unpack_fields_rejects_short_reply():
    with pytest.raises(ValueError, match="slot-layout drift"):
        wire.unpack_fields(wire.SERVER_STATS_FIELDS,
                           range(wire.SERVER_STATS_SLOTS - 1))
    d = wire.unpack_fields(wire.WORLD_REPLY_FIELDS, [7, 2, 3, 0, 40])
    assert d["world_version"] == 7 and d["start_step"] == 40


def test_fault_registry_rejects_unknown_kind_with_catalogue():
    with pytest.raises(ValueError, match="fault-kind catalogue"):
        faults.parse_step_entry("totally_new_kind@5")
    got = faults.parse_step_entry("job_kill@3:pre_commit")
    assert got["kind"] == "job_kill" and got["arg"] == "pre_commit"


def test_hetucheck_cli_json_smoke():
    """Tier-1 smoke: hetucheck exits 0 on the shipped tree and the JSON
    shape is the hetulint contract."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetucheck"), "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] is True
    assert payload["counts"].get("error", 0) == 0
    assert isinstance(payload["findings"], list)


def test_hetucheck_self_check():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetucheck"), "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PASS" in out.stdout


def test_hetucheck_fails_on_seeded_tree(tmp_path):
    """End-to-end exit-code check: a checkout carrying the slot drift
    makes `bin/hetucheck <root>` exit 1."""
    # clone just what the analyzers read, with the defect seeded
    import shutil
    for rel in ("hetu_tpu/csrc/ps", "hetu_tpu/csrc/cache", "hetu_tpu/ps",
                "hetu_tpu/analysis", "docs", "bin"):
        src = os.path.join(REPO, rel)
        if os.path.isdir(src):
            shutil.copytree(src, tmp_path / rel)
    for rel in ("hetu_tpu/faults.py", "hetu_tpu/resilience.py",
                "hetu_tpu/chaos.py", "hetu_tpu/recovery.py",
                "hetu_tpu/elastic.py", "hetu_tpu/runner.py",
                "hetu_tpu/comm_quant.py", "README.md"):
        if os.path.exists(os.path.join(REPO, rel)):
            shutil.copy(os.path.join(REPO, rel), tmp_path / rel)
    seeded = (tmp_path / SERVER_H)
    seeded.write_text(seeded.read_text().replace("int64_t stats[11]",
                                                 "int64_t stats[12]"))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetucheck"),
         str(tmp_path)], capture_output=True, text=True, timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "slot-count-drift" in out.stdout
