"""hetuq (quantized communication, docs/COMM_QUANT.md) tests.

Covers ISSUE 10's acceptance surface: quantize/dequantize round-trip error
bounds (<= scale/2 per block), error-feedback SGD on the w512 MLP converging
to within tolerance of the f32 run on the 8-device mesh, quantized
SparsePush/SSPushPull dedup-sum exactness against the bit-exact numpy mirror
of the C++ wire quantizer under a live ``local_cluster``, off-mode
bit-identity with the unquantized path, the server rejecting corrupted
quantized payloads (the ``quant_corrupt`` fault), and a resend-dedup
re-issue proof on the quantized path (server dies applied-but-unacked, the
failover re-issue of the SAME quantized message is answered without a
double apply).
"""
import contextlib
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu import comm_quant as cq


# ---------------------------------------------------------------------------
# quantizer round-trip bounds (traced + numpy mirror)
# ---------------------------------------------------------------------------

def test_jnp_roundtrip_error_bounded_by_half_scale():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1000).astype(np.float32) * 3.0)
    for block in (256, 64, 7):
        q, scales, n = cq.quantize_blocks(x, block, "int8")
        dq = cq.dequantize_blocks(q, scales, n, block)
        err = np.abs(np.asarray(dq) - np.asarray(x))
        # per-element bound: half the element's block scale
        per_elt_scale = np.repeat(np.asarray(scales), block)[:n]
        assert np.all(err <= per_elt_scale / 2 + 1e-7), err.max()


def test_jnp_roundtrip_zeros_and_extremes_exact():
    x = jnp.zeros(300, jnp.float32)
    q, s, n = cq.quantize_blocks(x, 256, "int8")
    assert np.all(np.asarray(s) == 0.0)
    assert np.all(np.asarray(cq.dequantize_blocks(q, s, n, 256)) == 0.0)
    # the block max quantizes to +/-127 exactly -> dequantizes to itself
    x = jnp.asarray(np.array([127.0, -127.0, 64.0, 1.0], np.float32))
    q, s, n = cq.quantize_blocks(x, 4, "int8")
    dq = np.asarray(cq.dequantize_blocks(q, s, n, 4))
    np.testing.assert_array_equal(dq, np.asarray(x))


def test_np_mirror_roundtrip_bound():
    rng = np.random.RandomState(1)
    for shape, block in (((13, 8), 8), ((1000,), 256)):
        x = rng.randn(*shape).astype(np.float32)
        rt = cq.np_roundtrip(x, block)
        flat = x.reshape(-1, block) if x.size % block == 0 else None
        scales = (np.abs(x.reshape(-1, block)).max(axis=1) / 127
                  if flat is not None else None)
        if scales is not None:
            err = np.abs(rt - x).reshape(-1, block)
            assert np.all(err <= scales[:, None] / 2 + 1e-7)
        assert rt.shape == x.shape


def test_fp8_roundtrip_when_supported():
    if cq.fp8_dtype() is None:
        pytest.skip("no float8_e4m3fn in this jax build")
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(512).astype(np.float32))
    q, s, n = cq.quantize_blocks(x, 256, "fp8")
    dq = np.asarray(cq.dequantize_blocks(q, s, n, 256))
    # e4m3 carries a ~2^-3 relative mantissa step; bound loosely
    assert np.abs(dq - np.asarray(x)).max() <= np.abs(np.asarray(x)).max() / 8


def test_policy_resolution_and_exemption():
    pol = cq.QuantPolicy("int8", min_size=100, force=("tiny",))

    class N:
        def __init__(self, name):
            self.name = name

    assert pol.applies(N("big"), 100)
    assert not pol.applies(N("small"), 99)
    assert pol.applies(N("tiny"), 4)          # forced override
    assert not cq.QuantPolicy("off").applies(N("big"), 10**6)
    with pytest.raises(ValueError):
        cq.QuantPolicy("int4")
    # env resolution: explicit args win over env
    os.environ["HETU_COMM_QUANT"] = "int8"
    try:
        assert cq.resolve_policy().mode == "int8"
        assert cq.resolve_policy("off").mode == "off"
    finally:
        del os.environ["HETU_COMM_QUANT"]


# ---------------------------------------------------------------------------
# DP AllReduce path: off-mode bit-identity + error-feedback convergence
# ---------------------------------------------------------------------------

def _mlp(width, n_classes=8, seed=0):
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    h = x
    for i in range(3):
        w = ht.init.random_normal((width, width), stddev=0.05, name=f"w{i}")
        h = ht.relu_op(ht.matmul_op(h, w))
    wo = ht.init.random_normal((width, n_classes), stddev=0.05, name="wo")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, wo), y_), [0])
    train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
    return x, y_, loss, train_op


def _run_mlp(width, batch, steps, **kw):
    x, y_, loss, train_op = _mlp(width)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="AllReduce", seed=0, **kw)
    rng = np.random.RandomState(0)
    bx = rng.randn(batch, width).astype(np.float32)
    by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]
    losses = []
    for _ in range(steps):
        lv, _ = ex.run("train", feed_dict={x: bx, y_: by},
                       convert_to_numpy_ret_vals=True)
        losses.append(float(lv))
    params = {n.name: np.asarray(ex.state["params"][id(n)])
              for n in ex.param_nodes}
    return losses, params, ex


def test_off_mode_bit_identical_and_default():
    assert jax.device_count() == 8
    l_def, p_def, ex_def = _run_mlp(32, 64, 4)
    l_off, p_off, ex_off = _run_mlp(32, 64, 4, comm_quant="off")
    assert l_def == l_off
    for k in p_def:
        np.testing.assert_array_equal(p_def[k], p_off[k])
    # off mode carries zero hetuq state and marks no ops
    assert not ex_off.qar_ops and not ex_off.state["qresid"]
    assert ex_off.comm_quant_report is None
    # sanity: int8 actually engages (params diverge from the exact run)
    l_q, p_q, ex_q = _run_mlp(32, 64, 4, comm_quant="int8",
                              comm_quant_min_size=512)
    assert ex_q.qar_ops and ex_q.state["qresid"]
    assert any(not np.array_equal(p_def[k], p_q[k]) for k in p_def)


def test_error_feedback_w512_converges_to_f32_tolerance():
    """ISSUE 10 acceptance: error-feedback int8 SGD on the w512 MLP tracks
    the f32 run. Without EF the same tolerance must also hold here (the
    quantizer is fine at this scale); EF's role is bounding the long-run
    drift, asserted via the residual actually carrying the error."""
    assert jax.device_count() == 8
    steps = 12
    l32, p32, _ = _run_mlp(512, 256, steps)
    lq, pq, exq = _run_mlp(512, 256, steps, comm_quant="int8")
    assert exq.comm_quant_report["ratio"] > 1.5
    # loss trajectory within tolerance of the f32 run at every step
    for a, b in zip(l32, lq):
        assert abs(a - b) <= 2e-3 * max(1.0, abs(a)), (l32, lq)
    # final params stay close in relative terms
    for k in p32:
        denom = np.abs(p32[k]).max() + 1e-12
        assert np.abs(p32[k] - pq[k]).max() / denom < 5e-3, k
    # the residual is live state: non-zero after quantized steps
    assert any(np.abs(np.asarray(v)).max() > 0
               for v in exq.state["qresid"].values())


def test_shared_graph_off_after_int8_stays_exact():
    """Regression (review finding): graph nodes are shared between
    executors in an A/B — an 'off' executor built over a graph a previous
    'int8' executor marked must re-assert the exact path, not inherit the
    stale per-op comm_quant mark."""
    x, y_, loss, train_op = _mlp(64)
    rng = np.random.RandomState(0)
    bx = rng.randn(64, 64).astype(np.float32)
    by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, 64)]

    def run(ex):
        out = []
        for _ in range(3):
            lv, _ = ex.run("train", feed_dict={x: bx, y_: by},
                           convert_to_numpy_ret_vals=True)
            out.append(float(lv))
        return out

    # fresh-graph oracle for the exact path
    x2, y2, loss2, train2 = _mlp(64)
    ex_ref = ht.Executor({"train": [loss2, train2]}, ctx=ht.cpu(0),
                         comm_mode="AllReduce", seed=0)
    ref = []
    for _ in range(3):
        lv, _ = ex_ref.run("train", feed_dict={x2: bx, y2: by},
                           convert_to_numpy_ret_vals=True)
        ref.append(float(lv))

    ex_q = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                       comm_mode="AllReduce", seed=0, comm_quant="int8",
                       comm_quant_min_size=1024)
    assert ex_q.qar_ops
    run(ex_q)   # marks the shared nodes
    ex_off = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="AllReduce", seed=0, comm_quant="off")
    assert not ex_off.qar_ops
    assert all(not n.comm_quant for n in ex_off.param_nodes
               if hasattr(n, "comm_quant"))
    assert run(ex_off) == ref


def test_small_params_exempt_by_threshold():
    _, _, ex = _run_mlp(32, 64, 1, comm_quant="int8")
    # every param (32x32=1024, 32x8=256) sits below the default 2048
    # threshold -> nothing quantized, but the mode is on
    assert ex.config.comm_quant == "int8" and not ex.qar_ops


def test_qresid_checkpointed(tmp_path):
    _, _, ex = _run_mlp(64, 64, 3, comm_quant="int8",
                        comm_quant_min_size=1024)
    assert ex.state["qresid"]
    ex.save(str(tmp_path / "ckpt"))
    ref = {i: np.asarray(ex.state["qresid"][id(n)])
           for i, n in enumerate(ex._qresid_ordered())}
    assert any(np.abs(v).max() > 0 for v in ref.values())
    # a fresh executor restores the residuals alongside params
    x, y_, loss, train_op = _mlp(64)
    ex2 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                      comm_mode="AllReduce", seed=0, comm_quant="int8",
                      comm_quant_min_size=1024)
    ex2.load(str(tmp_path / "ckpt"))
    for i, n in enumerate(ex2._qresid_ordered()):
        np.testing.assert_array_equal(
            np.asarray(ex2.state["qresid"][id(n)]), ref[i])


# ---------------------------------------------------------------------------
# PS wire path under a live local cluster
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _quant_cluster(n_servers=2):
    from hetu_tpu.ps.local_cluster import local_cluster
    from hetu_tpu import ps as ps_pkg
    with local_cluster(n_servers=n_servers, n_workers=1):
        ps_pkg.worker_init()
        try:
            yield ps_pkg.get_worker_communicate()
        finally:
            ps_pkg.worker_finish()


def test_quant_sparse_push_dedup_sum_exact_vs_mirror():
    """Duplicate rows in one quantized SparsePush must dedup-sum in f32
    BEFORE quantization (exactly the mirror's quantize-of-the-sum), and the
    applied values must sit within the f32 apply's half-scale bound."""
    with _quant_cluster() as comm:
        W = 8
        comm.InitTensor(21, sparse=True, length=100, width=W,
                        init_type="constant", init_a=0.0, opt_type="sgd",
                        lrs=(1.0,))
        comm.SetCommQuant(1)
        rng = np.random.RandomState(0)
        idx = np.array([3, 60, 3, 97, 60, 3], np.int64)
        g = rng.randn(6, W).astype(np.float32)
        comm.SparsePush(21, idx, g)
        comm.Wait(21)
        uniq = np.unique(idx)
        out = comm.SparsePull(21, uniq, np.empty((uniq.size, W), np.float32))
        comm.Wait(21)
        acc = np.zeros((uniq.size, W), np.float32)
        for i, r in enumerate(idx):
            acc[np.searchsorted(uniq, r)] += g[i]
        # sgd += applies dequant(quant(sum)); the pull leg re-quantizes
        expect = cq.np_roundtrip(cq.np_roundtrip(acc, W), W)
        np.testing.assert_array_equal(out, expect)
        scale = np.abs(acc).max(axis=1, keepdims=True) / 127
        assert np.all(np.abs(out - acc) <= scale + 1e-6)
        cs = comm.ClientStats()
        assert 0 < cs["quant_wire_bytes"] < cs["quant_raw_bytes"]


def test_quant_ss_pushpull_matches_mirror():
    with _quant_cluster() as comm:
        W = 4
        comm.InitTensor(22, sparse=True, length=64, width=W,
                        init_type="constant", init_a=0.0, opt_type="sgd",
                        lrs=(1.0,))
        comm.SetCommQuant(1)
        rng = np.random.RandomState(3)
        push = np.array([1, 5, 40, 5], np.int64)
        pull = np.array([1, 5, 40, 63], np.int64)
        g = rng.randn(4, W).astype(np.float32)
        out = comm.SSPushPull(22, push, g, pull,
                              np.empty((4, W), np.float32))
        comm.Wait(22)
        table = np.zeros((64, W), np.float32)
        acc = np.zeros_like(table)
        np.add.at(acc, push, g)
        nz = np.unique(push)
        table[nz] = cq.np_roundtrip(acc[nz], W)
        expect = cq.np_roundtrip(table[pull], W)
        # row 63 was never pushed: stays exact zeros through the wire
        np.testing.assert_array_equal(out, expect)
        assert np.all(out[3] == 0.0)


def test_quant_dense_ddpushpull_matches_mirror():
    with _quant_cluster() as comm:
        n = 1000
        comm.InitTensor(23, sparse=False, length=n, width=1,
                        init_type="constant", init_a=0.0, opt_type="sgd",
                        lrs=(1.0,))
        comm.SetCommQuant(1)
        gd = np.random.RandomState(4).randn(n).astype(np.float32)
        out = comm.DDPushPull(23, gd, np.empty(n, np.float32))
        comm.Wait(23)
        lo = n // 2  # 2 servers -> independent shard quantization
        expect = np.concatenate([
            cq.np_roundtrip(cq.np_roundtrip(gd[:lo], 256), 256),
            cq.np_roundtrip(cq.np_roundtrip(gd[lo:], 256), 256)])
        np.testing.assert_array_equal(out, expect)
        # a NaN gradient fails at the SENDER with a numeric diagnosis, not
        # a misleading server-side "malformed scale" rejection
        bad = gd.copy()
        bad[3] = np.nan
        comm.Push(23, bad)
        with pytest.raises(RuntimeError, match="non-finite"):
            comm.Wait(23)


def test_corrupted_quant_message_rejected_param_untouched(monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    with _quant_cluster() as comm:
        W = 8
        comm.InitTensor(24, sparse=True, length=100, width=W,
                        init_type="constant", init_a=0.0, opt_type="sgd",
                        lrs=(1.0,))
        comm.SetCommQuant(1)
        rows = np.array([3, 10, 20], np.int64)  # one shard (server 0)
        before = comm.SparsePull(24, rows, np.empty((3, W), np.float32))
        comm.Wait(24)
        comm.TestCorruptNextQuant(-1)
        comm.SparsePush(24, rows, np.ones((3, W), np.float32))
        with pytest.raises(RuntimeError, match="scale|quantized"):
            comm.Wait(24)
        after = comm.SparsePull(24, rows, np.empty((3, W), np.float32))
        comm.Wait(24)
        np.testing.assert_array_equal(before, after)
        # the next clean push applies normally (connection survived)
        comm.SparsePush(24, rows, np.full((3, W), 2.0, np.float32))
        comm.Wait(24)
        out = comm.SparsePull(24, rows, np.empty((3, W), np.float32))
        comm.Wait(24)
        np.testing.assert_allclose(out, 2.0)


def test_corrupt_hook_gated_on_test_mode(monkeypatch):
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    with _quant_cluster() as comm:
        with pytest.raises(RuntimeError, match="HETU_TEST_MODE"):
            comm.TestCorruptNextQuant(-1)


def test_fault_injector_parses_quant_corrupt(monkeypatch):
    from hetu_tpu import resilience

    fi = resilience.FaultInjector("quant_corrupt@3:7")
    assert fi.entries[0]["kind"] == "quant_corrupt"
    assert fi.entries[0]["step"] == 3 and fi.entries[0]["arg"] == 7.0
    calls = []

    class _Comm:
        def TestCorruptNextQuant(self, node):
            calls.append(node)

    from hetu_tpu import ps as ps_pkg
    monkeypatch.setattr(ps_pkg, "get_worker_communicate", lambda: _Comm())
    fi.inject_host(2)
    assert calls == []
    fi.inject_host(3)
    assert calls == [7]
    fi.inject_host(3)   # one-shot
    assert calls == [7]


# ---------------------------------------------------------------------------
# resend-dedup re-issue proof on the quantized path (PR 4's scenario 5,
# quantized wire): the server applies + snapshots the quantized push, dies
# unacked; the failover re-issue of the SAME quantized bytes is answered
# from the restored ledger WITHOUT a second apply.
# ---------------------------------------------------------------------------

def _worker_quant_dedup_proof(client, rank, tmpdir):
    client.SetCommQuant(1)
    client.InitTensor(12, sparse=True, length=200, width=4,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    row = np.array([200 - 10], np.int64)  # owned by server 1
    # integer grads with amax 127: scale == 1.0, the int8 roundtrip is
    # EXACT, so the no-double-apply algebra below is exact equality
    g = np.tile(np.array([[127.0, 64.0, 32.0, 1.0]], np.float32), (1, 1))
    for _ in range(2):
        client.SparsePush(12, row, g)
        client.Wait(12)
    # 3rd push trips the server's exit-after-updates hook: applied +
    # snapshotted (data AND dedup ledger), never acked — Wait returns only
    # after the failover re-issue is answered by the replacement
    client.SparsePush(12, row, g)
    client.Wait(12)
    out = client.SparsePull(12, row, np.empty((1, 4), np.float32))
    client.Wait(12)
    np.testing.assert_array_equal(out, 3 * g)  # NOT 4x: no double-apply
    st = client.ServerStats(1)
    assert st["restored_updates"] == 3 and st["updates"] == 3, st
    # the next real update still lands exactly once, still quantized
    client.SparsePush(12, row, g)
    client.Wait(12)
    out = client.SparsePull(12, row, np.empty((1, 4), np.float32))
    client.Wait(12)
    np.testing.assert_array_equal(out, 4 * g)


def test_quant_reissue_no_double_apply(tmp_path):
    from test_ps_fault import _run_ha_cluster

    def orchestrate(ctx, env):
        pass  # the server kills itself (hook); the supervisor respawns

    sup = _run_ha_cluster(
        _worker_quant_dedup_proof, orchestrate, tmp_path,
        snapshot_ms=60000,
        server1_extra={"HETU_PS_TEST_EXIT_AFTER_UPDATES": "3:snap",
                       "HETU_TEST_MODE": "1"})
    assert sup.respawns == 1 and sup.fatal is None
