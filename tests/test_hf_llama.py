"""HuggingFace Llama numerical parity (models/hf_llama.py): RoPE, RMSNorm,
SwiGLU, GQA — random-weight transformers Llama (no network), import,
compare logits / KV-cache decode / whole-loop generation, round-trip
export, refusals. Same pinning pattern as the BERT/GPT-2/ViT suites."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from hetu_tpu.models import generate as gen
from hetu_tpu.models import transformer as tfm
from hetu_tpu.models.hf_llama import (config_from_hf, export_to_hf,
                                      params_from_hf)


def small_hf_config(**over):
    kw = dict(vocab_size=96, hidden_size=64, num_hidden_layers=3,
              num_attention_heads=4, num_key_value_heads=2,  # GQA
              intermediate_size=112, max_position_embeddings=64,
              rms_norm_eps=1e-6, rope_theta=10000.0,
              tie_word_embeddings=False)
    kw.update(over)
    return transformers.LlamaConfig(**kw)


@pytest.fixture(scope="module")
def llama_pair():
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(small_hf_config()).eval()
    params, cfg = params_from_hf(model)
    import dataclasses
    cfg = dataclasses.replace(cfg, remat=False, attn_impl="dot",
                              fused_lm_ce=False)
    return model, params, cfg


def hf_logits(model, ids):
    with torch.no_grad():
        return model(input_ids=torch.tensor(ids)).logits.numpy()


def test_logits_match_hf(llama_pair):
    model, params, cfg = llama_pair
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (3, 20))
    ours, _ = tfm.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_kv_cache_decode_matches_hf(llama_pair):
    """RoPE through the cache: teacher-forced incremental logits equal the
    torch full forward (rotated keys cached at absolute positions)."""
    model, params, cfg = llama_pair
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, (2, 14))
    fn = gen.make_generate_fn(cfg, max_len=14)
    toks, inc_logits = fn(params, jnp.asarray(ids, jnp.int32),
                          jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), ids)
    np.testing.assert_allclose(np.asarray(inc_logits),
                               hf_logits(model, ids), atol=3e-4, rtol=3e-4)


def test_greedy_generation_matches_hf_generate(llama_pair):
    model, params, cfg = llama_pair
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    ours = gen.generate(params, cfg, prompt, max_len=16)
    with torch.no_grad():
        ref = model.generate(
            torch.tensor(prompt, dtype=torch.long),
            attention_mask=torch.ones((2, 6), dtype=torch.long),
            max_new_tokens=10, do_sample=False, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(ours), ref.numpy())


def test_speculative_decode_runs_on_llama(llama_pair):
    """The imported Llama rides speculative decoding unchanged (self-draft
    -> full acceptance -> exact greedy)."""
    model, params, cfg = llama_pair
    prompt = np.random.default_rng(4).integers(
        0, cfg.vocab_size, (1, 5)).astype(np.int32)
    plain = gen.generate(params, cfg, prompt, max_len=20)
    fn = gen.make_speculative_generate_fn(cfg, cfg, 20, k=3)
    spec, rounds = fn(params, params, jnp.asarray(prompt))
    np.testing.assert_array_equal(np.asarray(spec), plain)
    assert int(rounds) == -(-(20 - 5 - 1) // 4)


def test_imported_llama_trains_a_step(llama_pair):
    model, params, cfg = llama_pair
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)), jnp.int32)
    step = tfm.make_train_step(cfg, lr=1e-3)
    p2 = jax.tree.map(jnp.array, params)
    opt = tfm.init_opt_state(p2)
    l1, p2, opt = step(p2, opt, toks[:, :-1], toks[:, 1:])
    l2, p2, opt = step(p2, opt, toks[:, :-1], toks[:, 1:])
    assert float(l2) < float(l1)


def test_train_then_export_roundtrip(llama_pair):
    model, params, cfg = llama_pair
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)), jnp.int32)
    step = tfm.make_train_step(cfg, lr=1e-3)
    trained = jax.tree.map(jnp.array, params)
    _, trained, _ = step(trained, tfm.init_opt_state(trained),
                         toks[:, :-1], toks[:, 1:])
    fresh = transformers.LlamaForCausalLM(model.config).eval()
    export_to_hf(trained, cfg, fresh)
    ids = rng.integers(0, cfg.vocab_size, (3, 12))
    ours, _ = tfm.forward(trained, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(fresh, ids),
                               atol=3e-4, rtol=3e-4)


def test_mha_variant_and_tied_head():
    """num_key_value_heads == num_attention_heads (plain MHA) and
    tie_word_embeddings=True both import and match."""
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(small_hf_config(
        num_key_value_heads=4, tie_word_embeddings=True)).eval()
    params, cfg = params_from_hf(model)
    assert cfg.tied_head and cfg.n_kv_heads == 0 and "head" not in params
    import dataclasses
    cfg = dataclasses.replace(cfg, remat=False, attn_impl="dot",
                              fused_lm_ce=False)
    ids = np.random.default_rng(8).integers(0, cfg.vocab_size, (2, 10))
    ours, _ = tfm.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_mesh_forward_matches_hf(llama_pair):
    """The imported GQA Llama sharded dp2/tp2 on the virtual mesh equals
    the torch forward (kv heads split 2-over-tp2, rope under GSPMD)."""
    model, params, cfg = llama_pair
    from hetu_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    sharded = tfm.shard_params(params, cfg, mesh)
    rng = np.random.default_rng(9)
    ids = rng.integers(0, cfg.vocab_size, (4, 12))
    ours, _ = jax.jit(lambda p, t: tfm.forward(p, t, cfg, mesh))(
        sharded, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_windowless_mistral_imports(llama_pair):
    """Mistral shares the Llama layout; a windowless config imports and
    matches the torch forward (the windowed default refuses instead)."""
    import dataclasses
    torch.manual_seed(10)
    model = transformers.MistralForCausalLM(transformers.MistralConfig(
        vocab_size=96, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=112, max_position_embeddings=64,
        rms_norm_eps=1e-6, sliding_window=None)).eval()
    params, cfg = params_from_hf(model)
    cfg = dataclasses.replace(cfg, remat=False, attn_impl="dot",
                              fused_lm_ce=False)
    ids = np.random.default_rng(11).integers(0, 96, (2, 12))
    ours, _ = tfm.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(ours), hf_logits(model, ids),
                               atol=3e-4, rtol=3e-4)


def test_import_refuses_mismatched_config(llama_pair):
    model, _, _ = llama_pair
    truncated = config_from_hf(model.config, n_layers=2)
    with pytest.raises(ValueError, match="n_layers"):
        params_from_hf(model, truncated)


def test_import_refuses_attention_bias():
    cfg = small_hf_config(attention_bias=True)
    model = transformers.LlamaForCausalLM(cfg).eval()
    with pytest.raises(NotImplementedError, match="attention_bias"):
        params_from_hf(model)


def test_import_refuses_sliding_window_and_odd_head_dim():
    class FakeCfg:
        # minimal duck-typed config: a Mistral-style windowed variant
        vocab_size = 96; hidden_size = 64; num_attention_heads = 4
        num_key_value_heads = 2; num_hidden_layers = 2
        intermediate_size = 112; max_position_embeddings = 64
        rms_norm_eps = 1e-6; rope_theta = 10000.0
        tie_word_embeddings = False; hidden_act = "silu"
        attention_bias = False; rope_scaling = None
        sliding_window = 4096; head_dim = None
    with pytest.raises(NotImplementedError, match="sliding_window"):
        config_from_hf(FakeCfg())
    FakeCfg.sliding_window = None
    FakeCfg.head_dim = 32     # != hidden_size / num_heads
    with pytest.raises(NotImplementedError, match="head_dim"):
        config_from_hf(FakeCfg())


def test_swiglu_moe_combination_refuses():
    with pytest.raises(ValueError, match="swiglu"):
        tfm.TransformerConfig(mlp="swiglu", n_experts=4)
