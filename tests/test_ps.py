"""Parameter-server integration tests: a full local-process cluster
(scheduler + 2 servers + 2 workers) over loopback.

Mirrors the reference's tests/pstests/test_apis.py strategy (SURVEY.md §4.3):
all roles as local processes, config via env vars, workers cross-check
InitTensor/Push/Pull/sparse APIs against numpy oracles. Uses the ``spawn``
start method (children never touch the parent's JAX runtime — fork with JAX
threads deadlocks).
"""
import multiprocessing as mp
import os
import queue as pyqueue
import shutil
import tempfile
import time

import numpy as np

NITEM = 200
ITEM_LEN = 50
_PORT_BASE = int(os.environ.get("HETU_TEST_PS_PORT", "13700"))
_port_iter = iter(range(_PORT_BASE, _PORT_BASE + 10000, 7))


def _env(role, idx, port, n_workers=2, n_servers=2):
    env = {
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(n_workers),
        "DMLC_NUM_SERVER": str(n_servers),
        "DMLC_ROLE": role,
        # keep spawned roles off the real TPU (sitecustomize pins axon; the
        # env alone is not authoritative — worker bodies also config-update)
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"),
    }
    if role == "server":
        env["SERVER_ID"] = str(idx)
        env["DMLC_PS_SERVER_URI"] = "127.0.0.1"
        env["DMLC_PS_SERVER_PORT"] = str(port + 1 + idx)
    elif role == "worker":
        env["WORKER_ID"] = str(idx)
    return env


def _run_scheduler(port, n_workers, n_servers):
    os.environ.update(_env("scheduler", 0, port, n_workers, n_servers))
    from hetu_tpu.ps import server as srv
    srv.start_scheduler_from_env()
    srv.scheduler_wait()
    srv.stop_scheduler()


def _worker_body(rank, port, n_workers, n_servers, fn, tmpdir, result_q):
    os.environ.update(_env("worker", rank, port, n_workers, n_servers))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    try:
        fn(client, rank, tmpdir)
        result_q.put((rank, "ok", None))
    except Exception:  # noqa: BLE001
        import traceback
        result_q.put((rank, "fail", traceback.format_exc()))
    finally:
        client.close()


def run_cluster(worker_fn, tmpdir="/tmp", n_workers=2, n_servers=2,
                timeout=120):
    """Spawn scheduler/servers (LIGHT subprocesses — ctypes-only, no
    hetu_tpu/jax import) and workers (spawn method, full framework);
    assert every worker body passed."""
    from hetu_tpu.ps.local_cluster import (reap_light_procs,
                                           spawn_light_role,
                                           spawn_light_server)
    ctx = mp.get_context("spawn")
    port = next(_port_iter)
    stopdir = tempfile.mkdtemp(prefix="hetups_stop_")
    stopfile = os.path.join(stopdir, "stop")
    result_q = ctx.Queue()
    infra = []
    procs = []
    results = {}
    deadline = time.time() + timeout
    try:
        # spawn INSIDE the try so a partial bootstrap still gets reaped
        infra.append(spawn_light_role(
            "scheduler", _env("scheduler", 0, port, n_workers, n_servers)))
        for s in range(n_servers):
            infra.append(spawn_light_server(
                s, _env("server", s, port, n_workers, n_servers), stopfile,
                port=str(port + 1 + s)))
        for w in range(n_workers):
            procs.append(ctx.Process(
                target=_worker_body,
                args=(w, port, n_workers, n_servers, worker_fn, str(tmpdir),
                      result_q)))
        for p in procs:
            p.start()
        # Poll instead of one blocking get so failures surface the moment
        # they happen rather than after the full timeout, and so queue.Empty
        # is reserved for the one retryable meaning: "host too slow".
        while len(results) < n_workers:
            try:
                rank, status, err = result_q.get(timeout=2)
                results[rank] = (status, err)
                if status != "ok":
                    # fail fast with the real traceback — a failed worker's
                    # peer may hang on a barrier forever, and that hang must
                    # not reclassify this failure as a timeout
                    raise AssertionError(f"worker {rank} failed:\n{err}")
                continue
            except pyqueue.Empty:
                pass
            # a worker that died without reporting (e.g. a native crash
            # _worker_body's except clause cannot catch, ANY exit code)
            dead = {i: p.exitcode for i, p in enumerate(procs)
                    if i not in results and not p.is_alive()}
            if dead:
                raise RuntimeError(
                    f"worker(s) died without reporting: "
                    f"{{rank: exitcode}} = {dead}")
            # scheduler/server crash (abnormal exit only — they run until
            # the stopfile during a healthy run)
            dead_infra = {i: p.returncode for i, p in enumerate(infra)
                          if p.poll() is not None and p.returncode != 0}
            if dead_infra:
                raise RuntimeError(
                    f"scheduler/server died: {{idx: exitcode}} = "
                    f"{dead_infra}")
            if time.time() > deadline:
                raise pyqueue.Empty
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        for p in procs:
            p.join(timeout=20)
        for p in procs:
            if p.is_alive():
                p.terminate()
        reap_light_procs(infra, timeout=20)
        shutil.rmtree(stopdir, ignore_errors=True)
    for rank, (status, err) in sorted(results.items()):
        assert status == "ok", f"worker {rank} failed:\n{err}"
    assert len(results) == n_workers, "some workers produced no result"
    return results


# ---------------------------------------------------------------------------
# worker bodies (module-level: spawn pickles them by reference)
# ---------------------------------------------------------------------------

def _dense_ops(client, rank, tmpdir):
    client.InitTensor(0, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=1.5)
    out = client.Pull(0, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(0)
    np.testing.assert_allclose(out, 1.5, rtol=1e-6)
    client.BarrierWorker()

    # accumulate push from both workers: server does += (SGD semantics with
    # worker-side lr pre-scaling, reference PSFHandle.h:51)
    grad = np.full(NITEM * ITEM_LEN, 0.25, np.float32)
    client.Push(0, grad)
    client.Wait(0)
    client.BarrierWorker()
    out = client.Pull(0, out)
    client.Wait(0)
    np.testing.assert_allclose(out, 1.5 + 0.25 * 2, rtol=1e-6)
    client.BarrierWorker()

    # DDPushPull returns post-update values
    client.DDPushPull(0, grad, np.empty_like(out))
    client.Wait(0)
    client.BarrierWorker()
    out = client.Pull(0, out)
    client.Wait(0)
    np.testing.assert_allclose(out, 2.0 + 0.25 * 2, rtol=1e-6)
    client.BarrierWorker()
    if rank == 0:
        client.ClearOnServer(0)
    client.BarrierWorker()
    out = client.Pull(0, out)
    client.Wait(0)
    np.testing.assert_allclose(out, 0.0)


def _random_init(client, rank, tmpdir):
    # normal init happens ON the servers (reference init_on_ps,
    # initializers.py:28-39): all workers must pull identical values
    client.InitTensor(1, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="normal", init_a=0.0, init_b=1.0, seed=7)
    out = client.Pull(1, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(1)
    assert np.std(out) > 0.5
    np.save(os.path.join(tmpdir, f"init_{rank}.npy"), out)
    client.BarrierWorker()


def _sparse_ops(client, rank, tmpdir):
    client.InitTensor(2, sparse=True, length=NITEM, width=ITEM_LEN,
                      init_type="constant", init_a=0.0)
    client.BarrierWorker()
    rng = np.random.RandomState(42 + rank)
    idx = rng.randint(0, NITEM, 64).astype(np.int64)
    vals = np.ones((64, ITEM_LEN), np.float32)
    client.SparsePush(2, idx, vals)
    client.Wait(2)
    client.BarrierWorker()

    # oracle: both workers' scatter-adds
    expect = np.zeros((NITEM, ITEM_LEN), np.float32)
    for r in range(2):
        rr = np.random.RandomState(42 + r)
        for i in rr.randint(0, NITEM, 64):
            expect[i] += 1.0
    pull_idx = np.arange(NITEM, dtype=np.int64)
    out = client.SparsePull(2, pull_idx,
                            np.empty((NITEM, ITEM_LEN), np.float32))
    client.Wait(2)
    np.testing.assert_allclose(out, expect, rtol=1e-6)
    client.BarrierWorker()

    # duplicate keys within one push accumulate (worker-side dedup sums)
    dup_idx = np.zeros(4, np.int64)
    client.SparsePush(2, dup_idx, np.ones((4, ITEM_LEN), np.float32))
    client.Wait(2)
    client.BarrierWorker()
    out1 = client.SparsePull(2, np.zeros(1, np.int64),
                             np.empty((1, ITEM_LEN), np.float32))
    client.Wait(2)
    np.testing.assert_allclose(out1[0], expect[0] + 8.0, rtol=1e-6)


def _ss_pushpull(client, rank, tmpdir):
    client.InitTensor(3, sparse=True, length=NITEM, width=ITEM_LEN,
                      init_type="constant", init_a=2.0)
    client.BarrierWorker()
    idx = np.arange(10, dtype=np.int64) + rank * 10  # disjoint per worker
    vals = np.full((10, ITEM_LEN), 0.5, np.float32)
    out = client.SSPushPull(3, idx, vals, idx,
                            np.empty((10, ITEM_LEN), np.float32))
    client.Wait(3)
    np.testing.assert_allclose(out, 2.5, rtol=1e-6)  # own push visible


def _server_optimizer(client, rank, tmpdir):
    # server-side adagrad: w -= lr * g / (sqrt(sum g^2) + eps)
    client.InitTensor(4, sparse=False, length=100, width=1,
                      init_type="constant", init_a=1.0,
                      opt_type="adagrad", lrs=(0.5, 1e-7))
    client.BarrierWorker()
    if rank == 0:
        client.Push(4, np.full(100, 2.0, np.float32))
        client.Wait(4)
    client.BarrierWorker()
    out = client.Pull(4, np.empty(100, np.float32))
    client.Wait(4)
    np.testing.assert_allclose(out, 1.0 - 0.5 * 2.0 / 2.0, rtol=1e-5)


def _save_load(client, rank, tmpdir):
    client.InitTensor(5, sparse=False, length=500, width=1,
                      init_type="uniform", init_a=-1.0, init_b=1.0, seed=3)
    before = client.Pull(5, np.empty(500, np.float32))
    client.Wait(5)
    client.BarrierWorker()  # both workers snapshot before rank 0 mutates
    if rank == 0:
        client.SaveParam(5, tmpdir)
        client.ClearOnServer(5)
    client.BarrierWorker()
    zero = client.Pull(5, np.empty(500, np.float32))
    client.Wait(5)
    np.testing.assert_allclose(zero, 0.0)
    if rank == 0:
        client.LoadParam(5, tmpdir)
    client.BarrierWorker()
    after = client.Pull(5, np.empty(500, np.float32))
    client.Wait(5)
    np.testing.assert_allclose(after, before, rtol=1e-6)


def _data_push_pull(client, rank, tmpdir):
    ids = np.array([10 + rank, 20 + rank], np.uint64)
    lens = np.array([3, 4], np.int64)
    vals = np.arange(7, dtype=np.float32) + rank * 100
    qid = client.PushData(9, ids, vals, lens)
    client.WaitData(qid)
    client.BarrierWorker()
    out = np.empty(7, np.float32)
    qid, out = client.PullData(9, ids, out, lens)
    client.WaitData(qid)
    np.testing.assert_allclose(out, vals)


def _loads_recording(client, rank, tmpdir):
    client.InitTensor(6, sparse=False, length=64, width=1,
                      init_type="constant", init_a=0.0)
    client.startRecord(tmpdir)
    client.Push(6, np.ones(64, np.float32))
    client.Wait(6)
    loads = client.getLoads()
    assert loads.get("push", 0) == 64 * 4


def _oob_row_ids(client, rank, tmpdir):
    # out-of-range embedding ids (straight from user data) must come back as
    # a clean error, not corrupt the server's heap
    client.InitTensor(9, sparse=True, length=NITEM, width=ITEM_LEN,
                      init_type="constant", init_a=0.0)
    client.BarrierWorker()
    bad = np.array([NITEM + 5], np.int64)
    vals = np.ones((1, ITEM_LEN), np.float32)
    try:
        client.SparsePush(9, bad, vals)
        client.Wait(9)
        raise AssertionError("OOB row id did not raise")
    except RuntimeError as e:
        assert "out of range" in str(e), e
    client.BarrierWorker()
    # the server survived and the table is untouched
    idx = np.arange(NITEM, dtype=np.int64)
    out = client.SparsePull(9, idx, np.empty((NITEM, ITEM_LEN), np.float32))
    client.Wait(9)
    np.testing.assert_allclose(out, 0.0)


def _exits_without_reporting(client, rank, tmpdir):
    os._exit(3)   # simulates a native crash: no result ever enqueued


# ---------------------------------------------------------------------------

def test_ps_dense_ops(tmp_path):
    run_cluster(_dense_ops, tmp_path)


def test_dead_worker_is_not_a_timeout(tmp_path):
    # a worker that dies without reporting must surface as the distinct
    # dead-worker RuntimeError (never retried by callers), not as the
    # retryable slow-host queue.Empty
    import pytest
    # generous deadline: on an oversubscribed host the worker needs time to
    # even START before it can die; what's under test is that its death is
    # CLASSIFIED as the dead-worker error, never the retryable queue.Empty
    # (observed flaking at timeout=20 under concurrent torch compiles)
    with pytest.raises(RuntimeError, match="died without reporting"):
        run_cluster(_exits_without_reporting, tmp_path, n_workers=1,
                    timeout=90)


def test_ps_oob_row_ids(tmp_path):
    run_cluster(_oob_row_ids, tmp_path)


def test_ps_random_init_consistency(tmp_path):
    run_cluster(_random_init, tmp_path)
    a = np.load(os.path.join(tmp_path, "init_0.npy"))
    b = np.load(os.path.join(tmp_path, "init_1.npy"))
    np.testing.assert_allclose(a, b)


def test_ps_sparse_ops(tmp_path):
    run_cluster(_sparse_ops, tmp_path)


def test_ps_ss_pushpull(tmp_path):
    run_cluster(_ss_pushpull, tmp_path)


def test_ps_server_optimizer(tmp_path):
    run_cluster(_server_optimizer, tmp_path)


def test_ps_save_load(tmp_path):
    run_cluster(_save_load, tmp_path)


def test_ps_data_push_pull(tmp_path):
    run_cluster(_data_push_pull, tmp_path)


def test_ps_loads_recording(tmp_path):
    run_cluster(_loads_recording, tmp_path)
