"""PS fault tolerance: heartbeats, recv timeouts, resend, recovery re-add.

Mirrors the reference's resender + recovery machinery
(ps-lite/src/resender.h:15-35,116 ack+timeout resend; van.cc:27,47,569
heartbeats and recovery-node re-add), redesigned for the raw-TCP van:
SO_RCVTIMEO bounds every wait, the worker resends over a fresh connection
(servers dedup on (client_id, req_id)), the scheduler's heartbeat ledger
declares dead servers, and a replacement server re-registering under the
same id is picked up by worker reconnects.

Scenarios (the VERDICT's acceptance test): SIGKILL one of 2 servers
mid-run and observe either a clean, prompt error — or recovery once a
replacement registers.
"""
import multiprocessing as mp
import os
import time

import numpy as np

from test_ps import _env, _run_scheduler, _worker_body, _port_iter, NITEM, ITEM_LEN

# tight knobs so death is detected in seconds, not minutes
FAULT_ENV = {
    "DMLC_PS_RECV_TIMEOUT_MS": "2000",
    "DMLC_PS_MAX_RETRY": "3",
    "DMLC_PS_HEARTBEAT_MS": "300",
    "DMLC_PS_HEARTBEAT_TIMEOUT_MS": "1500",
}


def _run_server_fault(idx, port, n_workers, n_servers, stopfile,
                      restore_dir=None):
    os.environ.update(_env("server", idx, port, n_workers, n_servers))
    os.environ.update(FAULT_ENV)
    if restore_dir is not None:
        os.environ["DMLC_PS_RESTORE_DIR"] = restore_dir
    from hetu_tpu.ps import server as srv
    srv.start_server_from_env()
    while not os.path.exists(stopfile):
        time.sleep(0.05)
    srv.stop_server()


def _worker_body_fault(rank, port, n_workers, n_servers, fn, tmpdir, result_q):
    os.environ.update(FAULT_ENV)
    _worker_body(rank, port, n_workers, n_servers, fn, tmpdir, result_q)


def _wait_file(path, timeout=60):
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > timeout:
            raise TimeoutError(f"waiting for {path}")
        time.sleep(0.05)


def _run_fault_cluster(worker_fn, orchestrate, tmpdir, restore_dir=None):
    """1 worker + 2 servers + scheduler; ``orchestrate(ctx, procs, env_port)``
    runs in the main process to inject faults (kill/restart servers)."""
    port = next(_port_iter)
    tmpdir = str(tmpdir)
    ctx = mp.get_context("spawn")
    stopfile = os.path.join(tmpdir, "stop_servers")
    sched = ctx.Process(target=_run_scheduler, args=(port, 1, 2))
    servers = [ctx.Process(target=_run_server_fault,
                           args=(i, port, 1, 2, stopfile, restore_dir))
               for i in range(2)]
    result_q = ctx.Queue()
    worker = ctx.Process(target=_worker_body_fault,
                         args=(0, port, 1, 2, worker_fn, tmpdir, result_q))
    sched.start()
    for s in servers:
        s.start()
    worker.start()
    try:
        orchestrate(ctx, {"servers": servers, "port": port,
                          "stopfile": stopfile, "tmpdir": tmpdir,
                          "restore_dir": restore_dir})
        rank, status, err = result_q.get(timeout=120)
        assert status == "ok", f"worker failed:\n{err}"
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        worker.join(timeout=20)
        for p in servers + [sched, worker]:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)


# ---------------------------------------------------------------------------
# scenario 1: server dies, stays dead -> clean prompt error, no hang
# ---------------------------------------------------------------------------

def _worker_clean_error(client, rank, tmpdir):
    client.InitTensor(0, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=1.5)
    out = client.Pull(0, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(0)
    np.testing.assert_allclose(out, 1.5)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "killed"))
    t0 = time.time()
    try:
        client.Pull(0, out)
        client.Wait(0)
        raise AssertionError("pull against a dead server did not raise")
    except RuntimeError as e:
        elapsed = time.time() - t0
        assert "unreachable" in str(e) or "timed out" in str(e), e
        # prompt: bounded by recv timeout x retries, not a forever-hang
        assert elapsed < 60, f"error took {elapsed:.0f}s"


def test_server_death_prompt_clean_error(tmp_path):
    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        open(os.path.join(env["tmpdir"], "killed"), "w").write("ok")

    _run_fault_cluster(_worker_clean_error, orchestrate, tmp_path)


# ---------------------------------------------------------------------------
# scenario 2: server dies, a replacement re-registers -> worker recovers
# ---------------------------------------------------------------------------

def _worker_recovers(client, rank, tmpdir):
    client.InitTensor(1, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=2.5)
    out = client.Pull(1, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(1)
    np.testing.assert_allclose(out, 2.5)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "restarted"))
    # the replacement server is empty: re-init (idempotent on the survivor,
    # creates the shard on the recovered one), then pull through the worker's
    # reconnect path
    client.InitTensor(1, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=2.5)
    out = client.Pull(1, out)
    client.Wait(1)
    np.testing.assert_allclose(out, 2.5)


def test_server_recovery_after_restart(tmp_path):
    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        # replacement under the same SERVER_ID: scheduler takes the
        # recovery re-add path and workers reconnect to it
        repl = ctx.Process(target=_run_server_fault,
                           args=(1, env["port"], 1, 2, env["stopfile"]))
        repl.start()
        env["servers"][1] = repl
        time.sleep(1.5)  # let it register + heartbeat
        open(os.path.join(env["tmpdir"], "restarted"), "w").write("ok")

    _run_fault_cluster(_worker_recovers, orchestrate, tmp_path)


# ---------------------------------------------------------------------------
# scenario 3: recovery RESTORES STATE — replacement server rebuilds its
# shard from the last ParamSave directory; the worker does NOT re-init
# (VERDICT weak#5; intent of reference van.cc:47 + psf/PSFunc.h:25-28)
# ---------------------------------------------------------------------------

def _worker_state_restored(client, rank, tmpdir):
    ckpt = os.path.join(tmpdir, "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    n = NITEM * ITEM_LEN
    rng = np.random.RandomState(3)
    client.InitTensor(2, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    # train: pushes move the param off its init value
    grad = rng.randn(n).astype(np.float32)
    client.Push(2, grad)
    client.Wait(2)
    buf = client.Pull(2, np.empty(n, np.float32))
    client.Wait(2)   # Pull fills the buffer only after Wait
    expected = buf.copy()
    assert np.abs(expected).max() > 0.1  # actually trained
    client.SaveParam(2, ckpt)
    client.Wait(2)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "restarted"))
    # NO re-init: the replacement restored its shard from the checkpoint
    out = client.Pull(2, np.empty(n, np.float32))
    client.Wait(2)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_server_recovery_restores_state(tmp_path):
    ckpt = os.path.join(str(tmp_path), "ckpt")

    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        repl = ctx.Process(
            target=_run_server_fault,
            args=(1, env["port"], 1, 2, env["stopfile"], env["restore_dir"]))
        repl.start()
        env["servers"][1] = repl
        time.sleep(1.5)
        open(os.path.join(env["tmpdir"], "restarted"), "w").write("ok")

    _run_fault_cluster(_worker_state_restored, orchestrate, tmp_path,
                       restore_dir=ckpt)
