"""PS fault tolerance: heartbeats, recv timeouts, resend, recovery re-add.

Mirrors the reference's resender + recovery machinery
(ps-lite/src/resender.h:15-35,116 ack+timeout resend; van.cc:27,47,569
heartbeats and recovery-node re-add), redesigned for the raw-TCP van:
SO_RCVTIMEO bounds every wait, the worker resends over a fresh connection
(servers dedup on (client_id, req_id)), the scheduler's heartbeat ledger
declares dead servers, and a replacement server re-registering under the
same id is picked up by worker reconnects.

Scenarios (the VERDICT's acceptance test): SIGKILL one of 2 servers
mid-run and observe either a clean, prompt error — or recovery once a
replacement registers.
"""
import multiprocessing as mp
import os
import time

import numpy as np

from test_ps import _env, _run_scheduler, _worker_body, _port_iter, NITEM, ITEM_LEN

# tight knobs so death is detected in seconds, not minutes
FAULT_ENV = {
    "DMLC_PS_RECV_TIMEOUT_MS": "2000",
    "DMLC_PS_MAX_RETRY": "3",
    "DMLC_PS_HEARTBEAT_MS": "300",
    "DMLC_PS_HEARTBEAT_TIMEOUT_MS": "1500",
}


def _run_server_fault(idx, port, n_workers, n_servers, stopfile,
                      restore_dir=None, extra_env=None):
    os.environ.update(_env("server", idx, port, n_workers, n_servers))
    os.environ.update(FAULT_ENV)
    if restore_dir is not None:
        os.environ["DMLC_PS_RESTORE_DIR"] = restore_dir
    if extra_env:
        os.environ.update(extra_env)
    from hetu_tpu.ps import server as srv
    srv.start_server_from_env()
    while not os.path.exists(stopfile):
        time.sleep(0.05)
    srv.stop_server()


def _worker_body_fault(rank, port, n_workers, n_servers, fn, tmpdir, result_q):
    os.environ.update(FAULT_ENV)
    _worker_body(rank, port, n_workers, n_servers, fn, tmpdir, result_q)


def _wait_file(path, timeout=60):
    t0 = time.time()
    while not os.path.exists(path):
        if time.time() - t0 > timeout:
            raise TimeoutError(f"waiting for {path}")
        time.sleep(0.05)


def _run_fault_cluster(worker_fn, orchestrate, tmpdir, restore_dir=None):
    """1 worker + 2 servers + scheduler; ``orchestrate(ctx, procs, env_port)``
    runs in the main process to inject faults (kill/restart servers)."""
    port = next(_port_iter)
    tmpdir = str(tmpdir)
    ctx = mp.get_context("spawn")
    stopfile = os.path.join(tmpdir, "stop_servers")
    sched = ctx.Process(target=_run_scheduler, args=(port, 1, 2))
    servers = [ctx.Process(target=_run_server_fault,
                           args=(i, port, 1, 2, stopfile, restore_dir))
               for i in range(2)]
    result_q = ctx.Queue()
    worker = ctx.Process(target=_worker_body_fault,
                         args=(0, port, 1, 2, worker_fn, tmpdir, result_q))
    sched.start()
    for s in servers:
        s.start()
    worker.start()
    try:
        orchestrate(ctx, {"servers": servers, "port": port,
                          "stopfile": stopfile, "tmpdir": tmpdir,
                          "restore_dir": restore_dir})
        rank, status, err = result_q.get(timeout=120)
        assert status == "ok", f"worker failed:\n{err}"
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        worker.join(timeout=20)
        for p in servers + [sched, worker]:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)


# ---------------------------------------------------------------------------
# scenario 1: server dies, stays dead -> clean prompt error, no hang
# ---------------------------------------------------------------------------

def _worker_clean_error(client, rank, tmpdir):
    client.InitTensor(0, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=1.5)
    out = client.Pull(0, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(0)
    np.testing.assert_allclose(out, 1.5)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "killed"))
    t0 = time.time()
    try:
        client.Pull(0, out)
        client.Wait(0)
        raise AssertionError("pull against a dead server did not raise")
    except RuntimeError as e:
        elapsed = time.time() - t0
        assert "unreachable" in str(e) or "timed out" in str(e), e
        # prompt: bounded by recv timeout x retries, not a forever-hang
        assert elapsed < 60, f"error took {elapsed:.0f}s"


def test_server_death_prompt_clean_error(tmp_path):
    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        open(os.path.join(env["tmpdir"], "killed"), "w").write("ok")

    _run_fault_cluster(_worker_clean_error, orchestrate, tmp_path)


# ---------------------------------------------------------------------------
# scenario 2: server dies, a replacement re-registers -> worker recovers
# ---------------------------------------------------------------------------

def _worker_recovers(client, rank, tmpdir):
    client.InitTensor(1, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=2.5)
    out = client.Pull(1, np.empty(NITEM * ITEM_LEN, np.float32))
    client.Wait(1)
    np.testing.assert_allclose(out, 2.5)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "restarted"))
    # the replacement server is empty: re-init (idempotent on the survivor,
    # creates the shard on the recovered one), then pull through the worker's
    # reconnect path
    client.InitTensor(1, sparse=False, length=NITEM * ITEM_LEN, width=1,
                      init_type="constant", init_a=2.5)
    out = client.Pull(1, out)
    client.Wait(1)
    np.testing.assert_allclose(out, 2.5)


def test_server_recovery_after_restart(tmp_path):
    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        # replacement under the same SERVER_ID: scheduler takes the
        # recovery re-add path and workers reconnect to it
        repl = ctx.Process(target=_run_server_fault,
                           args=(1, env["port"], 1, 2, env["stopfile"]))
        repl.start()
        env["servers"][1] = repl
        time.sleep(1.5)  # let it register + heartbeat
        open(os.path.join(env["tmpdir"], "restarted"), "w").write("ok")

    _run_fault_cluster(_worker_recovers, orchestrate, tmp_path)


# ---------------------------------------------------------------------------
# scenario 3: recovery RESTORES STATE — replacement server rebuilds its
# shard from the last ParamSave directory; the worker does NOT re-init
# (VERDICT weak#5; intent of reference van.cc:47 + psf/PSFunc.h:25-28)
# ---------------------------------------------------------------------------

def _worker_state_restored(client, rank, tmpdir):
    ckpt = os.path.join(tmpdir, "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    n = NITEM * ITEM_LEN
    rng = np.random.RandomState(3)
    client.InitTensor(2, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    # train: pushes move the param off its init value
    grad = rng.randn(n).astype(np.float32)
    client.Push(2, grad)
    client.Wait(2)
    buf = client.Pull(2, np.empty(n, np.float32))
    client.Wait(2)   # Pull fills the buffer only after Wait
    expected = buf.copy()
    assert np.abs(expected).max() > 0.1  # actually trained
    client.SaveParam(2, ckpt)
    client.Wait(2)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "restarted"))
    # NO re-init: the replacement restored its shard from the checkpoint
    out = client.Pull(2, np.empty(n, np.float32))
    client.Wait(2)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_server_recovery_restores_state(tmp_path):
    ckpt = os.path.join(str(tmp_path), "ckpt")

    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "phase1"))
        env["servers"][1].kill()
        env["servers"][1].join()
        repl = ctx.Process(
            target=_run_server_fault,
            args=(1, env["port"], 1, 2, env["stopfile"], env["restore_dir"]))
        repl.start()
        env["servers"][1] = repl
        time.sleep(1.5)
        open(os.path.join(env["tmpdir"], "restarted"), "w").write("ok")

    _run_fault_cluster(_worker_state_restored, orchestrate, tmp_path,
                       restore_dir=ckpt)


# ---------------------------------------------------------------------------
# High availability: continuous snapshots + PSSupervisor auto-respawn +
# worker failover (the full stack, no manual replacement, no re-init)
# ---------------------------------------------------------------------------

# worker-side failover: block-with-deadline through a server death and
# re-issue instead of raising
HA_WORKER_ENV = {
    "DMLC_PS_FAILOVER_DEADLINE_MS": "60000",
    "DMLC_PS_FAILOVER_POLL_MS": "200",
}


def _worker_body_ha(rank, port, n_workers, n_servers, fn, tmpdir, result_q):
    os.environ.update(FAULT_ENV)
    os.environ.update(HA_WORKER_ENV)
    _worker_body(rank, port, n_workers, n_servers, fn, tmpdir, result_q)


def _run_ha_cluster(worker_fn, orchestrate, tmpdir, *, snapshot_ms=150,
                    server1_extra=None, max_respawns=2):
    """1 worker + 2 snapshotting servers + scheduler + a real PSSupervisor.
    ``orchestrate(ctx, env)`` injects faults from the main process;
    ``env["kill"](i)`` SIGKILLs the CURRENT process of server i (the
    supervisor then respawns it from the freshest snapshot)."""
    from hetu_tpu.ps.supervisor import PSSupervisor
    port = next(_port_iter)
    tmpdir = str(tmpdir)
    snapdir = os.path.join(tmpdir, "snapshots")
    os.makedirs(snapdir, exist_ok=True)
    snap_env = {"DMLC_PS_SNAPSHOT_DIR": snapdir,
                "DMLC_PS_SNAPSHOT_MS": str(snapshot_ms)}
    ctx = mp.get_context("spawn")
    stopfile = os.path.join(tmpdir, "stop_servers")
    sched = ctx.Process(target=_run_scheduler, args=(port, 1, 2))
    servers = {}
    for i in range(2):
        extra = dict(snap_env)
        if i == 1 and server1_extra:
            extra.update(server1_extra)
        servers[i] = ctx.Process(target=_run_server_fault,
                                 args=(i, port, 1, 2, stopfile, None, extra))
    result_q = ctx.Queue()
    worker = ctx.Process(target=_worker_body_ha,
                         args=(0, port, 1, 2, worker_fn, tmpdir, result_q))
    sched.start()
    for s in servers.values():
        s.start()
    worker.start()

    def _respawn(i):
        p = ctx.Process(target=_run_server_fault,
                        args=(i, port, 1, 2, stopfile, snapdir, snap_env))
        p.start()
        return p

    def _kill(i):
        servers[i].kill()
        servers[i].join()

    # procs is held by reference: _kill's victim stays the supervisor's view
    sup = PSSupervisor("127.0.0.1", port, 2, _respawn, procs=servers,
                       poll_s=0.3, max_respawns=max_respawns)
    sup.start()
    try:
        orchestrate(ctx, {"servers": servers, "port": port,
                          "stopfile": stopfile, "tmpdir": tmpdir,
                          "snapdir": snapdir, "kill": _kill,
                          "supervisor": sup})
        rank, status, err = result_q.get(timeout=120)
        assert status == "ok", f"worker failed:\n{err}"
        return sup
    finally:
        sup.stop()
        with open(stopfile, "w") as f:
            f.write("stop")
        worker.join(timeout=20)
        for p in list(servers.values()) + [sched, worker]:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)


# ---------------------------------------------------------------------------
# scenario 4 (the acceptance test): SIGKILL one of two servers mid-training
# with snapshots + supervisor + failover on. The run completes WITHOUT a
# training-loop restart, the recovered shard reports exactly how many
# updates it lost (bounded by what was pushed after the covering snapshot),
# and final params match the fault-free oracle up to those lost updates.
# ---------------------------------------------------------------------------

K_BEFORE, L_AT_RISK, M_AFTER = 5, 3, 4


def _worker_ha_lost_updates(client, rank, tmpdir):
    n = NITEM  # dense split: server 0 owns [0, n/2), server 1 owns [n/2, n)
    client.InitTensor(11, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    grad = np.ones(n, np.float32)  # sgd +=: value == applied update count
    for _ in range(K_BEFORE):
        client.Push(11, grad)
        client.Wait(11)
    # wait until server 1's continuous snapshot covers all K updates
    deadline = time.time() + 30
    while client.ServerStats(1)["snapshot_updates"] < K_BEFORE:
        assert time.time() < deadline, "no covering snapshot appeared"
        time.sleep(0.05)
    # L more ACKED updates land after the covering snapshot: at risk
    for _ in range(L_AT_RISK):
        client.Push(11, grad)
        client.Wait(11)
    open(os.path.join(tmpdir, "push_done"), "w").write("ok")
    _wait_file(os.path.join(tmpdir, "killed"))
    # keep training THROUGH the death: failover blocks until the
    # supervisor's replacement registers, then transparently re-issues
    for _ in range(M_AFTER):
        client.Push(11, grad)
        client.Wait(11)
    out = client.Pull(11, np.empty(n, np.float32))
    client.Wait(11)
    st = client.ServerStats(1)
    # lost-update accounting: the snapshot's counter stamp tells the
    # replacement (and us) where it resumed
    assert st["restored_updates"] >= K_BEFORE, st
    lost = (K_BEFORE + L_AT_RISK) - st["restored_updates"]
    assert 0 <= lost <= L_AT_RISK, st
    # the replacement applied exactly the re-issued/new updates: counter
    # algebra has no room for a double-apply
    assert st["updates"] == st["restored_updates"] + M_AFTER, st
    total = K_BEFORE + L_AT_RISK + M_AFTER
    np.testing.assert_allclose(out[:n // 2], total)  # survivor shard
    # recovered shard: the counter stamp is captured BEFORE the param files
    # (it never OVER-claims coverage), so a push landing mid-snapshot can be
    # in the restored shard yet not in the stamp — the true value sits in
    # [oracle - reported_lost, oracle]. Both HA guarantees are exactly
    # these bounds: reported lost never understates, and no double-apply
    # can push the value past the fault-free oracle.
    vals = np.unique(out[n // 2:])
    assert vals.size == 1, vals              # one consistent shard state
    v = float(vals[0])
    assert total - lost <= v <= total, (v, total, lost, st)
    np.save(os.path.join(tmpdir, "lost.npy"), np.asarray([lost]))


def test_ps_ha_snapshot_supervisor_failover(tmp_path):
    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "push_done"))
        env["kill"](1)
        open(os.path.join(env["tmpdir"], "killed"), "w").write("ok")

    sup = _run_ha_cluster(_worker_ha_lost_updates, orchestrate, tmp_path)
    assert sup.respawns == 1 and sup.fatal is None
    lost = int(np.load(os.path.join(str(tmp_path), "lost.npy"))[0])
    assert 0 <= lost <= L_AT_RISK


# ---------------------------------------------------------------------------
# scenario 5 (dedup proof): the server dies mid-SparsePush — AFTER applying
# the update and snapshotting it (data + resend-dedup ledger) but BEFORE
# sending the ack. The worker re-issues the same req_id through failover;
# the restored ledger answers it WITHOUT re-applying.
# ---------------------------------------------------------------------------

def _worker_dedup_proof(client, rank, tmpdir):
    client.InitTensor(12, sparse=True, length=NITEM, width=4,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    row = np.array([NITEM - 10], np.int64)  # owned by server 1
    g = np.ones((1, 4), np.float32)
    for _ in range(2):
        client.SparsePush(12, row, g)
        client.Wait(12)
    # 3rd push trips the server's gated exit-after-updates hook: it applies,
    # snapshots, and _Exit()s without acking — this Wait returns only after
    # the failover re-issue is answered by the replacement
    client.SparsePush(12, row, g)
    client.Wait(12)
    out = client.SparsePull(12, row, np.empty((1, 4), np.float32))
    client.Wait(12)
    np.testing.assert_allclose(out, 3.0)  # NOT 4.0: no double-apply
    st = client.ServerStats(1)
    assert st["restored_updates"] == 3 and st["updates"] == 3, st
    # the next real update still lands exactly once
    client.SparsePush(12, row, g)
    client.Wait(12)
    out = client.SparsePull(12, row, np.empty((1, 4), np.float32))
    client.Wait(12)
    np.testing.assert_allclose(out, 4.0)


def test_ps_ha_no_double_apply_after_reissue(tmp_path):
    def orchestrate(ctx, env):
        pass  # the server kills itself (hook); the supervisor does the rest

    sup = _run_ha_cluster(
        _worker_dedup_proof, orchestrate, tmp_path,
        # long period: only the hook's final synchronous snapshot exists, so
        # the restored ledger provably answered the re-issue
        snapshot_ms=60000,
        server1_extra={"HETU_PS_TEST_EXIT_AFTER_UPDATES": "3:snap",
                       "HETU_TEST_MODE": "1"})
    assert sup.respawns == 1 and sup.fatal is None


# ---------------------------------------------------------------------------
# scenario 5b: the WORKER restarts (PR 1's supervise()/heturun
# --max-restarts) against LIVE servers whose per-client dedup slots
# survive. The fresh incarnation reuses its rank's client_id, so if its
# req_ids restarted at 1 they would sit below the slot's last_id and every
# request would be silently dropped as a pre-reconnect straggler — req_ids
# are seeded from the wall clock (worker.h boot_req_id) precisely so each
# incarnation starts above anything the previous one issued.
# ---------------------------------------------------------------------------

def _worker_restart_phase1(client, rank, tmpdir):
    n = NITEM * ITEM_LEN
    client.InitTensor(13, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    client.Push(13, np.full(n, 1.0, np.float32))
    client.Wait(13)
    buf = client.Pull(13, np.empty(n, np.float32))
    client.Wait(13)
    np.save(os.path.join(tmpdir, "after_a.npy"), buf)
    open(os.path.join(tmpdir, "phase1"), "w").write("ok")
    # crash WITHOUT close(): the realistic restart — the servers keep
    # serving and keep this client_id's dedup slot with a high last_id
    os._exit(1)


def _worker_restart_phase2(client, rank, tmpdir):
    n = NITEM * ITEM_LEN
    # a restarted trainer re-runs its init path: re-init of a sized param
    # is a server-side no-op, the trained state must survive
    client.InitTensor(13, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    after_a = np.load(os.path.join(tmpdir, "after_a.npy"))
    out = client.Pull(13, np.empty(n, np.float32))
    client.Wait(13)
    np.testing.assert_allclose(out, after_a, rtol=1e-6)
    # one more identical sgd step moves the param by the same delta
    client.Push(13, np.full(n, 1.0, np.float32))
    client.Wait(13)
    out = client.Pull(13, np.empty(n, np.float32))
    client.Wait(13)
    np.testing.assert_allclose(out, 2 * after_a, rtol=1e-6)


def test_restarted_worker_served_despite_dedup_slot(tmp_path):
    port = next(_port_iter)
    tmpdir = str(tmp_path)
    ctx = mp.get_context("spawn")
    stopfile = os.path.join(tmpdir, "stop_servers")
    sched = ctx.Process(target=_run_scheduler, args=(port, 1, 2))
    servers = [ctx.Process(target=_run_server_fault,
                           args=(i, port, 1, 2, stopfile))
               for i in range(2)]
    result_q = ctx.Queue()
    a = ctx.Process(target=_worker_body_fault,
                    args=(0, port, 1, 2, _worker_restart_phase1, tmpdir,
                          result_q))
    sched.start()
    for s in servers:
        s.start()
    a.start()
    workers = [a]
    try:
        _wait_file(os.path.join(tmpdir, "phase1"))
        a.join(timeout=30)
        assert a.exitcode == 1, a.exitcode   # crashed, never checked out
        b = ctx.Process(target=_worker_body_fault,
                        args=(0, port, 1, 2, _worker_restart_phase2, tmpdir,
                              result_q))
        b.start()
        workers.append(b)
        rank, status, err = result_q.get(timeout=120)
        assert status == "ok", f"restarted worker failed:\n{err}"
        b.join(timeout=20)
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        for p in servers + [sched] + workers:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)


# ---------------------------------------------------------------------------
# scenario 6: bounded scheduler teardown wait. The clock arms at the FIRST
# checkout (training itself may run arbitrarily long), re-arms on each
# further one, and a progress-free window exits with a diagnostic naming
# the ranks that never checked out.
# ---------------------------------------------------------------------------

def _run_sched_bounded(port, n_workers, n_servers, timeout_ms, out_file):
    os.environ.update(_env("scheduler", 0, port, n_workers, n_servers))
    os.environ.update(FAULT_ENV)
    os.environ["DMLC_PS_SCHED_WAIT_TIMEOUT_MS"] = str(timeout_ms)
    from hetu_tpu.ps import server as srv
    srv.start_scheduler_from_env()
    try:
        srv.scheduler_wait()
    except RuntimeError as e:
        srv.stop_scheduler()
        open(out_file, "w").write(str(e))
        raise SystemExit(1)
    srv.stop_scheduler()
    open(out_file, "w").write("clean")


def _checkout_worker(rank, port, n_workers, n_servers, delay_s,
                     checkout=True):
    os.environ.update(_env("worker", rank, port, n_workers, n_servers))
    os.environ.update(FAULT_ENV)
    from hetu_tpu.ps.client import PSClient
    c = PSClient.from_env()
    time.sleep(delay_s)
    if not checkout:
        os._exit(0)  # register, then die WITHOUT the kShutdown checkout
    c.close()


def _sched_wait_round(tmp_path, tag, worker_specs, timeout_ms):
    """worker_specs: [(delay_s, checkout)] — ALL workers must register
    (cluster bringup blocks on the announced topology), but a
    checkout=False one dies without sending kShutdown."""
    n_workers = len(worker_specs)
    port = next(_port_iter)
    ctx = mp.get_context("spawn")
    stopfile = os.path.join(str(tmp_path), f"stop_{tag}")
    out = os.path.join(str(tmp_path), f"sched_{tag}")
    sched = ctx.Process(target=_run_sched_bounded,
                        args=(port, n_workers, 1, timeout_ms, out))
    server = ctx.Process(target=_run_server_fault,
                         args=(0, port, n_workers, 1, stopfile))
    workers = [ctx.Process(target=_checkout_worker,
                           args=(r, port, n_workers, 1, d, co))
               for r, (d, co) in enumerate(worker_specs)]
    sched.start()
    server.start()
    for w in workers:
        w.start()
    try:
        for w in workers:
            w.join(timeout=60)
        open(stopfile, "w").write("stop")  # server checks out too
        server.join(timeout=30)
        sched.join(timeout=60)
        assert sched.exitcode is not None, "scheduler still waiting"
        return sched.exitcode, open(out).read()
    finally:
        for p in workers + [server, sched]:
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)


def test_sched_wait_clock_arms_at_teardown_not_startup(tmp_path):
    # quiet "training" phase 4x longer than the window, then everyone checks
    # out: a startup-armed timeout would kill this healthy run mid-training
    rc, msg = _sched_wait_round(tmp_path, "healthy", [(3.2, True)], 800)
    assert rc == 0 and msg == "clean", (rc, msg)


def test_sched_wait_timeout_names_never_checked_out_ranks(tmp_path):
    # worker 1 registers (bringup completes) then dies WITHOUT checking
    # out; worker 0 and the server check out (arming + re-arming the
    # clock), then no progress -> diagnostic names the missing rank
    rc, msg = _sched_wait_round(tmp_path, "missing",
                                [(0.3, True), (0.1, False)], 1500)
    assert rc == 1, (rc, msg)
    assert "never checked out" in msg and "workers [1]" in msg, msg
