"""Hybrid comm mode at MULTI-HOST scale: a 2-process jax.distributed world
(dense grads via Gloo collectives on the global mesh) where each process is
also a live PS worker (sparse embedding rows pulled/pushed per step, BSP).

This is the reference's flagship deployment story — Hybrid
(optimizer.py:129-136) on a multi-node cluster — reproduced with real
processes: PS scheduler + server (OS-assigned port, registered via the
scheduler) + 2 dual-role workers, launched through the shared
``test_multihost._run_world`` harness.
"""
import os

import pytest

from test_multihost import _run_world


def test_two_host_hybrid_dense_gloo_sparse_ps(tmp_path):
    from hetu_tpu.runner import _get_available_port
    from hetu_tpu.ps.local_cluster import (_ps_env, reap_light_procs,
                                           spawn_light_role,
                                           spawn_light_server)

    ps_port = _get_available_port("127.0.0.1")
    stopfile = str(tmp_path / "stop")
    base = _ps_env(ps_port, 2, 1)
    procs = [spawn_light_role("scheduler", base),
             spawn_light_server(0, base, stopfile)]
    try:
        results = _run_world(
            nproc=2, timeout=240, script="mh_hybrid_worker.py",
            extra_env={"DMLC_PS_ROOT_URI": "127.0.0.1",
                       "DMLC_PS_ROOT_PORT": str(ps_port),
                       "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "1",
                       "DMLC_ROLE": "worker"},
            per_worker_env=lambda pid: {"WORKER_ID": str(pid)})
    finally:
        with open(stopfile, "w") as f:
            f.write("stop")
        reap_light_procs(procs)

    r0 = next(r for r in results if r["pid"] == 0)
    r1 = next(r for r in results if r["pid"] == 1)
    # trained: loss dropped hard; dense params identical across hosts
    # (GSPMD mean + same update), PS table state identical (one server)
    assert r0["final_loss"] < 0.3 * r0["first_loss"], r0
    assert r0["final_loss"] == pytest.approx(r1["final_loss"], rel=1e-4)
    assert r0["w_sum"] == pytest.approx(r1["w_sum"], rel=1e-5)
    assert r0["table_digest"] == pytest.approx(r1["table_digest"], rel=1e-5)
    assert r0["table_moved"] > 1e-4  # embeddings actually trained on the PS
