"""hetukern (docs/KERNELS.md): the Pallas kernel tier.

ISSUE 12 acceptance pinned here:
- every kernel has an interpret-mode equality test vs its XLA fallback
  (force vs off through the REAL registry dispatch, both sides under jit
  so they compile through the same XLA pipeline);
- the registry's mode semantics: off = pre-hetukern expression verbatim,
  auto = per-shape fallback (always fallback off-TPU), force = kernel or
  KernelEligibilityError;
- kernels="off" is bit-identical at the executor level (off vs the
  default auto on CPU train the same bits, with zero pallas dispatches);
- the PS sparse-push dedup-sum (sort + reduceat) equals the old
  np.add.at scatter EXACTLY on duplicate-heavy indices;
- the PS-push rows route: an explicit embedding_lookup_gradient_op
  consumed by a PS push skips the dense zeros-table scatter and hands the
  runtime (rows, grads).
"""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu import comm_quant
from hetu_tpu.kernels import (
    registry, embed_grad, csr_spmm, quant_comm, fused_opt,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_stats():
    registry.reset_stats()
    yield
    registry.reset_stats()


def _force(fn):
    @jax.jit
    def wrapped(*a):
        with registry.active("force"):
            return fn(*a)
    return wrapped


def _off(fn):
    @jax.jit
    def wrapped(*a):
        with registry.active("off"):
            return fn(*a)
    return wrapped


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_registry_modes_and_counters():
    rng = np.random.RandomState(0)
    sv = jnp.asarray(rng.randn(128, 128).astype(np.float32))
    seg = jnp.zeros((128,), jnp.int32)
    with registry.active("off"):
        registry.dispatch("fused_embed_grad", sv, seg)
    with registry.active("auto"):     # CPU: eligible shape still falls back
        registry.dispatch("fused_embed_grad", sv, seg)
    with registry.active("force"):
        registry.dispatch("fused_embed_grad", sv, seg)
    s = registry.dispatch_stats()
    assert s[("fused_embed_grad", "off")] == 1
    assert s[("fused_embed_grad", "fallback")] == 1
    # force-mode servings count under the distinct "forced" path so the
    # lint's auto-only fallback_ratio cannot be diluted by smoke runs
    assert s[("fused_embed_grad", "forced")] == 1
    assert registry.fallback_ratio("fused_embed_grad") == 1.0


def test_registry_force_ineligible_raises():
    bad = jnp.ones((16, 20), jnp.float32)      # dim 20: not lane-aligned
    seg = jnp.zeros((16,), jnp.int32)
    with registry.active("force"):
        with pytest.raises(registry.KernelEligibilityError) as e:
            registry.dispatch("fused_embed_grad", bad, seg)
    assert "fused_embed_grad" in str(e.value)
    # the same shape under auto falls back per-call instead
    with registry.active("auto"):
        out = registry.dispatch("fused_embed_grad", bad, seg)
    assert out.shape == (16, 20)
    assert registry.dispatch_stats()[("fused_embed_grad", "fallback")] == 1


def test_registry_mode_resolution(monkeypatch):
    assert registry.resolve_mode("force") == "force"
    monkeypatch.setenv("HETU_KERNELS", "off")
    assert registry.resolve_mode(None) == "off"
    monkeypatch.delenv("HETU_KERNELS")
    assert registry.resolve_mode(None) == "auto"
    with pytest.raises(ValueError):
        registry.resolve_mode("maybe")
    # scopes nest, innermost wins
    with registry.active("off"):
        with registry.active("force"):
            assert registry.current_mode() == "force"
        assert registry.current_mode() == "off"


def test_dispatch_counter_exports_to_telemetry(tmp_path):
    from hetu_tpu import telemetry as tel
    t = tel.activate("metrics", out_dir=str(tmp_path))
    try:
        sv = jnp.ones((128, 128), jnp.float32)
        with registry.active("force"):
            registry.dispatch("fused_embed_grad", sv,
                              jnp.zeros((128,), jnp.int32))
        snap = t.metrics.snapshot()
        key = ('hetu_kernel_dispatch_total'
               '{kernel="fused_embed_grad",path="forced"}')
        assert snap.get(key) == 1.0
    finally:
        tel.shutdown()


# ---------------------------------------------------------------------------
# kernel 1: fused sparse embedding grad
# ---------------------------------------------------------------------------

def test_embed_grad_rows_equality_duplicate_heavy():
    rng = np.random.RandomState(0)
    vec = jnp.asarray(rng.randn(4, 64, 128).astype(np.float32))
    # duplicate-heavy: 256 lookups over only 17 distinct rows
    idx = jnp.asarray(rng.randint(0, 17, (4, 64)))
    f = _force(lambda v, i: embed_grad.embed_grad_rows(v, i, 1000))
    o = _off(lambda v, i: embed_grad.embed_grad_rows(v, i, 1000))
    rows_f, grads_f, cnt_f = f(vec, idx)
    rows_o, grads_o, cnt_o = o(vec, idx)
    assert int(cnt_f) == int(cnt_o) == 17
    assert np.array_equal(np.asarray(rows_f), np.asarray(rows_o))
    # sentinel-padded tail: vocab sentinel + zero grads
    assert np.all(np.asarray(rows_f)[17:] == 1000)
    assert np.all(np.asarray(grads_f)[17:] == 0.0)
    np.testing.assert_allclose(np.asarray(grads_f), np.asarray(grads_o),
                               atol=1e-4)
    # and the sums are RIGHT: compare against a numpy oracle
    fi = np.asarray(idx).reshape(-1)
    fv = np.asarray(vec).reshape(-1, 128)
    want = np.zeros((17, 128), np.float32)
    for r, v in zip(fi, fv):
        want[r] += v
    np.testing.assert_allclose(np.asarray(grads_o)[:17], want, atol=1e-4)


def test_embed_grad_dense_off_is_pre_hetukern_bit_identical():
    rng = np.random.RandomState(1)
    vec = jnp.asarray(rng.randn(32, 128).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 50, (32,)))
    shape = (100, 128)
    g = ht.embedding_lookup_gradient_op(
        ht.Variable(name="v", value=np.asarray(vec), trainable=False),
        ht.Variable(name="i", value=np.asarray(idx), dtype=np.int64,
                    trainable=False), shape)
    with registry.active("off"):
        got = g.fn(vec, idx)
    want = embed_grad.embed_grad_dense_xla(vec, idx, shape)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_embed_grad_dense_force_matches_fallback():
    rng = np.random.RandomState(2)
    vec = jnp.asarray(rng.randn(128, 128).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 33, (128,)))
    shape = (64, 128)
    f = _force(lambda v, i: embed_grad.embed_grad_dense(v, i, shape))
    want = embed_grad.embed_grad_dense_xla(vec, idx, shape)
    np.testing.assert_allclose(np.asarray(f(vec, idx)), np.asarray(want),
                               atol=1e-4)


# ---------------------------------------------------------------------------
# kernel 2: CSR spmm
# ---------------------------------------------------------------------------

def test_csr_spmm_equality():
    rng = np.random.RandomState(0)
    nnz, k, n, f = 500, 16, 8, 128
    vals = jnp.asarray(rng.randn(nnz).astype(np.float32))
    rows = jnp.asarray(rng.randint(0, n, nnz).astype(np.int32))
    cols = jnp.asarray(rng.randint(0, k, nnz).astype(np.int32))
    b = jnp.asarray(rng.randn(k, f).astype(np.float32))
    ff = _force(lambda v, r, c, bb: csr_spmm.coo_matmat(v, r, c, n, bb))
    oo = _off(lambda v, r, c, bb: csr_spmm.coo_matmat(v, r, c, n, bb))
    np.testing.assert_allclose(np.asarray(ff(vals, rows, cols, b)),
                               np.asarray(oo(vals, rows, cols, b)),
                               atol=1e-4)


def test_csr_matvec_equality():
    rng = np.random.RandomState(3)
    nnz, k, n = 200, 16, 8
    vals = jnp.asarray(rng.randn(nnz).astype(np.float32))
    rows = jnp.asarray(rng.randint(0, n, nnz).astype(np.int32))
    cols = jnp.asarray(rng.randint(0, k, nnz).astype(np.int32))
    x = jnp.asarray(rng.randn(k).astype(np.float32))
    ff = _force(lambda v, r, c, xx: csr_spmm.coo_matvec(v, r, c, n, xx))
    oo = _off(lambda v, r, c, xx: csr_spmm.coo_matvec(v, r, c, n, xx))
    np.testing.assert_allclose(np.asarray(ff(vals, rows, cols, x)),
                               np.asarray(oo(vals, rows, cols, x)),
                               atol=1e-4)


def test_csr_op_auto_on_cpu_is_fallback():
    """The graph-level csrmm_op under the default mode on CPU must count a
    fallback dispatch, never a pallas one (nothing in the existing test
    matrix changes behavior by default)."""
    from tests.test_ops import run_graph  # same-suite helper
    from hetu_tpu.ndarray import ND_Sparse_Array
    rng = np.random.RandomState(0)
    dense = (rng.rand(6, 5) < 0.4) * rng.randn(6, 5)
    r, c = np.nonzero(dense)
    spv = ND_Sparse_Array(dense[r, c].astype(np.float32), r, c, 6, 5)
    a = ht.graph.ops.matmul.SparseInputOp()
    m = ht.Variable(name="m", value=rng.randn(5, 4).astype(np.float32),
                    trainable=False)
    out = run_graph(ht.csrmm_op(a, m), {a: spv, m: m.value})
    np.testing.assert_allclose(out, dense @ m.value, atol=1e-5)
    s = registry.dispatch_stats()
    assert s.get(("csr_spmm", "pallas")) is None
    assert s.get(("csr_spmm", "fallback"), 0) >= 1


# ---------------------------------------------------------------------------
# kernel 3: quant-fused comm legs (wire payloads must be bit-identical)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_quant_blocks_bit_identical(mode):
    if mode == "fp8" and comm_quant.fp8_dtype() is None:
        pytest.skip("no float8_e4m3fn in this jax build")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4096).astype(np.float32))
    ff = _force(lambda v: quant_comm.quantize_blocks(v, 256, mode))
    oo = _off(lambda v: comm_quant.quantize_blocks(v, 256, mode))
    qf, sf, nf = ff(x)
    qo, so, no = oo(x)
    assert nf == no
    assert np.array_equal(np.asarray(sf), np.asarray(so))
    assert np.array_equal(np.asarray(qf).view(np.uint8),
                          np.asarray(qo).view(np.uint8))
    # dequant leg, same contract
    df = _force(lambda q, s: quant_comm.dequantize_blocks(q, s, 4096, 256))
    do = _off(lambda q, s: comm_quant.dequantize_blocks(q, s, 4096, 256))
    assert np.array_equal(np.asarray(df(qf, sf)), np.asarray(do(qo, so)))


def test_quant_blocks_all_zero_block_and_padding():
    x = np.zeros(300, np.float32)       # 300 pads to 2 blocks of 256
    x[0] = 3.0
    xj = jnp.asarray(x)
    ff = _force(lambda v: quant_comm.quantize_blocks(v, 256, "int8"))
    q, s, n = ff(xj)
    qo, so, no = comm_quant.quantize_blocks(xj, 256, "int8")
    assert n == no == 300
    assert np.array_equal(np.asarray(q), np.asarray(qo))
    assert np.asarray(s)[1] == 0.0      # all-zero block stores scale 0


# ---------------------------------------------------------------------------
# kernel 4: fused optimizer step
# ---------------------------------------------------------------------------

class _AdamCfg:
    beta1, beta2, epsilon, weight_decay, l2reg = 0.9, 0.999, 1e-7, 0.01, 0.0


def test_fused_adam_exact_over_steps():
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(16, 128).astype(np.float32))
    slot_f = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
              "t": jnp.zeros((), jnp.float32)}
    slot_o = {k: v for k, v in slot_f.items()}
    pf, po = p, p
    ff = _force(lambda pp, gg, mm, vv, tt: fused_opt.adam_step(
        _AdamCfg, pp, gg, {"m": mm, "v": vv, "t": tt}, 0.01))
    oo = _off(lambda pp, gg, mm, vv, tt: fused_opt.adam_step(
        _AdamCfg, pp, gg, {"m": mm, "v": vv, "t": tt}, 0.01))
    for step in range(3):
        g = jnp.asarray(rng.randn(16, 128).astype(np.float32))
        pf, slot_f = ff(pf, g, slot_f["m"], slot_f["v"], slot_f["t"])
        po, slot_o = oo(po, g, slot_o["m"], slot_o["v"], slot_o["t"])
    assert np.array_equal(np.asarray(pf), np.asarray(po))
    for k in ("m", "v", "t"):
        assert np.array_equal(np.asarray(slot_f[k]), np.asarray(slot_o[k]))
    assert float(slot_f["t"]) == 3.0


def test_fused_sgd_exact_with_l2():
    class _S:
        l2reg = 0.01
    rng = np.random.RandomState(1)
    p = jnp.asarray(rng.randn(8, 128).astype(np.float32))
    g = jnp.asarray(rng.randn(8, 128).astype(np.float32))
    ff = _force(lambda pp, gg: fused_opt.sgd_step(_S, pp, gg, 0.05))
    oo = _off(lambda pp, gg: fused_opt.sgd_step(_S, pp, gg, 0.05))
    assert np.array_equal(np.asarray(ff(p, g)), np.asarray(oo(p, g)))


def test_fused_adam_odd_shape_padded_exact():
    """Odd-sized params (biases) are eligible — the kernel pads to the
    8x128 tile internally and slices back; still exact vs the XLA rule."""
    rng = np.random.RandomState(4)
    p = jnp.asarray(rng.randn(5, 7).astype(np.float32))
    g = jnp.asarray(rng.randn(5, 7).astype(np.float32))
    slot = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
            "t": jnp.zeros((), jnp.float32)}
    ff = _force(lambda pp, gg: fused_opt.adam_step(_AdamCfg, pp, gg,
                                                   slot, 0.01))
    oo = _off(lambda pp, gg: fused_opt.adam_step(_AdamCfg, pp, gg,
                                                 slot, 0.01))
    pf, sf = ff(p, g)
    po, so = oo(p, g)
    assert pf.shape == (5, 7)
    # slots are exact; the param update may differ by 1 ulp — XLA makes
    # different FMA decisions for the padded-shape program (the same
    # compile-level noise class the jit-vs-eager gotcha documents)
    assert np.array_equal(np.asarray(sf["m"]), np.asarray(so["m"]))
    assert np.array_equal(np.asarray(sf["v"]), np.asarray(so["v"]))
    np.testing.assert_allclose(np.asarray(pf), np.asarray(po),
                               atol=1e-6, rtol=0)
    class _S:
        l2reg = 0.0
    sgf = _force(lambda pp, gg: fused_opt.sgd_step(_S, pp, gg, 0.05))(p, g)
    sgo = _off(lambda pp, gg: fused_opt.sgd_step(_S, pp, gg, 0.05))(p, g)
    np.testing.assert_allclose(np.asarray(sgf), np.asarray(sgo),
                               atol=1e-6, rtol=0)


def test_fused_adam_odd_shape_falls_back_in_auto():
    p = jnp.ones((5, 7), jnp.float32)
    slot = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
            "t": jnp.zeros((), jnp.float32)}
    with registry.active("auto"):
        new_p, new_slot = fused_opt.adam_step(_AdamCfg, p,
                                              jnp.ones_like(p), slot, 0.01)
    assert new_p.shape == (5, 7)
    assert registry.dispatch_stats()[("fused_adam", "fallback")] == 1


# ---------------------------------------------------------------------------
# executor level: off is bit-identical, force trains
# ---------------------------------------------------------------------------

def _mlp_executor(kernels, width=128, seed=7):
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w1 = ht.init.random_normal((width, width), stddev=0.05, name="w1")
    w2 = ht.init.random_normal((width, 8), stddev=0.05, name="w2")
    h = ht.relu_op(ht.matmul_op(x, w1))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y_), [0])
    opt = ht.optim.AdamOptimizer(0.01).minimize(loss)
    ex = ht.Executor({"train": [loss, opt]}, ctx=ht.cpu(0), seed=seed,
                     kernels=kernels)
    return ex, x, y_


def _train(ex, x, y_, steps=4, width=128):
    rng = np.random.RandomState(0)
    bx = rng.randn(16, width).astype(np.float32)
    by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, 16)]
    losses = []
    for _ in range(steps):
        losses.append(float(np.mean(
            ex.run("train", feed_dict={x: bx, y_: by})[0].asnumpy())))
    params = {n.name: np.asarray(ex.state["params"][id(n)])
              for n in ex.param_nodes}
    return losses, params


def test_executor_off_bit_identical_to_default_auto_on_cpu():
    """kernels='off' must train the same BITS as the default (auto) on
    CPU — auto's off-TPU fallback IS the pre-hetukern expression — and
    the dispatch counter must show zero pallas servings either way."""
    ex_off, x1, y1 = _mlp_executor("off")
    l_off, p_off = _train(ex_off, x1, y1)
    registry.reset_stats()
    ex_auto, x2, y2 = _mlp_executor("auto")
    l_auto, p_auto = _train(ex_auto, x2, y2)
    assert l_off == l_auto
    for k in p_off:
        assert np.array_equal(p_off[k], p_auto[k])
    s = registry.dispatch_stats()
    assert not any(path == "pallas" for _k, path in s)
    assert s.get(("fused_adam", "fallback"), 0) >= 1


def test_executor_force_trains_and_dispatches_pallas():
    ex_f, xf, yf = _mlp_executor("force")
    l_f, p_f = _train(ex_f, xf, yf)
    ex_o, xo, yo = _mlp_executor("off")
    l_o, p_o = _train(ex_o, xo, yo)
    # interpret-mode kernels inside the same jit pipeline: the fused-adam
    # math is the same expression sequence, losses agree to f32 noise
    np.testing.assert_allclose(l_f, l_o, atol=1e-5)
    assert registry.dispatch_stats()[("fused_adam", "forced")] >= 1


def test_hetuconfig_rejects_bad_kernels_mode():
    x = ht.Variable(name="x", trainable=False)
    with pytest.raises(ValueError, match="kernels"):
        ht.Executor({"d": [ht.relu_op(x)]}, ctx=ht.cpu(0),
                    kernels="sometimes")


# ---------------------------------------------------------------------------
# satellite: PS dedup-sum sort+reduceat == np.add.at, exactly
# ---------------------------------------------------------------------------

def test_ps_dedup_sum_reduceat_exact():
    from hetu_tpu.graph.ps_runtime import _dedup_sum_rows
    rng = np.random.RandomState(0)
    # duplicate-heavy (zipf-ish): 5000 pushes over ~40 distinct rows
    flat_idx = (rng.zipf(1.2, 5000) % 40).astype(np.int64)
    g = rng.randn(5000, 16).astype(np.float32)
    uniq, inv = np.unique(flat_idx, return_inverse=True)
    want = np.zeros((uniq.size, 16), np.float32)
    np.add.at(want, inv, g)                      # the old scatter loop
    got_idx, got = _dedup_sum_rows(flat_idx, g)
    assert got.dtype == np.float32
    assert np.array_equal(got_idx, uniq)
    # reduceat sums pairwise (more accurate than the sequential scatter):
    # equal to the old path within f32 rounding, and at least as close to
    # the float64 oracle
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    oracle = np.zeros((uniq.size, 16), np.float64)
    np.add.at(oracle, inv, g.astype(np.float64))
    assert (np.abs(got - oracle).max()
            <= np.abs(want - oracle).max() + 1e-6)
    # no-duplicate fast path: inputs pass through untouched
    ni = np.arange(8, dtype=np.int64)
    ng = rng.randn(8, 16).astype(np.float32)
    oi, og = _dedup_sum_rows(ni, ng)
    assert oi is ni and og is ng


# ---------------------------------------------------------------------------
# satellite: PS-push rows route (no dense zeros-table on the push path)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ps_push_rows_route():
    from hetu_tpu.ps.local_cluster import local_cluster
    from hetu_tpu.graph.ops.embedding import IndexedRows
    vocab, dim = 50, 8
    with local_cluster(n_servers=1, n_workers=1):
        table = ht.init.zeros((vocab, dim), name="emb_rows_route",
                              is_embed=True)
        idx = ht.Variable(name="idx", dtype=np.int64, trainable=False)
        vec = ht.Variable(name="vec", trainable=False)
        look = ht.embedding_lookup_op(table, idx)
        loss = ht.reduce_mean_op(look, [0, 1])
        g = ht.embedding_lookup_gradient_op(vec, idx, (vocab, dim))
        push = ht.parameterServerCommunicate_op(g, ps_id=table.name)
        ex = ht.Executor({"train": [loss, push]}, ctx=ht.cpu(0),
                         comm_mode="PS", seed=0, prefetch=False)
        try:
            # the rewire flipped the grad op into rows mode
            assert g.rows_mode is True
            assert push.ps_param_node is table
            bi = np.array([3, 7, 3, 9], np.int64)     # duplicate row 3
            bv = np.arange(4 * dim, dtype=np.float32).reshape(4, dim)
            ex.run("train", feed_dict={idx: bi, vec: bv})
            # the traced push output is the compact rows pair
            grad_out = ex.subexecutors["train"].ps_comm_ops
            assert len(grad_out) == 1
            ex.ps_runtime.drain()
            p = ex.ps_runtime.params[id(table)]
            got = ex.ps_runtime.pull_sparse_rows(
                p, np.array([3, 7, 9, 0], np.int64))
            # server-side prescaled SGD: w += -lr * summed_grad
            lr = ex.ps_runtime._prescale_lr(0)
            want3 = -(bv[0] + bv[2]) * lr
            np.testing.assert_allclose(got[0], want3, atol=1e-5)
            np.testing.assert_allclose(got[1], -bv[1] * lr, atol=1e-5)
            np.testing.assert_allclose(got[2], -bv[3] * lr, atol=1e-5)
            np.testing.assert_allclose(got[3], np.zeros(dim), atol=0)

            # guard: a grad op with ANOTHER consumer (here an eval
            # target needing the dense table) must stay dense — flipping
            # it would hand that consumer an IndexedRows pair
            os.environ["HETU_PS_ID_BASE"] = "1000"
            table2 = ht.init.zeros((vocab, dim), name="emb_dense_kept",
                                   is_embed=True)
            idx2 = ht.Variable(name="idx2", dtype=np.int64,
                               trainable=False)
            vec2 = ht.Variable(name="vec2", trainable=False)
            look2 = ht.embedding_lookup_op(table2, idx2)
            loss2 = ht.reduce_mean_op(look2, [0, 1])
            g2 = ht.embedding_lookup_gradient_op(vec2, idx2, (vocab, dim))
            push2 = ht.parameterServerCommunicate_op(g2, ps_id=table2.name)
            ex2 = ht.Executor({"train": [loss2, g2, push2]}, ctx=ht.cpu(0),
                              comm_mode="PS", seed=0, prefetch=False)
            try:
                assert g2.rows_mode is False
                out2 = ex2.run("train", feed_dict={idx2: bi, vec2: bv})
                assert out2[1].asnumpy().shape == (vocab, dim)
            finally:
                ex2.close()
                os.environ.pop("HETU_PS_ID_BASE", None)
        finally:
            # finalize the process-singleton worker INSIDE the cluster
            # context — a live worker leaking past teardown poisons the
            # next test's cluster bootstrap (the test_elastic_executor
            # idiom)
            ex.close()
            from hetu_tpu import ps as ps_pkg
            ps_pkg.worker_finish()


# ---------------------------------------------------------------------------
# satellite: roofline families + hetutop kernels panel
# ---------------------------------------------------------------------------

def test_roofline_covers_kernel_families():
    from hetu_tpu.telemetry.profiler import roofline_rows
    x = ht.Variable(name="x", value=np.ones((16, 64), np.float32),
                    trainable=False)
    w = ht.Variable(name="w_r", value=np.ones((64, 8), np.float32))
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    opt = ht.optim.AdamOptimizer(0.01).minimize(loss)
    vec = ht.Variable(name="v_r", value=np.ones((16, 8), np.float32),
                      trainable=False)
    idx = ht.Variable(name="i_r", value=np.zeros(16, np.int64),
                      dtype=np.int64, trainable=False)
    eg = ht.embedding_lookup_gradient_op(vec, idx, (100, 8))
    rows = roofline_rows([loss, opt, eg])
    fams = {r.family: r for r in rows}
    # fused-adam family: one pass over grad+m+v+param (10 flops, 7 moves)
    adam = next((r for r in rows
                 if r.family.startswith("Optimizer_Adam")), None)
    assert adam is not None
    n = 64 * 8
    assert adam.flops == pytest.approx(10.0 * n)
    assert adam.bytes == pytest.approx(7.0 * 4.0 * n)
    # fused-embed-grad family: one add per input grad element, HBM-bound
    egr = fams.get("EmbeddingLookUpGradient")
    assert egr is not None and egr.bound == "memory"
    assert egr.flops == pytest.approx(2.0 * 16 * 8)   # training 2x mult


def test_hetutop_kernels_panel(tmp_path):
    from hetu_tpu.telemetry import hetutop
    d = tmp_path / "tel"
    d.mkdir()
    recs = [
        {"kind": "run_info", "ts": 1.0, "rank": 0, "device_kind": "cpu",
         "peak_tflops_assumed": 197.0},
        {"kind": "step", "ts": 2.0, "rank": 0, "sub": "train", "step": 1,
         "step_ms": 5.0,
         "metrics": {
             'hetu_kernel_dispatch_total{kernel="fused_adam",path="pallas"}': 3.0,
             'hetu_kernel_dispatch_total{kernel="csr_spmm",path="fallback"}': 2.0,
         }},
    ]
    (d / "metrics-r0.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    frame = hetutop.render_frame(hetutop.gather(str(d)))
    assert "kernels:" in frame
    assert "fused_adam pallas:3" in frame
    assert "csr_spmm fallback:2" in frame


def test_spmd_scope_declines_kernels():
    """A GSPMD multi-device scope (the executor's spmd flag) makes every
    kernel ineligible — a bare pallas_call has no SPMD partitioning rule,
    so auto must fall back and force must refuse (docs/KERNELS.md)."""
    sv = jnp.ones((128, 128), jnp.float32)
    seg = jnp.zeros((128,), jnp.int32)
    with registry.active("auto", spmd=True):
        assert registry.in_spmd_scope()
        ok, why = registry.eligibility_of("fused_embed_grad", sv, seg)
        assert not ok and "GSPMD" in why
    with registry.active("force", spmd=True):
        with pytest.raises(registry.KernelEligibilityError):
            registry.dispatch("fused_embed_grad", sv, seg)
    # outside the scope the same call is eligible again
    with registry.active("force"):
        assert not registry.in_spmd_scope()
        registry.dispatch("fused_embed_grad", sv, seg)


def test_rows_mode_reset_across_executors():
    """Graph nodes are shared between executors: a second build over a
    graph whose embedding-grad op an earlier (hypothetical) executor
    flipped to rows mode must reset it to dense when its own conditions
    don't wire the rows route (no PS runtime here at all)."""
    vec = ht.Variable(name="v_reset", trainable=False)
    idx = ht.Variable(name="i_reset", dtype=np.int64, trainable=False)
    g = ht.embedding_lookup_gradient_op(vec, idx, (50, 8))
    g.to_rows()          # simulate a previous executor's flip
    assert g.rows_mode
    ex = ht.Executor({"d": [g]}, ctx=ht.cpu(0))
    assert g.rows_mode is False     # reset at build: dense again
    out = ex.run("d", feed_dict={vec: np.ones((4, 8), np.float32),
                                 idx: np.array([1, 2, 1, 3])})
    assert out[0].asnumpy().shape == (50, 8)
