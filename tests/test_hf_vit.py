"""HuggingFace ViT numerical parity (models/hf_vit.py) — the vision side
of the checkpoint interop, pinned exactly like the BERT/GPT-2 suites:
random-weight transformers ViT (no network), import, compare forwards."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from hetu_tpu.models import vit as hvit
from hetu_tpu.models.hf_vit import (config_from_hf, export_to_hf,
                                    params_from_hf)


def small_hf_config(**over):
    kw = dict(image_size=32, patch_size=8, num_channels=3, hidden_size=48,
              num_hidden_layers=3, num_attention_heads=4,
              intermediate_size=96, hidden_act="gelu",
              layer_norm_eps=1e-12)
    kw.update(over)
    return transformers.ViTConfig(**kw)


def images(rng, n=2, size=32):
    return rng.standard_normal((n, 3, size, size)).astype(np.float32)


def test_hidden_states_match_hf():
    torch.manual_seed(0)
    model = transformers.ViTModel(small_hf_config(),
                                  add_pooling_layer=False).eval()
    params, cfg = params_from_hf(model)
    x = images(np.random.default_rng(1))
    with torch.no_grad():
        ref = model(pixel_values=torch.tensor(x)).last_hidden_state.numpy()
    ours = np.asarray(hvit.encode(params, jnp.asarray(x), cfg))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


def test_classifier_logits_match_hf():
    torch.manual_seed(1)
    model = transformers.ViTForImageClassification(
        small_hf_config(num_labels=7)).eval()
    params, cfg = params_from_hf(model)
    assert cfg.n_classes == 7
    x = images(np.random.default_rng(2), n=3)
    with torch.no_grad():
        ref = model(pixel_values=torch.tensor(x)).logits.numpy()
    ours = np.asarray(hvit.classify_logits(params, jnp.asarray(x), cfg))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


def test_import_refuses_mismatched_config():
    torch.manual_seed(2)
    model = transformers.ViTModel(small_hf_config(),
                                  add_pooling_layer=False).eval()
    bad = config_from_hf(model.config, n_layers=1)
    with pytest.raises(ValueError, match="n_layers"):
        params_from_hf(model, bad)


def test_imported_vit_trains_a_step():
    """Imported encoder + fresh head fine-tunes through the flagship step
    and learns a trivial brightness rule above chance."""
    import dataclasses
    torch.manual_seed(3)
    model = transformers.ViTModel(small_hf_config(),
                                  add_pooling_layer=False).eval()
    params, cfg = params_from_hf(model)
    cfg = dataclasses.replace(cfg, n_classes=2)
    k = jax.random.PRNGKey(0)
    params["cls_w"] = jax.random.normal(k, (cfg.d_model, 2)) * 0.02
    params["cls_b"] = jnp.zeros((2,))
    step = hvit.make_train_step(cfg, lr=1e-3)
    opt = hvit.init_opt_state(params)
    rng = np.random.default_rng(4)
    acc = 0.0
    for _ in range(30):
        x = images(rng, n=16)
        labels = (x.mean((1, 2, 3)) > 0).astype(np.int32)
        x = x + labels[:, None, None, None] * 0.5   # separable signal
        loss, acc, params, opt = step(params, opt, jnp.asarray(x),
                                      jnp.asarray(labels))
    assert float(acc) > 0.7


def test_train_then_export_roundtrip():
    """Fine-tune imported ViT weights, export into a fresh torch
    ViTForImageClassification, logits must match ours."""
    torch.manual_seed(4)
    model = transformers.ViTForImageClassification(
        small_hf_config(num_labels=4)).eval()
    params, cfg = params_from_hf(model)
    step = hvit.make_train_step(cfg, lr=1e-3)
    trained = jax.tree.map(jnp.array, params)
    rng = np.random.default_rng(5)
    x = images(rng, n=8)
    _, _, trained, _ = step(trained, hvit.init_opt_state(trained),
                            jnp.asarray(x),
                            jnp.asarray(rng.integers(0, 4, 8), jnp.int32))
    fresh = transformers.ViTForImageClassification(
        small_hf_config(num_labels=4)).eval()
    export_to_hf(trained, cfg, fresh)
    xt = images(rng, n=3)
    with torch.no_grad():
        ref = fresh(pixel_values=torch.tensor(xt)).logits.numpy()
    ours = np.asarray(hvit.classify_logits(trained, jnp.asarray(xt), cfg))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


def test_export_refuses_layer_mismatch():
    torch.manual_seed(5)
    model = transformers.ViTForImageClassification(
        small_hf_config(num_labels=4)).eval()
    params, cfg = params_from_hf(model)
    small = transformers.ViTForImageClassification(
        small_hf_config(num_labels=4, num_hidden_layers=2)).eval()
    with pytest.raises(ValueError, match="no slot"):
        export_to_hf(params, cfg, small)


def test_flagship_vit_mesh_forward_matches_single_device():
    """The from-scratch flagship ViT shards dp2/tp2 on the virtual mesh
    and matches its own single-device forward (tp-divisible widths)."""
    from hetu_tpu.parallel.mesh import make_mesh
    cfg = hvit.ViTConfig(image_size=32, patch_size=8, d_model=64,
                         n_heads=4, n_layers=2, d_ff=128, n_classes=6)
    params = hvit.init_params(jax.random.PRNGKey(5), cfg)
    x = images(np.random.default_rng(6), n=4)
    solo = np.asarray(hvit.classify_logits(params, jnp.asarray(x), cfg))
    mesh = make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    import functools
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(
            p, jax.sharding.NamedSharding(mesh, s)),
        params, hvit.param_specs(cfg),
        is_leaf=lambda v: isinstance(v, jax.sharding.PartitionSpec))
    meshed = np.asarray(jax.jit(
        lambda p, im: hvit.classify_logits(p, im, cfg, mesh))(
            sharded, jnp.asarray(x)))
    np.testing.assert_allclose(meshed, solo, atol=2e-4, rtol=2e-4)
