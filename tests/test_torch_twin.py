"""The PyTorch competitor twin (examples/cnn/torch_main.py) — the
reference keeps torch_main.py in-repo for cross-framework A/B; this proves
ours trains on the same synthetic data, single-process and 2-process DDP
over gloo (the reference's DDP mode on the CPU build of torch)."""
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TWIN = os.path.join(REPO, "examples", "cnn", "torch_main.py")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _final_acc(out):
    accs = re.findall(r"acc ([0-9.]+)", out)
    assert accs, out
    return float(accs[-1])


def test_torch_twin_mlp_trains():
    p = subprocess.run(
        [sys.executable, TWIN, "--model", "mlp", "--dataset", "MNIST",
         "--num-epochs", "1"],
        capture_output=True, text=True, timeout=240, env=_env())
    assert p.returncode == 0, p.stderr
    # synthetic MNIST is near-linearly-separable: one epoch trains high
    assert _final_acc(p.stdout) > 0.9, p.stdout


def test_torch_twin_ddp_two_process():
    p = subprocess.run(
        [sys.executable, "-m", "torch.distributed.run",
         "--nproc-per-node", "2", "--master-port", "29711", TWIN,
         "--model", "mlp", "--dataset", "MNIST", "--num-epochs", "1"],
        capture_output=True, text=True, timeout=300, env=_env())
    assert p.returncode == 0, p.stderr
    assert _final_acc(p.stdout) > 0.85, p.stdout
