"""DistGCN 1.5D oracle tests on the virtual 8-device mesh (reference
``tests/test_DistGCN/test_model_distGCN15d.py:9-22`` — there: mpirun -np 8
with --replication 2; here: a (gr=4, gc=2) mesh, dense single-device oracle).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import hetu_tpu as ht
from hetu_tpu.parallel import distgcn

N_NODES = 64
FDIM = 8


def _random_graph(seed=0, n=N_NODES, avg_deg=4):
    rng = np.random.RandomState(seed)
    nnz = n * avg_deg
    rows = rng.randint(0, n, nnz)
    cols = rng.randint(0, n, nnz)
    vals = rng.rand(nnz).astype(np.float32)
    dense = np.zeros((n, n), np.float32)
    # duplicate (r,c) entries accumulate, matching COO semantics
    np.add.at(dense, (rows, cols), vals)
    return rows, cols, vals, dense


def _mesh(gr=4, gc=2):
    devs = np.array(jax.devices()[:gr * gc]).reshape(gr, gc)
    return Mesh(devs, ("gr", "gc"))


def test_spmm_15d_matches_dense():
    rows, cols, vals, dense = _random_graph()
    rng = np.random.RandomState(1)
    h = rng.randn(N_NODES, FDIM).astype(np.float32)
    mesh = _mesh()
    adj, h_dev = distgcn.shard_gcn_inputs(mesh, rows, cols, vals, h, N_NODES)
    z = distgcn.spmm_15d(mesh, adj, h_dev, N_NODES)
    np.testing.assert_allclose(np.asarray(z), dense @ h, rtol=1e-5, atol=1e-5)


def test_spmm_15d_replication_1():
    """r=1 degenerates to plain row-parallel spmm (reference single-column
    path, broad_func with replication=1)."""
    rows, cols, vals, dense = _random_graph(seed=3)
    rng = np.random.RandomState(2)
    h = rng.randn(N_NODES, FDIM).astype(np.float32)
    devs = np.array(jax.devices()[:8]).reshape(8, 1)
    mesh = Mesh(devs, ("gr", "gc"))
    adj, h_dev = distgcn.shard_gcn_inputs(mesh, rows, cols, vals, h, N_NODES)
    z = distgcn.spmm_15d(mesh, adj, h_dev, N_NODES)
    np.testing.assert_allclose(np.asarray(z), dense @ h, rtol=1e-5, atol=1e-5)


def test_gcn_forward_matches_dense():
    rows, cols, vals, dense = _random_graph(seed=5)
    rng = np.random.RandomState(4)
    h = rng.randn(N_NODES, FDIM).astype(np.float32)
    w1 = (rng.randn(FDIM, 16) * 0.3).astype(np.float32)
    w2 = (rng.randn(16, 4) * 0.3).astype(np.float32)
    mesh = _mesh()
    adj, h_dev = distgcn.shard_gcn_inputs(mesh, rows, cols, vals, h, N_NODES)
    out = distgcn.gcn_forward(mesh, adj, h_dev, [jnp.asarray(w1),
                                                 jnp.asarray(w2)], N_NODES)
    oracle = np.maximum(dense @ h @ w1, 0.0)
    oracle = dense @ oracle @ w2
    np.testing.assert_allclose(np.asarray(out), oracle, rtol=1e-4, atol=1e-4)


def test_gcn_training_grads_match_dense():
    """Backward through the 1.5D spmm: weight grads match the dense oracle."""
    rows, cols, vals, dense = _random_graph(seed=7)
    rng = np.random.RandomState(6)
    h = rng.randn(N_NODES, FDIM).astype(np.float32)
    w1 = (rng.randn(FDIM, 16) * 0.3).astype(np.float32)
    w2 = (rng.randn(16, 4) * 0.3).astype(np.float32)
    labels = np.eye(4, dtype=np.float32)[rng.randint(0, 4, N_NODES)]
    mesh = _mesh()
    adj, h_dev = distgcn.shard_gcn_inputs(mesh, rows, cols, vals, h, N_NODES)

    def loss_15d(ws):
        logits = distgcn.gcn_forward(mesh, adj, h_dev, ws, N_NODES)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(jnp.asarray(labels) * logp, axis=1))

    def loss_dense(ws):
        a = jnp.asarray(dense)
        z = jax.nn.relu(a @ jnp.asarray(h) @ ws[0])
        logits = a @ z @ ws[1]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(jnp.asarray(labels) * logp, axis=1))

    ws = [jnp.asarray(w1), jnp.asarray(w2)]
    l1, g1 = jax.value_and_grad(loss_15d)(ws)
    l2, g2 = jax.value_and_grad(loss_dense)(ws)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_graph_api_distgcn_op():
    """distgcn_15d_op through the Executor (single device) vs dense oracle."""
    rows, cols, vals, dense = _random_graph(seed=9)
    rng = np.random.RandomState(8)
    h = rng.randn(N_NODES, FDIM).astype(np.float32)
    w = (rng.randn(FDIM, 4) * 0.3).astype(np.float32)

    A = ht.Variable(name="adj", trainable=False)
    H = ht.Variable(name="h", trainable=False)
    W = ht.Variable("w", value=w)
    z = ht.distgcn_15d_op(A, H, W, size=1, replication=1)
    ex = ht.Executor([z], ctx=ht.cpu(0))
    sp = ht.sparse_array(vals, (rows, cols), (N_NODES, N_NODES), ctx=ht.cpu(0))
    (out,) = ex.run("default", feed_dict={A: sp, H: h},
                    convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(out, dense @ h @ w, rtol=1e-4, atol=1e-4)
