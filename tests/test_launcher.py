"""Launcher tests: heturun-style yaml cluster launch end to end
(reference bin/heturun + runner.py; SURVEY §3.5)."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN_SCRIPT = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import hetu_tpu as ht

    ht.worker_init()
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((4, 2), stddev=0.5, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), comm_mode="PS")
    rng = np.random.RandomState(0)
    for _ in range(5):
        bx = rng.randn(8, 4).astype(np.float32)
        by = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        out = ex.run("train", feed_dict={x: bx, y_: by})
    print("WORKER_DONE", float(out[0].asnumpy()))
    ht.worker_finish()
""")


def _heturun_once(tmp_path):
    tmp_path.mkdir(parents=True, exist_ok=True)
    cfg = tmp_path / "cluster.yml"
    cfg.write_text(
        "nodes:\n"
        "  - host: localhost\n"
        "    servers: 2\n"
        "    workers: 2\n"
        "    chief: true\n")
    train = tmp_path / "train.py"
    train.write_text(TRAIN_SCRIPT)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # own session: on timeout the WHOLE tree (scheduler/servers/workers) is
    # killed — an orphaned server holding its port would wedge later runs
    proc = subprocess.Popen(
        [sys.executable, "-m", "hetu_tpu.runner", "-c", str(cfg),
         sys.executable, str(train)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=str(tmp_path), start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        raise
    assert proc.returncode == 0, stdout + "\n" + stderr
    assert stdout.count("WORKER_DONE") == 2, stdout + stderr


def test_heturun_single_machine(tmp_path):
    # one retry: the full e2e launch (scheduler + 2 servers + 2 fresh-jax
    # workers over loopback) is timing-sensitive under a loaded test host
    try:
        _heturun_once(tmp_path)
    except AssertionError:
        _heturun_once(tmp_path / "retry")


def test_launcher_yaml_ps_roles(tmp_path):
    # reference tests/pstests style: launcher starts scheduler+servers from
    # yaml, a separate worker process trains against them
    cfg = tmp_path / "local.yml"
    cfg.write_text(
        "shared:\n"
        "  DMLC_PS_ROOT_URI: 127.0.0.1\n"
        "  DMLC_PS_ROOT_PORT: 14310\n"
        "  DMLC_NUM_WORKER: 1\n"
        "  DMLC_NUM_SERVER: 1\n"
        "launch:\n"
        "  worker: 1\n"
        "  server: 1\n"
        "  scheduler: 1\n")
    train = tmp_path / "train.py"
    train.write_text(TRAIN_SCRIPT)
    driver = tmp_path / "driver.py"
    driver.write_text(textwrap.dedent(f"""
        import argparse, runpy, sys
        from hetu_tpu import launcher

        def target(args):
            runpy.run_path({str(train)!r}, run_name="__main__")

        args = argparse.Namespace(config={str(cfg)!r})
        launcher.launch(target, args)
        print("LAUNCH_OK")
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, str(driver)], capture_output=True,
                         text=True, timeout=240, env=env, cwd=str(tmp_path))
    assert out.returncode == 0, out.stdout + "\n" + out.stderr
    assert "LAUNCH_OK" in out.stdout, out.stdout + out.stderr
