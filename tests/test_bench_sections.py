"""Every bench section's Python path executes end to end (smoke configs).

The driver gets ONE hardware run per round; several sections (bert,
transformer350, decode, flash4k, wdl) have historically reached that run
without ever executing end to end, so an API drift in the framework
would surface as a lost bench cell. HETU_BENCH_SMOKE=1 shrinks each
section to a seconds-scale config; each runs here as the REAL
``--run-section`` subprocess (the exact child the driver spawns), on the
CPU backend the conftest pins.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

SECTIONS = ["probe", "resnet:128:bf16", "resnet:128:f32", "bert",
            "transformer", "transformer350", "twin", "decode", "flash4k",
            "vit", "pipeline", "wdl", "comm_quant_ps", "comm_quant_dp",
            "introspect", "trail", "kernels", "planner"]


# sections whose cells must carry their own diagnosis fields: a
# below-target hardware number is only actionable if the cell says which
# attention/CE path it ran and (bert) where its profiler trace landed
EXPECTED_KEYS = {
    "bert": ("attn_impl", "mlm_ce", "trace"),
    "transformer": ("attn_impl",),
    "transformer350": ("attn_impl", "trace"),
    # hetukern: the cell must carry the per-kernel equality verdicts and
    # the embed-grad A/B headline (docs/KERNELS.md)
    "kernels": ("equality_ok", "speedup_rows"),
    # hetutrail: the overhead A/B must actually have recorded spans, or
    # the on-leg measured nothing (docs/OBSERVABILITY.md pillar 5)
    "trail": ("trail_overhead_pct", "client_spans"),
    # hetuplan: the cell must carry both sides of the prediction claim
    # (docs/ANALYSIS.md Tier C)
    "planner": ("predicted_step_ms", "measured_step_ms", "plan_err_pct"),
}


@pytest.mark.parametrize("name", SECTIONS)
def test_section_runs_in_smoke_mode(name, monkeypatch):
    if name == "pipeline":
        import jax
        if jax.__version__.startswith("0.4."):
            # confirmed pre-existing (stash A/B in PR 7, unchanged in
            # PR 8): shard_map autodiff in parallel/pipeline.py raises
            # _SpecError on the 0.4.x line — an upstream limitation, not a
            # repo regression. Quarantined so tier-1 signal stays clean.
            pytest.xfail("pipeline autodiff unsupported on jax 0.4.x "
                         "(shard_map _SpecError; pre-existing)")
    monkeypatch.setenv("HETU_BENCH_SMOKE", "1")
    # the child re-runs this image's sitecustomize (PYTHONPATH points at
    # it), which pins the axon backend BEFORE the inherited
    # JAX_PLATFORMS=cpu can take effect — on a dead tunnel every section
    # would hang. Blank it: bench.py's cwd makes the repo importable.
    monkeypatch.setenv("PYTHONPATH", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    out = bench._section_subprocess(name, timeout=600)
    assert "error" not in out, out
    # every section's JSON records which device it actually ran on
    assert out.pop("_device", None) is not None
    for key in EXPECTED_KEYS.get(name, ()):
        assert key in out, (name, key, out)
    if "trace" in EXPECTED_KEYS.get(name, ()):
        # the profiler trace actually landed (verified IN-CHILD via
        # trace_files: the smoke trace dir is a TemporaryDirectory, deleted
        # by the time the parent sees the result — no more leaked
        # /tmp/hetu_bench_* dirs)
        assert out.get("trace_files", 0) > 0, out
        assert not os.path.isdir(out["trace"]), \
            f"smoke trace dir leaked: {out['trace']}"
