"""Every bench section's Python path executes end to end (smoke configs).

The driver gets ONE hardware run per round; several sections (bert,
transformer350, decode, flash4k, wdl) have historically reached that run
without ever executing end to end, so an API drift in the framework
would surface as a lost bench cell. HETU_BENCH_SMOKE=1 shrinks each
section to a seconds-scale config; each runs here as the REAL
``--run-section`` subprocess (the exact child the driver spawns), on the
CPU backend the conftest pins.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

SECTIONS = ["probe", "resnet:128:bf16", "resnet:128:f32", "bert",
            "transformer", "transformer350", "twin", "decode", "flash4k",
            "vit", "pipeline", "wdl", "comm_quant_ps", "comm_quant_dp",
            "introspect", "trail", "chaos", "kernels", "planner",
            "snapshot", "pilot"]


# sections whose cells must carry their own diagnosis fields: a
# below-target hardware number is only actionable if the cell says which
# attention/CE path it ran and (bert) where its profiler trace landed
EXPECTED_KEYS = {
    "bert": ("attn_impl", "mlm_ce", "trace"),
    "transformer": ("attn_impl",),
    "transformer350": ("attn_impl", "trace"),
    # hetukern: the cell must carry the per-kernel equality verdicts and
    # the embed-grad A/B headline (docs/KERNELS.md)
    "kernels": ("equality_ok", "speedup_rows"),
    # hetutrail: the overhead A/B must actually have recorded spans, or
    # the on-leg measured nothing (docs/OBSERVABILITY.md pillar 5)
    "trail": ("trail_overhead_pct", "client_spans"),
    # hetuchaos: the CRC A/B must be a clean-wire measurement — the cell
    # carries the retry/reject counters that prove it
    "chaos": ("crc_overhead_pct", "crc_rejects"),
    # hetuplan: the cell must carry both sides of the prediction claim
    # (docs/ANALYSIS.md Tier C)
    "planner": ("predicted_step_ms", "measured_step_ms", "plan_err_pct"),
    # hetusave: the stall A/B must have actually taken snapshots, and the
    # cell carries the per-epoch wall cost behind the stall headline
    "snapshot": ("snapshot_stall_pct", "snapshot_wall_ms", "snapshots"),
    # hetupilot: the armed-idle A/B must carry the direct boundary-walk
    # stopwatch behind the headline, and prove no era ever opened
    "pilot": ("pilot_overhead_pct", "pilot_boundary_ms", "eras"),
}


_GLIBC_ABORT_MARKS = ("corrupted", "LLVM ERROR", "glibc", "malloc",
                      "munmap_chunk", "free(", "invalid pointer",
                      "double free")


def _is_child_native_crash(out: dict) -> bool:
    """The section child died (or wedged) inside native code: the
    signature family of the known resnet:128 flake, distinct from
    in-child Python errors (rc=1 with a traceback tail). Observed
    signatures, ALL reproduced at the PR-15 seed (4-6 of 6 smoke runs on
    this host) and all during "Building ResNet-18 model...", so this is
    an XLA-CPU-client child-init race — the 'LLVM ERROR: Dialect Type
    already registered' variant pins the family to duplicate LLVM
    registration, the rest are its downstream heap corruption:
    rc=-11 (SIGSEGV); rc=-6 + a glibc malloc abort ('corrupted
    double-linked list' / 'corrupted size vs. prev_size' /
    'munmap_chunk(): invalid pointer' / 'free(): invalid size') or the
    LLVM dialect error; and a child that HANGS outright (the same race
    deadlocking instead of crashing). A plain rc=-6 with any other
    message still fails loudly."""
    if out.get("hang"):
        return True
    err = out.get("error")
    if not isinstance(err, str):
        return False
    if err.startswith("rc=-11"):
        return True
    return err.startswith("rc=-6") and any(
        m in err for m in _GLIBC_ABORT_MARKS)


@pytest.mark.parametrize("name", SECTIONS)
def test_section_runs_in_smoke_mode(name, monkeypatch):
    if name == "pipeline":
        import jax
        if jax.__version__.startswith("0.4."):
            # confirmed pre-existing (stash A/B in PR 7, unchanged in
            # PR 8): shard_map autodiff in parallel/pipeline.py raises
            # _SpecError on the 0.4.x line — an upstream limitation, not a
            # repo regression. Quarantined so tier-1 signal stays clean.
            pytest.xfail("pipeline autodiff unsupported on jax 0.4.x "
                         "(shard_map _SpecError; pre-existing)")
    monkeypatch.setenv("HETU_BENCH_SMOKE", "1")
    # the child re-runs this image's sitecustomize (PYTHONPATH points at
    # it), which pins the axon backend BEFORE the inherited
    # JAX_PLATFORMS=cpu can take effect — on a dead tunnel every section
    # would hang. Blank it: bench.py's cwd makes the repo importable.
    monkeypatch.setenv("PYTHONPATH", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    out = bench._section_subprocess(name, timeout=600)
    if name.startswith("resnet:128") and _is_child_native_crash(out):
        # deterministic quarantine of the KNOWN flaky resnet:128 child
        # native crash (recurring since PR 11; root-caused to the
        # signature family in _is_child_native_crash at the PR-15 seed,
        # not a repo regression). Policy: retry once; a second native
        # crash in a row SKIPS with the quarantine marker instead of
        # failing tier-1. Any other failure mode still fails loudly.
        out = bench._section_subprocess(name, timeout=600)
        if _is_child_native_crash(out):
            pytest.skip(f"known-flaky {name} child native crash "
                        "reproduced twice (quarantined; see CHANGES.md "
                        "PR 15)")
    assert "error" not in out, out
    # every section's JSON records which device it actually ran on
    assert out.pop("_device", None) is not None
    for key in EXPECTED_KEYS.get(name, ()):
        assert key in out, (name, key, out)
    if "trace" in EXPECTED_KEYS.get(name, ()):
        # the profiler trace actually landed (verified IN-CHILD via
        # trace_files: the smoke trace dir is a TemporaryDirectory, deleted
        # by the time the parent sees the result — no more leaked
        # /tmp/hetu_bench_* dirs)
        assert out.get("trace_files", 0) > 0, out
        assert not os.path.isdir(out["trace"]), \
            f"smoke trace dir leaked: {out['trace']}"
