"""Data-parallel (comm_mode='AllReduce') correctness on the virtual 8-CPU mesh.

The reference validates DP via 8-GPU NCCL scripts; here GSPMD shards the batch
over the mesh and inserts the gradient psum. Correctness oracle: the DP run
must match the single-device run bit-for-bit-ish (same global batch).
"""
import numpy as np
import pytest
import jax

import hetu_tpu as ht


def build(seed=0):
    rng = np.random.RandomState(seed)
    wv = rng.randn(16, 4).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    w = ht.Variable(name="w", value=wv.copy())
    logits = ht.matmul_op(x, w)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    opt = ht.optim.SGDOptimizer(0.1)
    train_op = opt.minimize(loss)
    return x, y_, w, loss, train_op


def make_data(n=64, seed=3):
    rng = np.random.RandomState(seed)
    xv = rng.randn(n, 16).astype(np.float32)
    yv = np.eye(4, dtype=np.float32)[rng.randint(0, 4, n)]
    return xv, yv


def test_allreduce_matches_single_device():
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    xv, yv = make_data()

    # single device
    x, y_, w, loss, train_op = build()
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    losses1 = []
    for _ in range(5):
        lv, _ = ex1.run("train", feed_dict={x: xv, y_: yv},
                        convert_to_numpy_ret_vals=True)
        losses1.append(float(lv))
    w1 = np.asarray(ex1.state["params"][id(w)])

    # 8-way data parallel over the mesh
    x, y_, w, loss, train_op = build()
    ex8 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                      comm_mode="AllReduce")
    assert ex8.config.mesh is not None and ex8.config.mesh.size == 8
    losses8 = []
    for _ in range(5):
        lv, _ = ex8.run("train", feed_dict={x: xv, y_: yv},
                        convert_to_numpy_ret_vals=True)
        losses8.append(float(lv))
    w8 = np.asarray(ex8.state["params"][id(w)])

    np.testing.assert_allclose(losses1, losses8, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w1, w8, rtol=1e-5, atol=1e-6)


def test_nondivisible_batch_warns_and_replicates():
    """batch % dp != 0 must not silently replicate: a warning fires and the
    run still computes correctly (replicated = every device sees the full
    batch, so the result matches the single-device oracle)."""
    xv, yv = make_data(n=13)  # 13 % 8 != 0
    x1, y1, _, loss1, train1 = build()
    ex1 = ht.Executor({"train": [loss1, train1]}, ctx=ht.cpu(0))
    ref, _ = ex1.run("train", feed_dict={x1: xv, y1: yv},
                     convert_to_numpy_ret_vals=True)
    x, y_, w, loss, train_op = build()
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="AllReduce")
    with pytest.warns(UserWarning, match="not divisible by dp"):
        lv, _ = ex.run("train", feed_dict={x: xv, y_: yv},
                       convert_to_numpy_ret_vals=True)
    np.testing.assert_allclose(lv, ref, rtol=1e-5)


def test_allreduce_feeds_are_sharded():
    xv, yv = make_data()
    x, y_, w, loss, train_op = build()
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="AllReduce")
    prepared = ex._prepare_input(xv)
    # batch axis sharded over the dp mesh axis
    assert len(prepared.sharding.device_set) == 8
    ex.run("train", feed_dict={x: xv, y_: yv})
    # params replicated on every device
    wval = ex.state["params"][id(w)]
    assert len(wval.sharding.device_set) == 8
    assert wval.sharding.is_fully_replicated
