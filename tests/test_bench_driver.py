"""Bench-driver orchestration: outage triage, recovery, and backstops.

The driver captures BENCH_r{N}.json by running ``bench.py`` once per round
against a tunneled TPU whose observed failure mode (rounds 2-4) is
INTERMITTENT outage — green probe, a few sections captured, then hangs.
These tests pin the orchestration loop's behavior with a scripted
``_section_subprocess`` (no backend, no subprocesses, no sleeps), covering:

- at-start outage -> wait-and-retry -> recovery runs every section
- mid-run outage -> section retried once after recovery
- genuine alive-backend hangs -> recorded, run continues; 2 consecutive
  trip the skip-remaining backstop; non-consecutive do not
- hang classification is structural (the "hang" marker), not a substring
  match on error text, so a crash mentioning "timed out" runs the sections
- exhausted wait budget -> fail-closed: rc=1, null headline

Reference analogue: the reference has no bench driver (BASELINE.md — it
prints timings ad hoc); this hardening exists because OUR scoreboard is a
single unattended run.
"""
import io
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

TO = {"error": "timed out after 420s (hung compile?)", "hang": True}
OK = {"samples_per_sec": 100.0, "_device": "TPU v5 lite"}
PROBE_OK = {"ok": True, "_device": "TPU v5 lite"}
PROBE_TO = {"error": "timed out after 180s (hung compile?)", "hang": True}
DEFAULT = {"samples_per_sec": 50.0, "_device": "TPU v5 lite"}


def run_sim(monkeypatch, behavior, budget=None, ledger_path="",
            kill_after=None, wedge_report="/nonexistent/wedge.json"):
    """Run bench.main() --fast with a scripted section runner.

    ``behavior``: section name -> list of results returned per successive
    call (the last entry repeats). Unlisted sections return DEFAULT.
    ``ledger_path``: HETU_BENCH_LEDGER value ("" disables the ledger so
    the orchestration sims stay stateless). ``kill_after``: simulate the
    invocation dying (tunnel loss, driver kill) after N non-probe section
    calls — raises KeyboardInterrupt out of main(), like a real SIGINT.
    Returns (rc, parsed JSON line) — (None, state) for a killed run.
    """
    state = {"_cells": 0}

    def fake(name, timeout):
        if name != "probe":
            if kill_after is not None and state["_cells"] >= kill_after:
                raise KeyboardInterrupt
            state["_cells"] += 1
        lst = behavior.get(name, [DEFAULT])
        i = state.get(name, 0)
        state[name] = i + 1
        return dict(lst[min(i, len(lst) - 1)])

    monkeypatch.setattr(bench, "_section_subprocess", fake)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    if budget is not None:
        monkeypatch.setenv("HETU_BENCH_PROBE_WAIT_S", str(budget))
    monkeypatch.setenv("HETU_BENCH_LEDGER", str(ledger_path))
    # keep a real repo-root WEDGE_BISECT.json from leaking into the sims
    monkeypatch.setenv("HETU_WEDGE_REPORT", str(wedge_report))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--fast"])
    buf = io.StringIO()
    monkeypatch.setattr(sys, "stdout", buf)
    rc = 0
    try:
        bench.main()
    except SystemExit as e:
        rc = e.code or 0
    except KeyboardInterrupt:
        return None, state
    line = buf.getvalue().strip().splitlines()[-1]
    return rc, json.loads(line)


def test_green_run_headline_is_max_resnet(monkeypatch):
    rc, out = run_sim(monkeypatch, {"resnet:512:bf16": [OK]})
    assert rc == 0
    assert out["value"] == 100.0          # max over resnet cells
    assert out["detail"]["device"] == "TPU v5 lite"
    # _device never leaks into the recorded cells
    assert all("_device" not in v for v in out["detail"].values()
               if isinstance(v, dict))


def test_at_start_outage_then_recovery_runs_all_sections(monkeypatch):
    rc, out = run_sim(monkeypatch, {"probe": [PROBE_TO, PROBE_OK]})
    d = out["detail"]
    assert rc == 0 and out["value"] == 50.0
    assert d.get("outage_recoveries") == 1
    assert "_probe" not in d              # no stale dead-tunnel evidence


def test_midrun_outage_retries_section_after_recovery(monkeypatch):
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_TO, PROBE_OK],
        "resnet:128:f32": [TO, OK],
    })
    d = out["detail"]
    assert rc == 0
    assert d["resnet18_f32_bs128"] == {"samples_per_sec": 100.0}
    assert d["mid_run_outages"] == ["resnet18_f32_bs128"]
    assert d["outage_recoveries"] == 1


def test_two_consecutive_alive_hangs_trip_backstop(monkeypatch):
    rc, out = run_sim(monkeypatch, {
        "resnet:128:bf16": [TO], "resnet:128:f32": [TO],
    })
    d = out["detail"]
    assert "timed out" in d["resnet18_bf16_bs128"]["error"]
    assert "timed out" in d["resnet18_f32_bs128"]["error"]
    for k in ("resnet18_f32_bs256", "resnet18_bf16_bs256",
              "resnet18_bf16_bs512"):
        assert "hanging with live backend" in d[k]["error"]


def test_non_consecutive_alive_hangs_do_not_trip_backstop(monkeypatch):
    rc, out = run_sim(monkeypatch, {
        "resnet:128:bf16": [TO], "resnet:256:f32": [TO],
    })
    d = out["detail"]
    assert rc == 0 and out["value"] == 50.0
    assert d["resnet18_f32_bs128"] == {"samples_per_sec": 50.0}


def test_successful_postoutage_retry_resets_hang_counter(monkeypatch):
    # three sections each hang-into-outage then succeed on retry: the
    # backstop must NOT trip (counter resets on every completed section)
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK] + [PROBE_TO, PROBE_OK] * 3,
        "resnet:128:bf16": [TO, OK],
        "resnet:128:f32": [TO, OK],
        "resnet:256:f32": [TO, OK],
    }, budget=100000)
    d = out["detail"]
    assert rc == 0
    for k in ("resnet18_bf16_bs128", "resnet18_f32_bs128",
              "resnet18_f32_bs256"):
        assert d[k] == {"samples_per_sec": 100.0}
    assert d["outage_recoveries"] == 3


def test_flapping_tunnel_retry_hangs_do_not_trip_backstop(monkeypatch):
    # two sections each: hang -> outage -> recover -> retry hangs -> probe
    # hangs AGAIN (flap). Neither counts as an alive-hang, so later
    # sections still run; the cells carry the flap attribution.
    # probe call order: at-start OK; section A triage TO, wait-loop OK,
    # retry-triage TO (flap); section B triage TO, wait-loop OK,
    # retry-triage TO (flap)
    flap = [PROBE_OK,
            PROBE_TO, PROBE_OK, PROBE_TO,
            PROBE_TO, PROBE_OK, PROBE_TO]
    rc, out = run_sim(monkeypatch, {
        "probe": flap,
        "resnet:128:bf16": [TO, TO],
        "resnet:128:f32": [TO, TO],
    }, budget=100000)
    d = out["detail"]
    assert "tunnel flapping" in d["resnet18_bf16_bs128"]["error"]
    assert "tunnel flapping" in d["resnet18_f32_bs128"]["error"]
    # backstop NOT tripped: remaining sections completed normally
    assert d["resnet18_f32_bs256"] == {"samples_per_sec": 50.0}
    assert d["resnet18_bf16_bs512"] == {"samples_per_sec": 50.0}


def test_risky_cells_run_last_in_green_run(monkeypatch):
    # the known backend-wedging cells must come after every other section
    # so a wedge costs only the least-important cells
    rc, out = run_sim(monkeypatch, {})
    keys = [k for k in out["detail"] if k.startswith("resnet")]
    assert keys[-2:] == ["resnet18_bf16_bs256", "resnet18_bf16_bs512"]


def test_risky_cell_hang_with_backend_never_returning_skips_rest(monkeypatch):
    # bs256 hangs AND every subsequent probe hangs: the wedge is recorded,
    # the remaining wait budget is spent (it has no other claimant after
    # the last safe section), and bs512 is skipped once it runs out
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_TO],
        "resnet:256:bf16": [TO],
    }, budget=2000)
    d = out["detail"]
    assert rc == 0 and out["value"] == 50.0   # earlier cells survive
    assert "not retried" in d["resnet18_bf16_bs256"]["error"]
    assert "unresponsive" in d["resnet18_bf16_bs512"]["error"]
    assert "outage_recoveries" not in d and "mid_run_outages" not in d


def test_risky_cell_wedge_recovery_lets_next_risky_cell_run(monkeypatch):
    # bs256 wedges the backend but it answers again during the wait (the
    # orphaned server-side compile finished): bs256 stays failed and is
    # NOT retried, bs512 still gets its window
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_TO, PROBE_OK],
        "resnet:256:bf16": [TO, OK],
    }, budget=100000)
    d = out["detail"]
    assert rc == 0
    assert "not retried" in d["resnet18_bf16_bs256"]["error"]
    assert d["resnet18_bf16_bs512"] == {"samples_per_sec": 50.0}
    assert d["outage_recoveries"] == 1


def test_risky_cell_hang_with_alive_probe_is_not_retried(monkeypatch):
    # backend still answers after the risky hang: record, do NOT retry
    # (a second attempt risks the wedge), continue to the next section
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_OK],
        "resnet:256:bf16": [TO, OK],
    })
    d = out["detail"]
    assert rc == 0
    assert "not retried" in d["resnet18_bf16_bs256"]["error"]
    assert d["resnet18_bf16_bs512"] == {"samples_per_sec": 50.0}


def test_device_recorded_from_recovery_probe_when_sections_fail(monkeypatch):
    crash = {"error": "rc=1: Traceback ..."}
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_TO, PROBE_OK],
        "resnet:128:bf16": [crash], "resnet:512:bf16": [crash],
        "resnet:128:f32": [crash], "resnet:256:bf16": [crash],
        "resnet:256:f32": [crash],
    })
    assert rc == 1 and out["value"] is None
    assert out["detail"]["device"] == "TPU v5 lite"


def test_exhausted_budget_fails_closed(monkeypatch):
    rc, out = run_sim(monkeypatch, {"probe": [PROBE_TO]}, budget=1)
    d = out["detail"]
    assert rc == 1 and out["value"] is None and out["vs_baseline"] is None
    assert d["_probe"]["hang"] is True
    assert all("unresponsive" in d[k]["error"] for k in d
               if k.startswith("resnet"))


def test_probe_crash_with_timeout_text_is_not_a_hang(monkeypatch):
    crash = {"error": "rc=1: TimeoutError: connection timed out"}
    rc, out = run_sim(monkeypatch, {"probe": [crash]})
    d = out["detail"]
    assert rc == 0 and out["value"] == 50.0     # sections ran
    assert d["_probe"] == crash


def test_midrun_budget_exhaustion_skips_remaining(monkeypatch):
    # outage mid-run with a budget too small to wait out: the hung section
    # and everything after it are skipped, earlier results survive
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_TO],
        "resnet:128:f32": [TO],
    }, budget=700)
    d = out["detail"]
    assert rc == 0 and out["value"] == 50.0     # bs128 captured first
    assert d["resnet18_bf16_bs128"] == {"samples_per_sec": 50.0}
    assert "budget exhausted" in d["resnet18_f32_bs128"]["error"]
    assert "unresponsive" in d["resnet18_f32_bs256"]["error"]


# ---------------------------------------------------------------------------
# Durable ledger (BENCH_PARTIAL.json): a killed invocation's completed cells
# are reused by the next one, so tunnel minutes are never lost (VERDICT r4 #2)
# ---------------------------------------------------------------------------

def test_ledger_killed_run_then_resume_completes_only_remainder(
        monkeypatch, tmp_path):
    lp = tmp_path / "ledger.json"
    # invocation 1 dies (KeyboardInterrupt, like a SIGINT/tunnel loss) after
    # two cells — both must already be on disk
    rc, state = run_sim(monkeypatch, {}, ledger_path=lp, kill_after=2)
    assert rc is None
    cells = json.loads(lp.read_text())["cells"]
    assert set(cells) == {"resnet18_bf16_bs128", "resnet18_f32_bs128"}
    assert all("ts" in v and "result" in v for v in cells.values())

    # invocation 2: the two recorded cells are served from the ledger (the
    # section runner is never called for them), the rest run fresh
    rc, out = run_sim(monkeypatch, {"resnet:128:bf16": [OK]}, ledger_path=lp)
    d = out["detail"]
    assert rc == 0
    assert sorted(d["from_ledger"]) == ["resnet18_bf16_bs128",
                                        "resnet18_f32_bs128"]
    # served from disk: invocation 2's OK (100.0) never ran — the ledger's
    # 50.0 stands, and the provenance stamp says where it came from
    assert d["resnet18_bf16_bs128"]["samples_per_sec"] == 50.0
    assert "ts" in d["resnet18_bf16_bs128"]["_ledger"]
    # the remainder ran fresh this invocation (no ledger stamp)
    assert d["resnet18_f32_bs256"] == {"samples_per_sec": 50.0}
    # and is now recorded too
    cells = json.loads(lp.read_text())["cells"]
    assert "resnet18_f32_bs256" in cells


def test_ledger_survives_dead_backend(monkeypatch, tmp_path):
    # invocation 1 captures one resnet cell then dies; invocation 2 finds
    # the tunnel gone for its whole window — the final line must still
    # carry the ledger cell as the headline instead of failing closed
    lp = tmp_path / "ledger.json"
    run_sim(monkeypatch, {"resnet:128:bf16": [OK]}, ledger_path=lp,
            kill_after=1)
    rc, out = run_sim(monkeypatch, {"probe": [PROBE_TO]}, budget=1,
                      ledger_path=lp)
    assert rc == 0
    assert out["value"] == 100.0
    assert out["detail"]["resnet18_bf16_bs128"]["samples_per_sec"] == 100.0
    assert "unresponsive" in out["detail"]["resnet18_f32_bs128"]["error"]


def test_ledger_error_cells_are_rerun(monkeypatch, tmp_path):
    # a hang/error recorded in invocation 1 is NOT reusable evidence
    lp = tmp_path / "ledger.json"
    lp.write_text(json.dumps({"cells": {
        "resnet18_bf16_bs128": {"result": {"error": "timed out"},
                                "smoke": False, "sha": "x", "ts": "t"},
    }}))
    rc, out = run_sim(monkeypatch, {"resnet:128:bf16": [OK]}, ledger_path=lp)
    assert out["detail"]["resnet18_bf16_bs128"]["samples_per_sec"] == 100.0
    assert "from_ledger" not in out["detail"]


def test_ledger_stale_sha_is_remeasured_not_reused(monkeypatch, tmp_path):
    # a cell recorded at another commit must not feed the merged headline:
    # the section re-runs at HEAD and the fresh number replaces the old one
    lp = tmp_path / "ledger.json"
    lp.write_text(json.dumps({"cells": {
        "resnet18_bf16_bs128": {"result": {"samples_per_sec": 77.0},
                                "smoke": False, "sha": "0000000", "ts": "t"},
    }}))
    rc, out = run_sim(monkeypatch, {}, ledger_path=lp)
    cell = out["detail"]["resnet18_bf16_bs128"]
    assert cell["samples_per_sec"] == 50.0        # DEFAULT: section re-ran
    assert "from_ledger" not in out["detail"]
    # the re-measurement was recorded at HEAD's sha
    saved = json.loads(lp.read_text())["cells"]["resnet18_bf16_bs128"]
    assert saved["sha"] != "0000000"
    assert saved["result"]["samples_per_sec"] == 50.0


def test_ledger_stale_sha_reused_only_with_optin(monkeypatch, tmp_path):
    # triage escape hatch (dead backend, any number beats none): explicit
    # env opt-in serves the stale cell, flagged as such
    lp = tmp_path / "ledger.json"
    lp.write_text(json.dumps({"cells": {
        "resnet18_bf16_bs128": {"result": {"samples_per_sec": 77.0},
                                "smoke": False, "sha": "0000000", "ts": "t"},
    }}))
    monkeypatch.setenv("HETU_BENCH_REUSE_STALE", "1")
    rc, out = run_sim(monkeypatch, {}, ledger_path=lp)
    cell = out["detail"]["resnet18_bf16_bs128"]
    assert cell["samples_per_sec"] == 77.0
    assert "stale" in cell["_ledger"]


def test_smoke_mode_never_touches_the_ledger(monkeypatch, tmp_path):
    # smoke exists to validate the section pipeline: it must neither be
    # served cached cells (every section runs) nor write its toy numbers
    # over real hardware measurements
    lp = tmp_path / "ledger.json"
    lp.write_text(json.dumps({"cells": {
        "resnet18_bf16_bs128": {"result": {"samples_per_sec": 50.0},
                                "sha": "x", "ts": "t"},
    }}))
    monkeypatch.setenv("HETU_BENCH_SMOKE", "1")
    rc, out = run_sim(monkeypatch, {"resnet:128:bf16": [OK]}, ledger_path=lp)
    # the section RAN (not served from the ledger) ...
    assert out["detail"]["resnet18_bf16_bs128"]["samples_per_sec"] == 100.0
    assert "from_ledger" not in out["detail"]
    # ... and the real measurement on disk is untouched
    cells = json.loads(lp.read_text())["cells"]
    assert cells["resnet18_bf16_bs128"]["result"]["samples_per_sec"] == 50.0


def test_ledger_corrupt_file_starts_fresh(monkeypatch, tmp_path):
    lp = tmp_path / "ledger.json"
    lp.write_text("{not json")
    rc, out = run_sim(monkeypatch, {}, ledger_path=lp)
    assert rc == 0 and out["value"] == 50.0
    assert "resnet18_bf16_bs128" in json.loads(lp.read_text())["cells"]


def _light_main_count():
    import subprocess
    out = subprocess.run(["pgrep", "-cf", "_light_main.py"],
                         capture_output=True, text=True).stdout.strip()
    return int(out or 0)


def test_wdl_dead_server_cannot_outlive_group_kill(monkeypatch):
    """The wdl section spawns a real PS cluster; a server that dies before
    registration leaves the worker blocked in a ctypes RPC that no signal
    can interrupt. The section-subprocess GROUP kill must both end the
    section within its deadline and reap the scheduler/servers — a
    leftover light process would hold ports (and on the bench host, the
    one TPU's attention) for the rest of the run."""
    import time as _time
    monkeypatch.setenv("HETU_BENCH_SMOKE", "1")
    monkeypatch.setenv("PYTHONPATH", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    # the kill hook follows the resilience fault-injection convention:
    # inert unless HETU_TEST_MODE is explicitly set
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_PS_TEST_KILL_SERVER", "1")
    before = _light_main_count()
    t0 = _time.time()
    out = bench._section_subprocess("wdl", timeout=90)
    assert _time.time() - t0 < 120
    assert "error" in out, out   # clean failure or group-killed hang
    # every cluster process is gone (poll: SIGKILL reaping is async)
    deadline = _time.time() + 10
    while _time.time() < deadline and _light_main_count() > before:
        _time.sleep(0.5)
    assert _light_main_count() <= before


def _load_wedge_tool():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "wedge_bisect.py")
    spec = importlib.util.spec_from_file_location("wedge_bisect", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_wedge_sim(monkeypatch, tmp_path, behavior):
    """Drive tools/wedge_bisect.py with a scripted section runner.
    behavior: name -> list of successive results (last repeats)."""
    wb = _load_wedge_tool()
    monkeypatch.setattr(wb, "REPORT", str(tmp_path / "WEDGE_BISECT.json"))
    state = {}

    def fake(name, timeout):
        # the tool distinguishes same-named experiments via env — mirror
        # that in the scripted key so behaviors can target them
        key = name
        if os.environ.get("HETU_NO_DONATE") == "1":
            key = name + ":no_donate"
        elif "hetu_wedge_cache_" in os.environ.get(
                "JAX_COMPILATION_CACHE_DIR", ""):
            key = name + ":fresh_cache"
        lst = behavior.get(key, [DEFAULT])
        i = state.get(key, 0)
        state[key] = i + 1
        return dict(lst[min(i, len(lst) - 1)])

    monkeypatch.setattr(wb.bench, "_section_subprocess", fake)
    monkeypatch.setattr(wb.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", ["wedge_bisect.py"])
    rc = wb.main()
    return rc, json.loads((tmp_path / "WEDGE_BISECT.json").read_text())


def test_wedge_bisect_compile_side_verdict(monkeypatch, tmp_path):
    # cold-cache bs256 wedges (and the backend needs one recovery wait),
    # warm-cache run is green -> the tool must blame the COMPILE stage
    rc, rep = _run_wedge_sim(monkeypatch, tmp_path, {
        # probes: initial, then post-probes per experiment; the cold-cache
        # wedge leaves the backend down for one recovery-wait probe
        "probe": [PROBE_OK, PROBE_OK, PROBE_OK, PROBE_OK,
                  PROBE_TO, PROBE_OK],
        "resnet:256:bf16:fresh_cache": [TO, OK],   # cold wedges, warm green
    })
    assert rc == 0
    assert "COMPILE-side" in rep["verdict"]["text"]
    assert rep["verdict"]["green"] is False
    assert rep["bf16_bs256_cold_cache"]["hang"] is True
    assert rep["bf16_bs256_warm_cache"]["samples_per_sec"] == 100.0


def test_wedge_bisect_all_green_says_reenable(monkeypatch, tmp_path):
    rc, rep = _run_wedge_sim(monkeypatch, tmp_path, {})
    assert rc == 0
    assert rep["verdict"]["green"] is True
    # every experiment + its post-probe recorded durably
    for k in ("bf16_bs192", "bf16_bs256_no_donate", "twin_bf16_bs512",
              "bf16_bs256_cold_cache", "bf16_bs256_warm_cache",
              "bf16_bs512_warm_cache"):
        assert k in rep and k + "_postprobe" in rep


def test_green_wedge_verdict_lifts_quarantine(monkeypatch, tmp_path):
    # a green bisect report makes the bs256/bs512 cells ordinary again:
    # a hang gets the normal outage-retry treatment instead of the
    # never-retry quarantine
    wp = tmp_path / "WEDGE_BISECT.json"
    wp.write_text(json.dumps({"verdict": {
        "text": "no wedge reproduced this window — re-enable the risky "
                "cells", "green": True}}))
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_TO, PROBE_OK],
        "resnet:256:bf16": [TO, OK],
    }, budget=100000, wedge_report=wp)
    d = out["detail"]
    assert "re-enable" in d["wedge_verdict"]
    # retried after the outage and captured — impossible under quarantine
    assert d["resnet18_bf16_bs256"] == {"samples_per_sec": 100.0}


def test_non_green_wedge_verdict_keeps_quarantine(monkeypatch, tmp_path):
    wp = tmp_path / "WEDGE_BISECT.json"
    wp.write_text(json.dumps({"verdict": {
        "text": "EXECUTE-side wedge: the cell hangs even with a warm "
                "cache", "green": False}}))
    rc, out = run_sim(monkeypatch, {
        "probe": [PROBE_OK, PROBE_OK],
        "resnet:256:bf16": [TO, OK],
    }, wedge_report=wp)
    assert "not retried" in out["detail"]["resnet18_bf16_bs256"]["error"]


def test_wedge_bisect_execute_side_verdict(monkeypatch, tmp_path):
    # the cell hangs even against a warm cache -> EXECUTE-side
    rc, rep = _run_wedge_sim(monkeypatch, tmp_path, {
        "probe": [PROBE_OK] * 20,        # backend stays alive throughout
        "resnet:256:bf16:fresh_cache": [TO, TO],
    })
    assert rc == 0
    assert "EXECUTE-side" in rep["verdict"]["text"]


def test_subprocess_timeout_result_carries_hang_marker():
    # the structured marker is load-bearing for every triage path; pin the
    # REAL timeout return shape: a 1s deadline usually kills the child
    # during interpreter startup. On a warm OS page/compile cache the
    # probe child can FINISH inside 1s (the historical flake) — that run
    # proves nothing about the timeout shape, so retry a few times and
    # skip (not fail) if the host is consistently that fast.
    out = None
    for _ in range(3):
        out = bench._section_subprocess("probe", 1)
        if "hang" in out or "error" in out:
            break
    if out is not None and "hang" not in out and "error" not in out:
        pytest.skip("probe child finished inside the 1s deadline on every "
                    "attempt (warm cache) — timeout shape not exercised")
    assert out.get("hang") is True
    assert "timed out after 1s" in out["error"]
