"""Worker process for the 4-process dp2 x tp2 multi-host test (not collected
by pytest).

Four processes with ONE virtual CPU device each form a (dp=2, tp=2) mesh
whose tp groups SPAN processes (devices are enumerated process-major, so the
tp pairs are (p0, p1) and (p2, p3)). A linear model with the weight sharded
over tp columns and the batch over dp trains against a single-process numpy
GD oracle — covering rank arithmetic (per-group batch feeding, cross-process
tp collectives) that a 2-process world cannot exercise.

Reference scale-out story: 2-node 16-GPU dp x mp worlds via mpirun
(``runner.py:204,250-265``, ``communicator/mpi_nccl_comm.py:54-152``).
"""
import json
import sys

import numpy as np


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    assert nproc == 4
    from hetu_tpu.parallel import multihost as mh

    assert mh.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid,
                         local_device_count=1)
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 4 and jax.device_count() == 4
    devs = np.array(jax.devices()).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "tp"))

    B, DIN, DOUT = 8, 4, 8
    rng = np.random.RandomState(0)
    X = rng.randn(B, DIN).astype(np.float32)
    W_true = rng.randn(DIN, DOUT).astype(np.float32)
    Y = X @ W_true

    # this process's dp group feeds its half of the batch (both tp peers in
    # a group feed the SAME rows — host-level data parallelism)
    dp_i = pid // 2
    lo, hi = dp_i * (B // 2), (dp_i + 1) * (B // 2)

    wsh = NamedSharding(mesh, P(None, "tp"))
    rep = NamedSharding(mesh, P())
    W0 = np.zeros((DIN, DOUT), np.float32)
    W = jax.make_array_from_callback((DIN, DOUT), wsh, lambda idx: W0[idx])

    @jax.jit
    def step(W, x, y):
        def loss_fn(W):
            return jnp.mean((x @ W - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(W)
        newW = jax.lax.with_sharding_constraint(W - 0.1 * g, wsh)
        return loss, newW

    wsum_fn = jax.jit(jnp.sum, out_shardings=rep)

    losses = []
    for _ in range(10):
        x = mh.host_local_batch(mesh, P("dp", None), X[lo:hi])
        y = mh.host_local_batch(mesh, P("dp", None), Y[lo:hi])
        loss, W = step(W, x, y)
        losses.append(float(loss))

    mh.barrier("dptp_final")
    pids = mh.process_allgather(np.array([pid], np.int32))
    print(json.dumps({
        "pid": pid,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "w_sum": float(wsum_fn(W)),
        "gathered_pids": np.asarray(pids).ravel().tolist(),
    }), flush=True)
    mh.shutdown()


if __name__ == "__main__":
    main()
