"""Telemetry subsystem (hetu_tpu/telemetry, docs/OBSERVABILITY.md):

- tracer spans nest and flush to valid Chrome-trace JSON (Perfetto schema)
- histogram percentile math and the Prometheus textfile exposition format
- the per-step JSONL records validate under ``hetutop --check``; per-rank
  traces merge into rank lanes and validate under ``hetutrace --check``
  (both CLIs smoke-tested as subprocesses, the CI pattern)
- an instrumented Executor run produces step records with phases; the
  graphboard timings overlay renders from them
- ``telemetry="off"`` (the default) leaves the hot path with ZERO
  instrument calls — asserted by patching every metric/trace mutator
- PS RPC counters + extended kServerStats under a live ``local_cluster``
- satellite regressions: AUC NaN-on-degenerate, bench telemetry line,
  heturun run summary, PSSupervisor stats export
"""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_telemetry(tmp_path, monkeypatch):
    """Isolated telemetry singleton: clean env, tmp output dir, and a
    guaranteed shutdown so no other test inherits an active instance."""
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    yield str(tmp_path / "tel")
    telemetry.shutdown()


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_span_nesting_flush_valid_chrome_json(tmp_path):
    from hetu_tpu.telemetry.tracing import Tracer
    path = str(tmp_path / "trace.json")
    tr = Tracer(path, rank=3)
    with tr.span("outer", args={"step": 1}):
        with tr.span("inner"):
            pass
    tr.instant("marker", args={"k": "v"})
    tr.flush()
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert isinstance(evs, list)
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"outer", "inner"}
    for e in spans.values():
        for k in ("ts", "dur", "pid", "tid"):
            assert k in e, (e, k)
        assert e["pid"] == 3
    # nesting: inner lies within outer on the same lane
    o, i = spans["outer"], spans["inner"]
    assert o["tid"] == i["tid"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"]
    # process_name metadata gives the rank lane its label
    meta = [e for e in evs if e["ph"] == "M" and e["name"] == "process_name"]
    assert meta and meta[0]["args"]["name"] == "rank 3"
    assert any(e["ph"] == "i" and e["name"] == "marker" for e in evs)


def test_tracer_file_always_valid_midrun(tmp_path):
    """flush_every causes periodic rewrites; the on-disk file must be valid
    JSON after every flush (crash durability for the resilience paths)."""
    from hetu_tpu.telemetry.tracing import Tracer
    path = str(tmp_path / "t.json")
    tr = Tracer(path, rank=0, flush_every=2)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    doc = json.load(open(path))  # auto-flushed at 2-span boundaries
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])


def test_xla_trace_window_spec_parsing():
    from hetu_tpu.telemetry.tracing import XlaTraceWindow
    w = XlaTraceWindow("/tmp/xla:100:5")
    assert (w.dir, w.start_step, w.n_steps) == ("/tmp/xla", 100, 5)
    w2 = XlaTraceWindow("/tmp/xla")
    assert (w2.start_step, w2.n_steps) == (0, 10)
    # the annotation is usable as a context manager with or without jax
    with XlaTraceWindow.step_annotation(7):
        pass


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_histogram_percentile_math():
    from hetu_tpu.telemetry.registry import Histogram
    h = Histogram("t_ms")
    for v in range(1, 101):   # 1..100
        h.observe(float(v))
    assert h.count == 100 and h.min == 1.0 and h.max == 100.0
    assert h.mean == pytest.approx(50.5)
    assert h.percentile(50) == pytest.approx(50.0, abs=1.0)
    assert h.percentile(99) == pytest.approx(99.0, abs=1.0)
    assert h.percentile(0) == 1.0 and h.percentile(100) == 100.0
    # cumulative bucket counts are monotone and end at count
    cum, total = 0, []
    for n in h.bucket_counts:
        cum += n
        total.append(cum)
    assert total[-1] == h.count
    assert Histogram("empty").percentile(50) is None


def test_prometheus_textfile_format(tmp_path):
    from hetu_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("hetu_steps_total").inc(3)
    reg.gauge("hetu_flops_per_step", {"sub": "train"}).set(1e9)
    h = reg.histogram("hetu_step_time_ms", buckets=(1, 10))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(50.0)
    text = reg.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE hetu_steps_total counter" in lines
    assert "hetu_steps_total 3" in lines
    assert '# TYPE hetu_flops_per_step gauge' in lines
    assert 'hetu_flops_per_step{sub="train"} 1e+09' in lines
    assert 'hetu_step_time_ms_bucket{le="1"} 1' in lines
    assert 'hetu_step_time_ms_bucket{le="10"} 2' in lines
    assert 'hetu_step_time_ms_bucket{le="+Inf"} 3' in lines
    assert "hetu_step_time_ms_count 3" in lines
    assert any(l.startswith("hetu_step_time_ms_sum ") for l in lines)
    # atomic textfile write
    p = reg.write_prometheus(str(tmp_path / "m.prom"))
    assert open(p).read() == text


def test_registry_snapshot_flat_scalars():
    from hetu_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.histogram("h").observe(2.0)
    snap = reg.snapshot()
    assert snap["c"] == 1.0
    assert snap["h_count"] == 1 and snap["h_p50"] == 2.0
    assert all(isinstance(v, (int, float)) for v in snap.values())


def test_type_conflict_raises():
    from hetu_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


# ---------------------------------------------------------------------------
# executor integration + CLIs
# ---------------------------------------------------------------------------

def _tiny_mlp(ht):
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((8, 2), stddev=0.1, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    opt = ht.optim.SGDOptimizer(0.1)
    return x, y_, loss, opt.minimize(loss)


def _feeds(rng, bs=16):
    return (rng.randn(bs, 8).astype(np.float32),
            np.eye(2, dtype=np.float32)[rng.randint(0, 2, bs)])


def test_executor_trace_end_to_end(fresh_telemetry, tmp_path):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.telemetry import hetutop, hetutrace
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op], "eval": [loss]},
                     ctx=ht.cpu(0), seed=0, telemetry="trace")
    assert ex.telemetry is not None and ex.config.telemetry == "trace"
    rng = np.random.RandomState(0)
    for _ in range(6):
        xv, yv = _feeds(rng)
        ex.run("train", feed_dict={x: xv, y_: yv})
    xv, yv = _feeds(rng)
    ex.run("eval", feed_dict={x: xv, y_: yv})
    tel = telemetry.get()
    tel.flush()

    # step records: phases + metrics, validated by the hetutop checker
    assert hetutop.check_dir(fresh_telemetry) == 0
    recs = [json.loads(l) for l in
            open(os.path.join(fresh_telemetry, "metrics-r0.jsonl"))]
    steps = [r for r in recs if r.get("kind") == "step"]
    assert len(steps) == 6   # training only; the eval run is a histogram
    assert {"prestep_ms", "dispatch_ms", "poststep_ms"} <= set(
        steps[0]["phases"])
    assert "compile_ms" in steps[0]["phases"]          # first step compiled
    assert "compile_ms" not in steps[1]["phases"]      # second did not
    # snapshots ride the cadence (step 0) + the flush-time "final" record
    assert "metrics" in steps[0] and "metrics" not in steps[1]
    finals = [r for r in recs if r.get("kind") == "final"]
    assert finals, "flush() writes a closing metrics snapshot"
    m = finals[-1]["metrics"]
    assert m["hetu_steps_total"] == 6
    assert m["hetu_examples_total"] == 6 * 16
    assert m["hetu_compiles_total"] == 1
    assert m["hetu_recompiles_total"] == 0
    # the eval run lands in the registry (it postdates the last step record)
    assert tel.metrics.snapshot()["hetu_eval_time_ms_count"] == 1
    assert any(r.get("kind") == "run_info" and "device_kind" in r
               for r in recs)

    # trace: spans for feed/compute/step phases, eval lane, valid schema
    trace_path = os.path.join(fresh_telemetry, "trace-r0.json")
    assert hetutrace.check_file(trace_path) == 0
    names = {e["name"] for e in json.load(open(trace_path))["traceEvents"]
             if e.get("ph") == "X"}
    assert {"step:train", "feed", "compile", "compute", "poststep",
            "eval:eval"} <= names

    # graphboard satellite: timings overlay renders heat + phase table
    from hetu_tpu import graphboard
    out = graphboard.render(ex, name="train",
                            out_dir=str(tmp_path / "gb"), timings=True)
    html = open(os.path.join(out, "index.html")).read()
    assert "phase timings" in html and "compute (dispatch)" in html
    svg = open(os.path.join(out, "output.svg")).read()
    assert "ms step (" in svg   # tooltip carries the phase share

    # prometheus textfile landed on flush
    prom = open(os.path.join(fresh_telemetry, "metrics-r0.prom")).read()
    assert "# TYPE hetu_step_time_ms histogram" in prom


def test_render_timings_without_telemetry_notes_absence(tmp_path):
    import hetu_tpu as ht
    from hetu_tpu import graphboard, telemetry
    telemetry.shutdown()
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)
    out = graphboard.render(ex, out_dir=str(tmp_path / "gb"), timings=True)
    assert "no telemetry data" in open(os.path.join(out, "index.html")).read()


def test_off_mode_adds_no_instrument_calls(tmp_path, monkeypatch):
    """The zero-overhead-off contract: with telemetry off (the default),
    a training step performs NO metric observations, counter increments,
    gauge sets, trace appends, or JSONL writes — counted by patching every
    mutator in the telemetry layer."""
    from hetu_tpu import telemetry
    from hetu_tpu.telemetry import registry as reg_mod, tracing as tr_mod
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    calls = []
    monkeypatch.setattr(reg_mod.Histogram, "observe",
                        lambda self, v: calls.append(("observe", v)))
    monkeypatch.setattr(reg_mod.Counter, "inc",
                        lambda self, v=1.0: calls.append(("inc", v)))
    monkeypatch.setattr(reg_mod.Gauge, "set",
                        lambda self, v: calls.append(("set", v)))
    monkeypatch.setattr(reg_mod.JsonlSink, "write",
                        lambda self, rec: calls.append(("jsonl", rec)))
    monkeypatch.setattr(tr_mod.Tracer, "_append",
                        lambda self, ev: calls.append(("trace", ev)))
    import hetu_tpu as ht
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)
    assert ex.telemetry is None and ex.config.telemetry == "off"
    rng = np.random.RandomState(0)
    for _ in range(3):
        xv, yv = _feeds(rng)
        ex.run("train", feed_dict={x: xv, y_: yv})
    assert calls == []   # instrument count: exactly zero
    assert ex.subexecutors["train"].last_phases is None


def test_hetutop_check_rejects_invalid(tmp_path):
    from hetu_tpu.telemetry import hetutop
    d = tmp_path / "tel"
    d.mkdir()
    assert hetutop.check_dir(str(d)) == 1           # no files
    (d / "metrics-r0.jsonl").write_text("not json\n")
    assert hetutop.check_dir(str(d)) == 1           # invalid line
    (d / "metrics-r0.jsonl").write_text(
        json.dumps({"kind": "step", "sub": "t", "step": 0}) + "\n")
    assert hetutop.check_dir(str(d)) == 1           # missing required keys
    (d / "metrics-r0.jsonl").write_text(
        json.dumps({"kind": "step", "sub": "t", "step": 0, "ts": 1.0,
                    "step_ms": 1.5, "metrics": {}}) + "\n")
    assert hetutop.check_dir(str(d)) == 0


def test_hetutrace_merge_rank_lanes(tmp_path):
    from hetu_tpu.telemetry.tracing import Tracer
    from hetu_tpu.telemetry import hetutrace
    d = tmp_path / "tel"
    for r in range(2):
        tr = Tracer(str(d / f"trace-r{r}.json"), rank=r)
        with tr.span("step"):
            pass
        tr.flush()
    out = hetutrace.merge([str(d)], str(tmp_path / "merged.json"))
    assert hetutrace.check_file(out) == 0
    evs = json.load(open(out))["traceEvents"]
    assert {e["pid"] for e in evs if e.get("ph") == "X"} == {0, 1}
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert lanes == {"rank 0", "rank 1"}


def test_cli_check_smoke(tmp_path):
    """bin/hetutop --check and bin/hetutrace --check as real subprocesses
    (exit 0 on valid, 1 on invalid) — the hetulint --json CI pattern."""
    from hetu_tpu.telemetry.tracing import Tracer
    d = tmp_path / "tel"
    d.mkdir()
    (d / "metrics-r0.jsonl").write_text(
        json.dumps({"kind": "step", "sub": "t", "step": 0, "ts": 1.0,
                    "step_ms": 1.5, "metrics": {"hetu_steps_total": 1}})
        + "\n")
    tr = Tracer(str(d / "trace-r0.json"))
    with tr.span("step"):
        pass
    tr.flush()
    env = {**os.environ, "PYTHONPATH": REPO}
    rc_top = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetutop"),
         str(d), "--check"], env=env, capture_output=True, text=True)
    assert rc_top.returncode == 0, rc_top.stderr + rc_top.stdout
    rc_tr = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetutrace"), "--check",
         str(d / "trace-r0.json")], env=env, capture_output=True, text=True)
    assert rc_tr.returncode == 0, rc_tr.stderr + rc_tr.stdout
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetutrace"), "--check",
         str(d / "metrics-r0.jsonl")], env=env, capture_output=True,
        text=True)
    assert bad.returncode == 1


# ---------------------------------------------------------------------------
# PS RPC counters under a live local cluster
# ---------------------------------------------------------------------------

def _telemetry_ps_worker(client, rank, tmpdir):
    import os
    tel_dir = os.path.join(tmpdir, "tel")
    os.environ["HETU_TELEMETRY_DIR"] = tel_dir
    os.environ["HETU_TELEMETRY_PS_EVERY"] = "1"
    import numpy as np
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.telemetry import hetutop
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.zeros((8, 1), name="w")
    err = ht.matmul_op(x, w) - y_
    loss = ht.reduce_mean_op(ht.mul_op(err, err), [0])
    opt = ht.optim.SGDOptimizer(0.05)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="PS", telemetry="metrics")
    rng = np.random.RandomState(3)
    for _ in range(4):
        xv = rng.randn(8, 8).astype(np.float32)
        yv = (xv.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        ex.run("train", feed_dict={x: xv, y_: yv})
    ex.close()
    tel = telemetry.get()
    assert tel is not None
    snap = tel.metrics.snapshot()
    # PS push latency histogram saw this run's gradient pushes
    assert snap.get("hetu_ps_push_ms_count", 0) > 0, snap
    # critical-path PS RPC share of the step (hetuprof pillar 1; the
    # executor stamps the staging-pull + push blocks on PS runs)
    assert 0 < snap.get("hetu_comm_fraction", 0) <= 1, snap
    tel.flush()
    # extended kServerStats: request count, apply latency, dedup ledger
    st = client.ServerStats(0)
    assert st["requests"] > 0
    assert st["apply_ms_avg"] is not None and st["apply_ms_avg"] >= 0
    assert st["dedup_clients"] >= 1
    assert st["snapshot_age_ms"] == -1   # no snapshot dir in this cluster
    cs = client.ClientStats()
    assert cs["rpcs"] > 0 and cs["retries"] == 0 and cs["failovers"] == 0
    # ps_server rows landed in the JSONL and the checker reads them
    assert hetutop.check_dir(tel_dir) == 0
    recs = [json.loads(l) for l in
            open(os.path.join(tel_dir, "metrics-r0.jsonl"))]
    ps_rows = [r for r in recs if r.get("kind") == "ps_server"]
    assert ps_rows and all("snapshot_age_ms" in r for r in ps_rows)


def test_ps_rpc_counters_local_cluster(tmp_path):
    from test_ps import run_cluster
    run_cluster(_telemetry_ps_worker, tmp_path, n_workers=1, n_servers=1)


def test_ps_supervisor_stats_export(tmp_path, monkeypatch):
    """PSSupervisor exports lapse/respawn counters and appends its events
    to <HETU_TELEMETRY_DIR>/ps_supervisor.jsonl."""
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    from hetu_tpu.ps.supervisor import PSSupervisor
    sup = PSSupervisor("127.0.0.1", 1, 1, respawn=lambda i: None)
    assert sup.stats() == {"lapses": 0, "respawns": 0, "max_respawns": 3,
                           "fatal": None}
    sup._note("server 0 dead; respawning")
    rec = json.loads(open(tmp_path / "ps_supervisor.jsonl").read())
    assert rec["name"] == "ps_supervisor" and "respawns" in rec
    assert "server 0 dead" in rec["message"]


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_auc_degenerate_inputs_nan_with_warning():
    from hetu_tpu import metrics as M
    # healthy case unchanged
    assert M.auc([0, 1, 0, 1], [0.1, 0.9, 0.2, 0.8]) > 0.99
    for labels, preds, curve in (
            ([1, 1, 1], [0.5, 0.6, 0.7], "ROC"),   # all positive
            ([0, 0, 0], [0.5, 0.6, 0.7], "ROC"),   # all negative
            ([], [], "ROC"),                        # empty
            ([0, 0], [0.1, 0.2], "PR")):            # PR without positives
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            v = M.auc(labels, preds, curve=curve)
        assert np.isnan(v), (labels, curve, v)
        assert len(w) == 1 and "undefined" in str(w[0].message)
    # PR with positives but single-class-negative is fine (defined)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        v = M.auc([1, 1], [0.6, 0.9], curve="PR")
    assert not np.isnan(v) and not w


def test_bench_telemetry_line(tmp_path):
    import bench
    led = bench._Ledger(str(tmp_path / "BENCH_PARTIAL.json"))
    led.record("resnet18_bf16_bs128",
               {"samples_per_sec": 10.0, "step_ms": 1.0, "mfu": 0.2},
               device="fake-v5e")
    line = json.loads(
        open(tmp_path / "BENCH_TELEMETRY.jsonl").read().strip())
    assert line["cell"] == "resnet18_bf16_bs128"
    assert line["device_kind"] == "fake-v5e"
    assert line["peak_tflops_assumed"] == bench.PEAK_TFLOPS
    assert line["samples_per_sec"] == 10.0
    # ledger-less (smoke) mode writes no telemetry line either
    bench._Ledger("").record("x", {"samples_per_sec": 1.0}, device="d")
    assert not (tmp_path / "x").exists()


def test_heturun_run_summary(tmp_path, monkeypatch):
    from hetu_tpu import runner
    (tmp_path / "metrics-r0.jsonl").write_text("{}\n")
    (tmp_path / "stale.tmp").write_text("")
    monkeypatch.setattr(runner, "_tel_dir", str(tmp_path))
    runner._write_telemetry_summary(0, False, 2)
    s = json.loads(open(tmp_path / "run_summary.json").read())
    assert s["workers"] == 2 and s["exit_code"] == 0
    assert s["files"] == ["metrics-r0.jsonl"]   # .tmp and itself excluded
