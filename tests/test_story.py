"""hetustory — unified run ledger (docs/OBSERVABILITY.md pillar 7).

The acceptance proofs live here: a real local_cluster training run whose
telemetry dir passes ``hetustory --audit`` (exit 0) and fails it (exit 1,
naming the invariant and both rows) after one seeded row corruption; an
anomaly-guard rollback that freezes an incident report drawing on >= 4
distinct ledger families; and ``--diff`` surfacing a seeded step-time
regression with plan context. The rest are the reader satellites: the
torn-tail-vs-mid-file classification contract, the rotation-under-reader
regression test (records that land between a poll and the rename must be
recovered from the ``.1`` backup — the ad-hoc readers this PR retired
silently lost them), one crash-truncated fixture per ledger family, the
run_id/incarnation base-field stamp, and the jax-free CLI self-test.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HETUSTORY = os.path.join(REPO, "bin", "hetustory")


def _story():
    from hetu_tpu.telemetry import story
    return story


def _cli(*args):
    return subprocess.run([sys.executable, HETUSTORY, *map(str, args)],
                          capture_output=True, text=True)


# ---------------------------------------------------------------------------
# reader: torn-tail classification + rotation recovery
# ---------------------------------------------------------------------------

def test_torn_tail_tolerated_midfile_is_error(tmp_path):
    story = _story()
    p = tmp_path / "metrics-r0.jsonl"
    p.write_text('{"kind": "step", "step": 1}\n'
                 'not json at all\n'
                 '{"kind": "step", "step": 2}\n'
                 '[1, 2]\n'
                 '{"kind": "step", "step": 3}\n'
                 '{"kind": "step", "step": 4, "trun')
    errors = []
    rows = story.read_rows(str(p), errors=errors)
    assert [r.rec["step"] for r in rows] == [1, 2, 3]
    reasons = [e["reason"] for e in errors]
    # mid-file garbage and non-objects are real errors; the torn LAST
    # line is the crash signature every ledger family tolerates
    assert reasons == ["invalid-json", "not-object", "torn-tail"]
    assert errors[0]["line"] == 2 and errors[-1]["line"] == 6
    # format_error keeps hetutop --check's historical strings
    assert "invalid JSON" in story.format_error(errors[0])
    assert "not an object" in story.format_error(errors[1])


def test_rotation_under_reader_recovers_backup_records(tmp_path):
    """The regression this PR fixes: records appended between a poll and
    the rotation rename used to be LOST by every offset-based reader
    (they re-read the new generation from the stale offset). The shared
    LedgerFollower drains the ``.1`` backup from the stored offset when
    the inode flips."""
    story = _story()
    p = str(tmp_path / "metrics-r0.jsonl")

    def w(path, recs, mode="a"):
        with open(path, mode) as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    fol = story.LedgerFollower()
    w(p, [{"step": 1}, {"step": 2}], mode="w")
    assert [r["step"] for r in fol.poll(p)] == [1, 2]
    # records 3 lands AFTER the poll, then the writer rotates
    w(p, [{"step": 3}])
    os.replace(p, p + ".1")
    w(p, [{"step": 4}], mode="w")
    assert [r["step"] for r in fol.poll(p)] == [3, 4]
    # in-place truncation (a fresh run reusing the path, now smaller)
    # restarts at 0
    with open(p, "w") as f:
        f.write('{"step":9}\n')
    assert [r["step"] for r in fol.poll(p)] == [9]
    # a partial line (no newline yet) is retried, not consumed
    with open(p, "a") as f:
        f.write('{"step": 10')
    assert fol.poll(p) == []
    with open(p, "a") as f:
        f.write(', "ok": true}\n')
    assert [r["step"] for r in fol.poll(p)] == [10]


def test_ledger_files_orders_backup_first_and_skips_tmp(tmp_path):
    story = _story()
    (tmp_path / "metrics-r0.jsonl").write_text('{"kind":"step","step":2}\n')
    (tmp_path / "metrics-r0.jsonl.1").write_text(
        '{"kind":"step","step":1}\n')
    (tmp_path / "metrics-r0.jsonl.tmp").write_text("{...torn")
    files = story.ledger_files("metrics", str(tmp_path))
    assert [os.path.basename(f) for f in files] \
        == ["metrics-r0.jsonl.1", "metrics-r0.jsonl"]
    rows = story.read_jsonl_rotated(str(tmp_path / "metrics-r0.jsonl"))
    assert [r["step"] for r in rows] == [1, 2]


def test_runner_scan_reads_rotated_pair(tmp_path):
    """heturun's exit scan rides the shared reader: the final step must
    come from the LIVE generation even when a ``.1`` backup exists."""
    from hetu_tpu import runner
    (tmp_path / "metrics-r0.jsonl.1").write_text(
        json.dumps({"kind": "step", "rank": 0, "step": 5}) + "\n")
    (tmp_path / "metrics-r0.jsonl").write_text(
        json.dumps({"kind": "step", "rank": 0, "step": 11}) + "\n"
        + '{"kind": "step", "torn')
    final_steps, resizes, world_versions, plan = \
        runner._scan_rank_jsonl(str(tmp_path))
    assert final_steps == {"0": 11}
    assert resizes == [] and plan is None


# ---------------------------------------------------------------------------
# crash-truncated fixture per family
# ---------------------------------------------------------------------------

def test_every_family_tolerates_its_crash_signature(tmp_path):
    """One artifact per ledger family, each cut off the way a crash cuts
    it: jsonl families get a torn tail (+ the metrics family a rotated
    pair), doc families a torn ``.tmp`` that must never be read."""
    story = _story()
    d = str(tmp_path)

    def jl(name, recs, torn=True):
        with open(os.path.join(d, name), "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
            if torn:
                f.write('{"kind": "step", "cut')

    jl("metrics-r0.jsonl.1", [{"kind": "step", "step": 1, "rank": 0}],
       torn=False)
    jl("metrics-r0.jsonl", [{"kind": "step", "step": 2, "rank": 0}])
    jl("trail-client-r0.jsonl", [{"kind": "rpc", "rank": 0, "step": 2}])
    jl("trail-server-s0.jsonl", [{"kind": "srv", "step": 2}])
    jl("trail-events.jsonl", [{"kind": "straggler", "rank": 0, "step": 2}])
    jl("pilot.jsonl", [{"era": 1, "phase": "propose", "step": 2}])
    jl("ps_supervisor.jsonl", [{"kind": "event", "name": "ps_supervisor"}])
    doc = {"schema": 1, "reason": "crash", "rank": 0, "k": 4,
           "records": []}
    with open(os.path.join(d, "flight-r0.json"), "w") as f:
        json.dump(doc, f)
    with open(os.path.join(d, "flight-r0.json.tmp"), "w") as f:
        f.write('{"schema": 1, "cut')      # crash mid-rename: never read
    with open(os.path.join(d, "job_epoch_000007.json"), "w") as f:
        json.dump({"format": 1, "epoch": 7, "servers": [], "workers": []},
                  f)
    with open(os.path.join(d, "run_summary.json"), "w") as f:
        f.write('{"final_steps": {"0": 2}, "cut')   # torn doc, classified
    errors = {}
    led = story.load_ledgers(d, errors=errors)
    assert [r.rec["step"] for r in led["metrics"]] == [1, 2]
    for fam in ("trail_client", "trail_server", "trail_events", "pilot",
                "ps_supervisor", "flight", "job_manifest"):
        assert len(led[fam]) == 1, fam
    assert led["run_summary"] == []                 # torn doc: no row
    flat = [e for errs in errors.values() for e in errs]
    assert {e["reason"] for e in flat} == {"torn-tail", "torn-doc"}, flat
    assert not any(e["path"].endswith(".tmp") for e in flat)


# ---------------------------------------------------------------------------
# run identity base fields
# ---------------------------------------------------------------------------

def test_run_identity_stamps_every_row(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.setenv("HETU_RUN_ID", "20260807-120000-42")
    monkeypatch.setenv("HETU_RUN_INCARNATION", "2")
    tel = telemetry.Telemetry("metrics", str(tmp_path), rank=0)
    tel.step_record("train", 0, 1.0)                  # hot path
    tel.step_record("train", 1, 1.0, extra_field=1)   # dict path
    tel.event("anomaly", step=1)
    tel.close()
    recs = [json.loads(l)
            for l in open(tmp_path / "metrics-r0.jsonl")]
    assert len(recs) >= 3
    for r in recs:
        assert r["run_id"] == "20260807-120000-42", r
        assert r["inc"] == 2, r


def test_run_identity_absent_outside_heturun(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_RUN_ID", raising=False)
    assert telemetry.run_identity() == (None, 0)
    tel = telemetry.Telemetry("metrics", str(tmp_path), rank=0)
    tel.step_record("train", 0, 1.0)
    tel.close()
    recs = [json.loads(l) for l in open(tmp_path / "metrics-r0.jsonl")]
    assert all("run_id" not in r and "inc" not in r for r in recs)


def test_run_identity_parses_defensively(monkeypatch):
    from hetu_tpu import telemetry
    monkeypatch.setenv("HETU_RUN_ID", "r1")
    monkeypatch.setenv("HETU_RUN_INCARNATION", "3")
    assert telemetry.run_identity() == ("r1", 3)
    monkeypatch.setenv("HETU_RUN_INCARNATION", "not-a-number")
    assert telemetry.run_identity() == ("r1", 0)
    monkeypatch.setenv("HETU_RUN_ID", "")
    assert telemetry.run_identity() == (None, 0)


# ---------------------------------------------------------------------------
# timeline + audit + incident + diff over the deterministic fixture
# ---------------------------------------------------------------------------

def test_timeline_merges_sources_and_step_range(tmp_path):
    story = _story()
    story._fixture_run(str(tmp_path))
    tl = story.load_timeline(str(tmp_path))
    assert tl["clock"]["comparable"] is True
    srcs = {e["src"] for e in tl["entries"]}
    assert {"metrics", "pilot", "flight"} <= srcs, srcs
    # merged entries are time-ordered
    ts = [e["t"] for e in tl["entries"] if e.get("t") is not None]
    assert ts == sorted(ts)
    narrow = story.load_timeline(str(tmp_path), step_range=(3, 4))
    steps = {e["rec"].get("step") for e in narrow["entries"]
             if e["rec"].get("kind") == "step"}
    assert steps and steps <= {1, 2, 3, 4, 5, 6}   # window +/- context
    out = _cli(str(tmp_path), "--step", "3:4", "--json")
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["entries"]


def test_audit_clean_fixture_exits_zero(tmp_path):
    story = _story()
    story._fixture_run(str(tmp_path))
    violations, _notes = story.audit(str(tmp_path))
    assert violations == [], violations
    out = _cli(str(tmp_path), "--audit")
    assert out.returncode == 0, out.stdout + out.stderr


def test_audit_seeded_corruption_names_invariant_and_rows(tmp_path):
    story = _story()
    story._fixture_run(str(tmp_path), corrupt=True)
    violations, _ = story.audit(str(tmp_path))
    assert [v["invariant"] for v in violations] == ["push-accounting"]
    assert len(violations[0]["rows"]) == 2          # both ledger rows
    out = _cli(str(tmp_path), "--audit")
    assert out.returncode == 1
    assert "push-accounting" in out.stdout
    assert "metrics-r0.jsonl" in out.stdout         # row locations shown


def test_diff_surfaces_seeded_regression_with_plan_context(tmp_path):
    story = _story()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    os.makedirs(a), os.makedirs(b)
    story._fixture_run(a, step_ms=10.0)
    story._fixture_run(b, step_ms=14.0)
    rep = story.diff_runs(a, b)
    assert rep["gate"]["status"] == 1               # regressed
    assert any("step_ms" in r["metric"] for r in rep["gate"]["regressions"])
    assert "predicted_step_ms" in rep["plan_delta"]
    # the fixtures act identically, so the episode context reports no
    # structural delta — the step-time shift is purely a perf regression
    assert rep["episode_delta"] == {}
    out = _cli("--diff", a, b)
    assert out.returncode == 1
    ident = story.diff_runs(a, a)
    assert ident["gate"]["status"] == 0


def test_story_check_cli_is_jaxfree_and_passes():
    out = subprocess.run(
        [sys.executable, HETUSTORY, "--check"], capture_output=True,
        text=True, env={**os.environ, "JAX_PLATFORMS": "dont_exist"})
    assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------------------
# incident: the anomaly-guard abort freezes a multi-source window
# ---------------------------------------------------------------------------

def test_anomaly_rollback_freezes_multisource_incident(tmp_path,
                                                       monkeypatch):
    """Acceptance: an anomaly-guard rollback writes one incident report
    whose window draws on >= 4 distinct ledger families."""
    import hetu_tpu as ht
    from hetu_tpu import resilience as rs
    from hetu_tpu import telemetry
    from hetu_tpu.checkpoint import TrainCheckpointer
    story = _story()
    telemetry.shutdown()
    tel_dir = tmp_path / "tel"
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tel_dir))
    monkeypatch.setenv("HETU_TELEMETRY", "metrics")
    # pre-existing artifacts from the same run's other subsystems: the
    # incident window must cut across them, not just the metrics stream
    os.makedirs(tel_dir)
    with open(tel_dir / "trail-client-r0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "anchor", "rank": 0,
                            "mono_us": 0, "wall_s": 0.0}) + "\n")
    with open(tel_dir / "pilot.jsonl", "w") as f:
        f.write(json.dumps({"era": 1, "phase": "propose", "step": 1,
                            "delta": {}}) + "\n")
    with open(tel_dir / "flight-r0.json", "w") as f:
        json.dump({"schema": 1, "reason": "anomaly", "rank": 0, "k": 4,
                   "records": []}, f)

    rng = np.random.RandomState(7)
    data_x = rng.randn(64, 6).astype(np.float32)
    data_y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)]
    x = ht.dataloader_op([ht.Dataloader(data_x, 16, "train", seed=11)])
    y_ = ht.dataloader_op([ht.Dataloader(data_y, 16, "train", seed=11)])
    w = ht.init.random_normal((6, 3), stddev=0.5, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     anomaly_guard=True, telemetry="metrics")
    try:
        with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
            sup = ex.attach_supervisor(rs.Supervisor(
                ckptr=ck, ckpt_every=1,
                anomaly=rs.AnomalyPolicy(max_consecutive=2),
                fault_injector=rs.FaultInjector(
                    "nan_grads@2,nan_grads@3")))
            with sup:
                for _ in range(4):
                    ex.run("train")
            assert sup.anomaly.rollbacks == 1
        inc = story.incident_files(str(tel_dir))
        assert len(inc) == 1, inc
        doc = json.load(open(inc[0]))
        assert doc["reason"] == "anomaly"
        populated = [f for f, rows in doc["sources"].items() if rows]
        assert len(populated) >= 4, doc["counts"]
        assert "metrics" in populated
        # the triggering anomaly event itself made it into the window
        assert any(r["rec"].get("kind") == "event"
                   and r["rec"].get("name") == "anomaly"
                   for r in doc["sources"]["metrics"])
        out = _cli(str(tel_dir), "--incident")
        assert out.returncode == 0, out.stderr
        assert "anomaly" in out.stdout
    finally:
        ex.close()
        telemetry.shutdown()


def test_incident_capture_can_be_disabled(tmp_path, monkeypatch):
    from hetu_tpu import resilience as rs
    from hetu_tpu import telemetry
    story = _story()
    telemetry.shutdown()
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("HETU_STORY_INCIDENT", "0")
    telemetry.activate("metrics", str(tmp_path), rank=0)
    try:
        rs._incident("watchdog", step=5)
        assert story.incident_files(str(tmp_path)) == []
    finally:
        telemetry.shutdown()


# ---------------------------------------------------------------------------
# live cluster: the audit over a REAL run
# ---------------------------------------------------------------------------

def _story_audit_worker(client, rank, tmpdir):
    import os
    tel_dir = os.path.join(tmpdir, "tel")
    os.environ["HETU_TELEMETRY_DIR"] = tel_dir
    os.environ["HETU_TELEMETRY_PS_EVERY"] = "1"
    os.environ["HETU_RUN_ID"] = "testrun-1"
    os.environ["HETU_RUN_INCARNATION"] = "0"
    import numpy as np
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.zeros((8, 1), name="w")
    err = ht.matmul_op(x, w) - y_
    loss = ht.reduce_mean_op(ht.mul_op(err, err), [0])
    train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="PS", bsp=True, prefetch=False,
                     telemetry="metrics")
    rng = np.random.RandomState(3)
    for _ in range(6):
        xv = rng.randn(8, 8).astype(np.float32)
        yv = (xv.sum(axis=1, keepdims=True) > 0).astype(np.float32)
        ex.run("train", feed_dict={x: xv, y_: yv})
    # quiesce, then write one final aligned ps_server/ClientStats poll:
    # the audit equality is exact only at a drained endpoint
    ex.ps_runtime.drain()
    tel = telemetry.get()
    for row in ex.ps_runtime.telemetry_stats():
        tel.record(**row)
    ex.close()
    telemetry.shutdown()


def test_live_cluster_audit_clean_then_seeded_corruption(tmp_path):
    from test_ps import run_cluster
    run_cluster(_story_audit_worker, tmp_path, n_workers=1, n_servers=1)
    tel_dir = tmp_path / "tel"
    # run identity rode every row of the real run
    recs = [json.loads(l) for l in open(tel_dir / "metrics-r0.jsonl")]
    assert recs and all(r.get("run_id") == "testrun-1" for r in recs)
    assert any(r.get("kind") == "ps_server" for r in recs)
    out = _cli(str(tel_dir), "--audit")
    assert out.returncode == 0, out.stdout + out.stderr
    # seed ONE corrupted row: the last ps_server row under-counts by one
    # update — exactly the silent-lost-write the audit exists to catch
    idx = max(i for i, r in enumerate(recs)
              if r.get("kind") == "ps_server")
    recs[idx]["updates"] = int(recs[idx]["updates"]) - 1
    with open(tel_dir / "metrics-r0.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = _cli(str(tel_dir), "--audit")
    assert out.returncode == 1, out.stdout
    assert "push-accounting" in out.stdout
    # the timeline renders the same dir (smoke over real artifacts)
    out = _cli(str(tel_dir))
    assert out.returncode == 0, out.stderr
