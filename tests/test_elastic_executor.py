"""hetu-elastic Executor integration: the in-process halves of the elastic
story — a live PS server join driven end to end by the ``ps_join`` fault
kind through the ``ElasticAgent``, and the dp re-mesh / state re-shard path
(``Executor.remesh``) on the virtual CPU mesh.

The multi-process worker worlds live in tests/test_elastic.py; this file
pays the jax/Executor import cost once for the integration seams.
"""
import os

import numpy as np
import pytest
import jax

import hetu_tpu as ht

NROWS = 40
WIDTH = 8
SLOTS = 4
BATCH = 16


def _build_ps_model():
    embed = ht.init.random_normal((NROWS, WIDTH), stddev=0.1, name="embed",
                                  is_embed=True)
    idx = ht.Variable(name="idx", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    vec = ht.embedding_lookup_op(embed, idx)
    flat = ht.array_reshape_op(vec, (-1, SLOTS * WIDTH))
    w = ht.init.xavier_uniform((SLOTS * WIDTH, 1), name="w")
    prob = ht.sigmoid_op(ht.matmul_op(flat, w))
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
    return embed, idx, y_, loss


def _gen_batch(rng):
    bidx = rng.randint(0, NROWS, (BATCH, SLOTS)).astype(np.float32)
    by = ((bidx >= NROWS // 2).sum(axis=1) > SLOTS // 2)
    return bidx, by.reshape(BATCH, 1).astype(np.float32)


def test_executor_ps_join_live_server_grow(monkeypatch):
    """``ps_join@3`` grows the live local_cluster by one PS server mid-run:
    the ElasticAgent drains/commits at the step boundary, key ranges
    migrate, the worker's partitioner sees 2 servers, and training
    continues with pulls serving from both shards."""
    from hetu_tpu.ps.local_cluster import local_cluster
    from hetu_tpu.resilience import FaultInjector, Supervisor
    from hetu_tpu import elastic

    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_ELASTIC", "1")
    monkeypatch.setenv("HETU_PS_ID_BASE", "500")
    with local_cluster(n_servers=1, n_workers=1):
        embed, idx, y_, loss = _build_ps_model()
        opt = ht.optim.SGDOptimizer(0.1)
        train_op = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="Hybrid")
        try:
            assert ex.elastic is not None, "HETU_ELASTIC must arm the agent"
            sup = ex.attach_supervisor(
                Supervisor(fault_injector=FaultInjector("ps_join@3")))
            comm = ex.ps_runtime.comm
            assert comm.num_servers == 1
            rng = np.random.RandomState(11)
            losses = []
            for _ in range(8):
                bidx, by = _gen_batch(rng)
                out = ex.run("train", feed_dict={idx: bidx, y_: by})
                losses.append(float(np.asarray(out[0].asnumpy()).ravel()[0]))
            assert comm.num_servers == 2
            assert ex.elastic.world_version == 2
            assert ex.elastic.resizes == 1
            assert all(np.isfinite(losses)), losses
            # the migrated table serves from both shards: pull every row
            rows = ex.ps_runtime.pull_sparse_rows(
                ex.ps_runtime.params[id(embed)],
                np.arange(NROWS, dtype=np.int64))
            assert rows.shape == (NROWS, WIDTH)
            assert np.isfinite(rows).all()
            # both servers hold live params now
            addrs, _ = elastic._query_book(
                "127.0.0.1", int(os.environ["DMLC_PS_ROOT_PORT"]))
            for a in addrs:
                assert elastic.server_list_params(a), a
        finally:
            ex.close()
            from hetu_tpu import ps as ps_pkg
            ps_pkg.worker_finish()


def _build_dp(seed=0):
    rng = np.random.RandomState(seed)
    wv = rng.randn(16, 4).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.Variable(name="w", value=wv.copy())
    logits = ht.matmul_op(x, w)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    opt = ht.optim.MomentumOptimizer(0.1, momentum=0.9)
    train_op = opt.minimize(loss)
    return x, y_, w, loss, train_op


def test_remesh_shrinks_dp_world_mid_run():
    """Live dp re-mesh: train 3 steps on a 4-device mesh, remesh to 2
    devices (params/slots re-placed through the checkpoint capture/restore
    path, compiled programs invalidated), train 3 more — losses and final
    weights match an uninterrupted fixed-mesh run."""
    from jax.sharding import Mesh
    assert jax.device_count() == 8
    rng = np.random.RandomState(3)
    xv = rng.randn(64, 16).astype(np.float32)
    yv = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 64)]

    # oracle: uninterrupted 6-step run (mesh size does not change the math)
    x, y_, w, loss, train_op = _build_dp()
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    ref_losses = [float(ex1.run("train", feed_dict={x: xv, y_: yv},
                                convert_to_numpy_ret_vals=True)[0])
                  for _ in range(6)]
    ref_w = np.asarray(ex1.state["params"][id(w)])

    x, y_, w, loss, train_op = _build_dp()
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("dp",))
    ex = ht.Executor({"train": [loss, train_op]}, comm_mode="AllReduce",
                     mesh=mesh4)
    got = [float(ex.run("train", feed_dict={x: xv, y_: yv},
                        convert_to_numpy_ret_vals=True)[0])
           for _ in range(3)]
    report = ex.remesh(Mesh(np.array(jax.devices()[:2]), ("dp",)))
    assert report["dp_size"] == 2
    assert ex.config.dp_size == 2
    got += [float(ex.run("train", feed_dict={x: xv, y_: yv},
                         convert_to_numpy_ret_vals=True)[0])
            for _ in range(3)]
    np.testing.assert_allclose(got, ref_losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ex.state["params"][id(w)]),
                               ref_w, rtol=1e-5, atol=1e-6)
    # optimizer slots survived the re-shard (momentum kept training exact);
    # step counter survived too
    assert ex.state["step"] == 6


def test_remesh_rejects_tp_meshes():
    from jax.sharding import Mesh
    x, y_, w, loss, train_op = _build_dp()
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    ex = ht.Executor({"train": [loss, train_op]}, comm_mode="AllReduce",
                     mesh=mesh)
    tp = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    with pytest.raises(NotImplementedError, match="model-parallel"):
        ex.remesh(tp)
