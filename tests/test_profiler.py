"""hetuprof (hetu_tpu/telemetry/profiler.py, docs/PROFILING.md):

- HLO op_name metadata parsing and scope extraction (jvp/transpose
  wrappers resolve backward work to its forward op)
- per-op attribution over a SYNTHETIC Chrome trace: lane filtering via
  trace metadata, interval-union wall time, collective bucketing, step
  normalization from hetu_step annotations
- named_scope presence in the executor's optimized HLO; the cached
  compiled-executable handle; ``last_memory_analysis``
- HBM/params/6ND telemetry gauges under ``JAX_PLATFORMS=cpu``
- the perf-regression gate's exit-code contract for {clean, regressed,
  incomplete-baseline, incomplete-current} + the ``--gate --check`` CLI
- bench.py satellites: the emergency final line (completed cells +
  ``incomplete_cells``), baseline-round selection, attn_flops parity
- hetutop's dual-denominator MFU columns
"""
import gzip
import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from hetu_tpu.telemetry import profiler as prof  # noqa: E402


# ---------------------------------------------------------------------------
# HLO metadata parsing + scope extraction
# ---------------------------------------------------------------------------

HLO_SAMPLE = """\
HloModule jit_step_fn
%fused_computation (p: f32[16,8]) -> f32[16,8] {
  ROOT %maximum.1 = f32[16,8] maximum(...), metadata={op_name="jit(step_fn)/jit(main)/Relu_6/max" source_file="x.py"}
}
ENTRY %main {
  %dot.1 = f32[16,8] dot(...), metadata={op_name="jit(step_fn)/jit(main)/MatMul_5/dot_general"}
  %fusion.2 = f32[16,8] fusion(...), kind=kLoop, metadata={op_name="jit(step_fn)/jit(main)/Gradient(w)/transpose(Gradient(w))/jvp(Relu_6)/max"}
  ROOT %all-reduce.3 = f32[16,8] all-reduce(...), metadata={op_name="jit(step_fn)/jit(main)/AllReduce_9/psum"}
}
"""


def test_hlo_op_map_parses_instructions():
    m = prof.hlo_op_map(HLO_SAMPLE)
    assert m["dot.1"].endswith("MatMul_5/dot_general")
    assert "jvp(Relu_6)" in m["fusion.2"]
    assert "maximum.1" in m and "all-reduce.3" in m


def test_scope_of_resolves_wrappers_to_forward_op():
    known = {"MatMul_5", "Relu_6", "Gradient(w)", "AllReduce_9"}
    op, bwd = prof.scope_of("jit(step_fn)/jit(main)/MatMul_5/dot_general",
                            known)
    assert (op, bwd) == ("MatMul_5", False)
    # backward work resolves to the INNERMOST op, not the Gradient node
    op, bwd = prof.scope_of(
        "jit(step_fn)/jit(main)/Gradient(w)/transpose(Gradient(w))/"
        "jvp(MatMul_5)/transpose", known)
    assert (op, bwd) == ("MatMul_5", True)
    # without a known set, hetu-shaped names (<Name>_<id>) are accepted
    op, _ = prof.scope_of("jit(f)/jit(main)/SoftmaxCrossEntropy_17/mul")
    assert op == "SoftmaxCrossEntropy_17"
    assert prof.scope_of("jit(f)/jit(main)/reduce_sum", known) == (None, False)


# ---------------------------------------------------------------------------
# synthetic-trace attribution
# ---------------------------------------------------------------------------

def _synthetic_events():
    """Two Eigen worker lanes + one python host lane, two annotated steps.
    dot.1 runs as two OVERLAPPING slices (parallel workers): total 200 us
    but wall-union 150 us."""
    meta = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 11, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/11"}},
        {"ph": "M", "pid": 7, "tid": 12, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/12"}},
        {"ph": "M", "pid": 7, "tid": 20, "name": "thread_name",
         "args": {"name": "python"}},
    ]
    evs = [
        {"ph": "X", "pid": 7, "tid": 11, "ts": 0, "dur": 100,
         "name": "dot.1"},
        {"ph": "X", "pid": 7, "tid": 12, "ts": 50, "dur": 100,
         "name": "dot.1"},
        {"ph": "X", "pid": 7, "tid": 11, "ts": 200, "dur": 50,
         "name": "fusion.2"},
        {"ph": "X", "pid": 7, "tid": 12, "ts": 300, "dur": 40,
         "name": "all-reduce.3"},
        # host-lane python work must NOT count as device time
        {"ph": "X", "pid": 7, "tid": 20, "ts": 0, "dur": 5000,
         "name": "shard_args"},
        {"ph": "X", "pid": 7, "tid": 20, "ts": 0, "dur": 400,
         "name": "hetu_step"},
        {"ph": "X", "pid": 7, "tid": 20, "ts": 500, "dur": 400,
         "name": "hetu_step"},
        # an unmapped device event lands in a visible <bucket>
        {"ph": "X", "pid": 7, "tid": 11, "ts": 400, "dur": 30,
         "name": "copy.9"},
    ]
    return meta + evs


OP_MAP = {
    "dot.1": "jit(step_fn)/jit(main)/MatMul_5/dot_general",
    "fusion.2": "jit(step_fn)/jit(main)/Gradient(w)/"
                "transpose(Gradient(w))/jvp(Relu_6)/max",
    "all-reduce.3": "jit(step_fn)/jit(main)/AllReduce_9/psum",
}
KNOWN = {"MatMul_5", "Relu_6", "Gradient(w)", "AllReduce_9"}


def test_attribute_synthetic_trace():
    att = prof.attribute(_synthetic_events(), op_map=OP_MAP,
                         known_ops=KNOWN)
    assert att.steps == 2   # from the hetu_step annotations
    rows = att.rows
    assert rows["MatMul_5"].total_us == 200
    assert rows["MatMul_5"].wall_us == 150      # overlap merged
    assert rows["MatMul_5"].count == 2
    assert rows["Relu_6"].bwd_us == 50          # via jvp/transpose wrappers
    assert rows["all-reduce.3"].family == "<collective>"
    assert att.collective_wall_us == 40
    assert "<copy>" in rows                      # unmapped but visible
    assert "shard_args" not in rows              # host lane excluded
    assert att.unattributed_us == 30
    assert 0 < att.attributed_fraction < 1
    table = att.table()
    assert "MatMul_5" in table and "us/step" in table
    d = att.as_dict()
    assert d["steps"] == 2 and d["ops"][0]["op"] == "MatMul_5"


def test_attribute_without_lane_metadata_falls_back_to_name_shape():
    evs = [e for e in _synthetic_events() if e["ph"] == "X"]
    att = prof.attribute(evs, op_map=OP_MAP, known_ops=KNOWN, steps=2)
    # no metadata: HLO-shaped lowercase names pass, PascalCase host
    # TraceMe names would not — shard_args unfortunately matches the
    # shape, which is exactly why real traces use lane metadata; here we
    # assert the mapped ops still resolve
    assert att.rows["MatMul_5"].total_us == 200
    assert att.steps == 2


def test_trace_file_roundtrip(tmp_path):
    run = tmp_path / "plugins" / "profile" / "run1"
    run.mkdir(parents=True)
    p = run / "host.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump({"traceEvents": _synthetic_events()}, f)
    files = prof.find_xla_traces(str(tmp_path))
    assert files == [str(p)]
    evs = prof.load_trace_events(files[0])
    assert any(e.get("name") == "dot.1" for e in evs)


# ---------------------------------------------------------------------------
# executor integration: named_scope, cached executable, memory analysis
# ---------------------------------------------------------------------------

def _tiny_mlp(ht):
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.1, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    opt = ht.optim.SGDOptimizer(0.1)
    return x, y_, loss, opt.minimize(loss)


def _run_steps(ex, x, y_, n=2, bs=16):
    rng = np.random.RandomState(0)
    for _ in range(n):
        xv = rng.randn(bs, 8).astype(np.float32)
        yv = np.eye(4, dtype=np.float32)[rng.randint(0, 4, bs)]
        ex.run("train", feed_dict={x: xv, y_: yv})


def test_named_scope_lands_in_optimized_hlo():
    import hetu_tpu as ht
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)
    _run_steps(ex, x, y_)
    sub = ex.subexecutors["train"]
    txt = sub.dump_hlo(stage="optimized")
    op_names = [n.name for n in sub.topo
                if not (n.is_placeholder or n.is_dataloader)]
    hit = [n for n in op_names if n in txt]
    # the heavy hitters must be navigable; tiny ops may fuse away entirely
    assert any(n.startswith("MatMul") for n in hit), (hit, op_names)
    assert any("Optimizer" in n for n in hit), hit
    # ... and the map parses back out of the text
    m = prof.hlo_op_map(txt)
    assert any("MatMul" in path for path in m.values())


def test_executable_cache_and_memory_analysis():
    import hetu_tpu as ht
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)
    _run_steps(ex, x, y_)
    sub = ex.subexecutors["train"]
    e1 = sub._executable()
    e2 = sub._executable()
    assert e1 is e2 and len(sub._exe_cache) == 1   # one fetch per signature
    cost = sub.last_cost_analysis()
    assert cost and cost.get("flops", 0) > 0
    mem = sub.last_memory_analysis()
    assert mem is not None
    for k in ("argument_bytes", "output_bytes", "temp_bytes",
              "alias_bytes", "peak_bytes"):
        assert k in mem and mem[k] >= 0, (k, mem)
    assert mem["peak_bytes"] == (mem["argument_bytes"] + mem["output_bytes"]
                                 + mem["temp_bytes"] - mem["alias_bytes"])
    # a second signature gets its own cached handle
    _run_steps(ex, x, y_, n=1, bs=32)
    sub._executable()
    assert len(sub._exe_cache) == 2


def test_memory_and_6nd_gauges_under_cpu(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    import hetu_tpu as ht
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     telemetry="metrics")
    _run_steps(ex, x, y_, n=3)
    snap = ex.telemetry.metrics.snapshot()
    assert snap["hetu_params_total"] == 32            # the 8x4 weight
    assert snap["hetu_flops_per_step_6nd"] == 6.0 * 32 * 16
    assert snap["hetu_hbm_peak_bytes"] > 0
    assert snap["hetu_hbm_argument_bytes"] > 0
    mem = ex.subexecutors["train"].last_memory_analysis()
    assert snap["hetu_hbm_peak_bytes"] == mem["peak_bytes"]
    telemetry.shutdown()


def test_xla_trace_window_advertised_in_jsonl(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    monkeypatch.setenv("HETU_XLA_TRACE", str(tmp_path / "xla") + ":5:3")
    tel = telemetry.activate("metrics")
    tel.flush()
    recs = [json.loads(l) for l in
            open(tmp_path / "tel" / "metrics-r0.jsonl")]
    w = [r for r in recs if r.get("kind") == "xla_trace"]
    assert w and w[0]["start_step"] == 5 and w[0]["n_steps"] == 3
    telemetry.shutdown()


# ---------------------------------------------------------------------------
# the perf-regression gate
# ---------------------------------------------------------------------------

GOOD = {"detail": {"a": {"samples_per_sec": 100.0, "step_ms": 10.0},
                   "b": {"mfu_6nd": 0.3, "tokens_per_sec": 5000.0}},
        "value": 100.0}


def _gate(base, cur, tol=10.0):
    bc, bm = prof.normalize_summary(base)
    cc, cm = prof.normalize_summary(cur)
    return prof.gate(bc, cc, tol, baseline_meta=bm, current_meta=cm)


def test_gate_clean_on_identical_rerun():
    res = _gate(GOOD, GOOD)
    assert res.status == prof.GATE_OK and not res.regressions
    assert res.compared == 4


def test_gate_regressed_on_slowed_current():
    slow = json.loads(json.dumps(GOOD))
    slow["detail"]["a"]["samples_per_sec"] = 70.0   # -30% < -10% tol
    slow["detail"]["a"]["step_ms"] = 14.3
    res = _gate(GOOD, slow)
    assert res.status == prof.GATE_REGRESSED
    cells = {r["cell"] for r in res.regressions}
    assert cells == {"a"}
    assert "REGRESSED" in res.report()
    # within tolerance: clean (and an improvement is not a regression)
    ok = json.loads(json.dumps(GOOD))
    ok["detail"]["a"]["samples_per_sec"] = 95.0     # -5% within tol
    ok["detail"]["b"]["tokens_per_sec"] = 9000.0    # improvement
    res = _gate(GOOD, ok)
    assert res.status == prof.GATE_OK
    assert res.improvements and not res.regressions


def test_gate_incomplete_current_never_reads_as_win_or_loss():
    part = {"detail": {"a": GOOD["detail"]["a"],
                       "b": {"error": "rc=124: backend died"}},
            "value": 100.0, "incomplete_cells": ["b"]}
    res = _gate(GOOD, part)
    assert res.status == prof.GATE_INCOMPLETE_CURRENT
    assert res.incomplete == ["b"] and not res.regressions


def test_gate_incomplete_baseline_distinct_code():
    dead = {"detail": {"a": {"error": "skipped: backend unresponsive"}},
            "value": None}
    assert _gate(dead, GOOD).status == prof.GATE_INCOMPLETE_BASELINE
    # the BENCH_r05 wrapper form: rc=124, parsed null
    wrapper = {"n": 5, "cmd": "python bench.py", "rc": 124, "parsed": None}
    bc, bm = prof.normalize_summary(wrapper)
    assert bc == {} and bm["incomplete"]


def test_gate_files_and_cli(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(GOOD))
    cur.write_text(json.dumps(GOOD))
    res = prof.gate_files(str(base), str(cur))
    assert res.status == prof.GATE_OK
    # unreadable current/baseline -> the matching incomplete code
    assert prof.gate_files(str(base), str(tmp_path / "nope.json")).status \
        == prof.GATE_INCOMPLETE_CURRENT
    assert prof.gate_files(str(tmp_path / "nope.json"), str(cur)).status \
        == prof.GATE_INCOMPLETE_BASELINE
    env = {**os.environ, "PYTHONPATH": REPO}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuprof"),
         "--gate", "--check"], env=env, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "incomplete-baseline -> exit 3 ok" in r.stdout
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuprof"),
         "--gate", str(base), "--current", str(cur)],
        env=env, capture_output=True, text=True)
    assert r.returncode == 0 and "clean" in r.stdout


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------

def test_roofline_classifies_op_families():
    import hetu_tpu as ht
    x = ht.Variable(name="x", value=np.zeros((512, 512), np.float32),
                    trainable=False)
    w = ht.init.random_normal((512, 2048), stddev=0.1, name="w")
    h = ht.relu_op(ht.matmul_op(x, w))
    rows = prof.roofline_rows([h], training=False)
    by_fam = {r.family: r for r in rows}
    assert "MatMul" in by_fam and "Relu" in by_fam
    mm = by_fam["MatMul"]
    assert mm.flops == 2.0 * 512 * 2048 * 512
    assert mm.bound in ("compute", "memory")
    # relu is pure traffic: memory-bound at any realistic ridge
    assert by_fam["Relu"].bound == "memory"
    assert by_fam["Relu"].intensity < mm.intensity
    txt = prof.format_roofline(rows)
    assert "MatMul" in txt and "ridge" in txt


def test_roofline_joins_measured_times():
    import hetu_tpu as ht
    x = ht.Variable(name="x", shape=(16, 8), trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.1, name="w")
    out = ht.matmul_op(x, w)
    att = prof.attribute(_synthetic_events(), op_map={
        "dot.1": f"jit(f)/jit(main)/{out.name}/dot_general"},
        known_ops={out.name})
    rows = prof.roofline_rows([out], training=False, attribution=att)
    mm = next(r for r in rows if r.family == "MatMul")
    assert mm.measured_us == pytest.approx(150 / 2)   # wall/steps
    assert mm.residual is not None and mm.residual > 0


# ---------------------------------------------------------------------------
# bench.py satellites
# ---------------------------------------------------------------------------

def _bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench", mod)
    spec.loader.exec_module(sys.modules["bench"])
    return sys.modules["bench"]


def test_bench_assemble_final_partial_run():
    bench = _bench()
    keys = ["resnet18_f32_bs128", "bert_base_pretrain_seq512"]
    detail = {"resnet18_f32_bs128": {"samples_per_sec": 5000.0,
                                     "step_ms": 25.6}}
    line = bench._assemble_final(detail, keys, error="terminated by signal "
                                 "15 before completion")
    assert line["value"] == 5000.0                 # completed cell survives
    assert line["incomplete_cells"] == ["bert_base_pretrain_seq512"]
    assert "error" in line
    # the gate reads this as incomplete, never win/loss
    cells, meta = prof.normalize_summary(line)
    assert meta["incomplete"]
    # nothing completed: value is null, every cell incomplete
    line = bench._assemble_final({}, keys)
    assert line["value"] is None
    assert line["incomplete_cells"] == keys


def test_bench_latest_good_round_skips_dead_rounds(tmp_path):
    bench = _bench()
    (tmp_path / "BENCH_r07.json").write_text(json.dumps(
        {"n": 7, "rc": 124, "cmd": "x", "parsed": None}))
    (tmp_path / "BENCH_r06.json").write_text(json.dumps(
        {"n": 6, "rc": 0, "cmd": "x", "parsed": GOOD}))
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "rc": 0, "cmd": "x", "parsed": GOOD}))
    pick = bench._latest_good_round(str(tmp_path))
    assert pick is not None and os.path.basename(pick) == "BENCH_r06.json"
    assert bench._latest_good_round(str(tmp_path / "empty")) is None


def test_attn_flops_parity_with_bench():
    bench = _bench()
    args = (32, 512, 12, 768, False)
    assert bench._attn_flops(*args) == prof.attn_flops(*args)
    assert prof.attn_flops(32, 512, 12, 768, True) \
        == prof.attn_flops(*args) / 2.0


# ---------------------------------------------------------------------------
# hetutop dual-denominator MFU + profile_dir
# ---------------------------------------------------------------------------

def test_hetutop_reports_both_mfu_denominators(tmp_path):
    from hetu_tpu.telemetry import hetutop
    d = tmp_path / "tel"
    d.mkdir()
    n_params, tokens = 110_000_000, 32 * 512
    f6 = 6.0 * n_params * tokens
    recs = [
        {"kind": "run_info", "ts": 1.0, "rank": 0,
         "device_kind": "fake-v5e", "peak_tflops_assumed": 197.0},
        {"kind": "model_info", "ts": 1.0, "rank": 0, "n_layers": 12,
         "d_model": 768, "seq_len": 512, "causal": False,
         "n_params": n_params},
        {"kind": "step", "ts": 2.0, "rank": 0, "sub": "train", "step": 1,
         "step_ms": 215.0,
         "metrics": {"hetu_flops_per_step_6nd": f6,
                     "hetu_params_total": float(n_params)}},
    ]
    (d / "metrics-r0.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    state = hetutop.gather(str(d))
    mfu6, mfu_a = hetutop._mfu_pair(
        state["ranks"][0]["metrics"], state["model"], 215.0, 197.0)
    # docs/ROOFLINE.md BERT numbers: ~25% 6ND, ~28% attention-inclusive
    assert mfu6 == pytest.approx(25.5, abs=1.0)
    assert mfu_a > mfu6   # attention add-on raises utilization
    assert mfu_a == pytest.approx(mfu6 * 1.086, rel=0.02)
    frame = hetutop.render_frame(state)
    assert "MFU6nd%" in frame and "MFUatt%" in frame
    # without model geometry the attention column falls back to the
    # measured cost-analysis gauge
    m = {"hetu_flops_per_step_6nd": f6, "hetu_flops_per_step": f6 * 1.1}
    mfu6b, mfu_ab = hetutop._mfu_pair(m, {}, 215.0, 197.0)
    assert mfu_ab == pytest.approx(mfu6b * 1.1, rel=1e-6)


def test_profile_dir_reports_partial_as_partial(tmp_path):
    d = tmp_path / "tel"
    d.mkdir()
    (d / "metrics-r0.jsonl").write_text("")
    rep = prof.profile_dir(str(d))
    assert rep["breakdown"] is None
    assert any("no step records" in w for w in rep["incomplete"])
    assert any("trace" in w for w in rep["incomplete"])


def test_profile_executor_end_to_end(tmp_path, monkeypatch):
    """The acceptance path (docs/PROFILING.md): a real executor run under
    telemetry=trace with a bounded HETU_XLA_TRACE window -> per-op time
    table attributing >= 85% of observed device time to graph ops (the
    'within 15% of the measured compute span' criterion), with backward
    shares and the exact HLO join."""
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    monkeypatch.setenv("HETU_XLA_TRACE", str(tmp_path / "xla") + ":2:3")
    import hetu_tpu as ht
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     telemetry="trace")
    _run_steps(ex, x, y_, n=7, bs=64)
    telemetry.get().flush()
    rep = prof.profile_executor(ex, "train")
    att = rep["attribution"]
    assert att.steps == 3                      # the configured window
    assert att.rows and att.device_wall_us > 0
    matmul = [r for r in att.rows.values() if r.family == "MatMul"]
    assert matmul and matmul[0].bwd_us > 0     # backward work resolved
    assert att.attributed_fraction >= 0.85, att.table()
    telemetry.shutdown()


def test_cli_attr_mode_smoke(tmp_path):
    """bin/hetuprof over a synthetic telemetry dir + trace window."""
    tel = tmp_path / "tel"
    tel.mkdir()
    xla = tmp_path / "xla" / "plugins" / "profile" / "r1"
    xla.mkdir(parents=True)
    with gzip.open(xla / "h.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": _synthetic_events()}, f)
    recs = [
        {"kind": "xla_trace", "ts": 1.0, "rank": 0,
         "dir": str(tmp_path / "xla"), "start_step": 0, "n_steps": 2},
        {"kind": "step", "ts": 2.0, "rank": 0, "sub": "train", "step": 1,
         "step_ms": 2.0, "phases": {"prestep_ms": 0.5, "dispatch_ms": 1.0,
                                    "poststep_ms": 0.5}},
    ]
    (tel / "metrics-r0.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    env = {**os.environ, "PYTHONPATH": REPO}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuprof"), str(tel)],
        env=env, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "per-step breakdown" in r.stdout
    assert "<dot>" in r.stdout   # no HLO given: base-name buckets
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuprof"), str(tel),
         "--json"], env=env, capture_output=True, text=True)
    rep = json.loads(r.stdout)
    assert rep["attribution"]["steps"] == 2
