"""ONNX bridge round-trip tests (reference ``tests/onnx/test_nodes.py`` and
``{cnn,dnn}_hetu_onnx_tf.py``).

The reference checks exports against onnxruntime; that package isn't in this
image, so the check here is export -> parse bytes -> import -> run both graphs
through the Executor and compare outputs. The wire format itself is validated
structurally (standard ONNX protobuf via the vendored codec).
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.onnx import hetu2onnx, onnx2hetu, proto as P


def _run(outputs, feeds):
    ex = ht.Executor([n for n in outputs], ctx=ht.cpu(0))
    res = ex.run("default", feed_dict=feeds, convert_to_numpy_ret_vals=True)
    return [np.asarray(r) for r in res]


def _roundtrip(build, feed_values, tmp_path, rtol=1e-5, atol=1e-6):
    """build() -> (input_nodes, output_node). Compares original vs re-imported
    outputs on the same feed values."""
    inputs, output = build()
    path = str(tmp_path / "m.onnx")
    shapes = {n: v.shape for n, v in zip(inputs, feed_values)}
    hetu2onnx.export(None, inputs, [output], path, input_shapes=shapes)

    (orig,) = _run([output], dict(zip(inputs, feed_values)))

    in_map, outs = onnx2hetu.load(path)
    assert len(outs) == 1
    # feed by name (names preserved through export); inputs the graph never
    # consumes are rightly absent from the exported model
    feeds2 = {in_map[n.name]: v for n, v in zip(inputs, feed_values)
              if n.name in in_map}
    assert feeds2, "exported graph consumed none of the declared inputs"
    (imported,) = _run(outs, feeds2)
    np.testing.assert_allclose(orig, imported, rtol=rtol, atol=atol)


RNG = np.random.RandomState(0)


CASES = {
    "add": lambda x, y: ht.add_op(x, y),
    "mul": lambda x, y: ht.mul_op(x, y),
    "div": lambda x, y: ht.div_op(x, y),
    "addconst": lambda x, y: ht.addbyconst_op(x, 2.5),
    "mulconst": lambda x, y: ht.mul_byconst_op(x, -1.5),
    "relu": lambda x, y: ht.relu_op(x),
    "leakyrelu": lambda x, y: ht.leaky_relu_op(x, 0.1),
    "sigmoid": lambda x, y: ht.sigmoid_op(x),
    "tanh": lambda x, y: ht.tanh_op(x),
    "opposite": lambda x, y: ht.opposite_op(x),
    "softmax": lambda x, y: ht.softmax_op(x),
    "matmul": lambda x, y: ht.matmul_op(x, ht.transpose_op(y)),
    "matmul_trans": lambda x, y: ht.matmul_op(x, y, trans_B=True),
    "reshape": lambda x, y: ht.array_reshape_op(x, (-1, 2)),
    "transpose": lambda x, y: ht.transpose_op(x, (1, 0)),
    "concat": lambda x, y: ht.concat_op(x, y, axis=1),
    "slice": lambda x, y: ht.slice_op(x, (1, 0), (2, -1)),
    "reduce_sum": lambda x, y: ht.reduce_sum_op(x, [1]),
    "reduce_mean": lambda x, y: ht.reduce_mean_op(x, [0], keepdims=True),
    "broadcastto": lambda x, y: ht.broadcastto_op(
        ht.reduce_mean_op(x, [0], keepdims=True), x),
    "where": lambda x, y: ht.where_op(ht.relu_op(x), x, y),
    "pad": lambda x, y: ht.pad_op(x, [(1, 1), (0, 2)]),
    "sqrt": lambda x, y: ht.sqrt_op(ht.mul_op(x, x)),
    "broadcast_shape": lambda x, y: ht.broadcast_shape_op(
        x, (2, 4, 6), add_axes=(0,)),
    "broadcast_shape_neg_axis": lambda x, y: ht.broadcast_shape_op(
        x, (4, 6, 3), add_axes=(-1,)),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_node_roundtrip(case, tmp_path):
    xv = RNG.randn(4, 6).astype(np.float32)
    yv = RNG.randn(4, 6).astype(np.float32)

    def build():
        x = ht.Variable(name="x", trainable=False)
        y = ht.Variable(name="y", trainable=False)
        return [x, y], CASES[case](x, y)

    _roundtrip(build, [xv, yv], tmp_path)


def test_onehot_roundtrip(tmp_path):
    idx = RNG.randint(0, 5, (8,)).astype(np.float32)

    def build():
        x = ht.Variable(name="x", trainable=False)
        return [x], ht.one_hot_op(x, 5)

    _roundtrip(build, [idx], tmp_path)


def test_embedding_gather_roundtrip(tmp_path):
    idx = RNG.randint(0, 10, (4, 3)).astype(np.float32)

    def build():
        table = ht.Variable("table",
                            value=RNG.randn(10, 5).astype(np.float32))
        x = ht.Variable(name="x", trainable=False)
        return [x], ht.embedding_lookup_op(table, x)

    _roundtrip(build, [idx], tmp_path)


def test_mlp_roundtrip(tmp_path):
    """Trained-parameter MLP export: values come from the executor state
    (VERDICT done-criterion: round-trips an MLP and matches outputs)."""
    xv = RNG.randn(8, 12).astype(np.float32)

    x = ht.Variable(name="x", trainable=False)
    w1 = ht.Variable("w1", value=RNG.randn(12, 16).astype(np.float32) * 0.3)
    b1 = ht.Variable("b1", value=np.zeros(16, np.float32))
    w2 = ht.Variable("w2", value=RNG.randn(16, 4).astype(np.float32) * 0.3)
    h = ht.relu_op(ht.matmul_op(x, w1) + ht.broadcastto_op(b1, ht.matmul_op(x, w1)))
    out = ht.softmax_op(ht.matmul_op(h, w2))
    ex = ht.Executor([out], ctx=ht.cpu(0))
    (orig,) = ex.run("default", feed_dict={x: xv},
                     convert_to_numpy_ret_vals=True)

    path = str(tmp_path / "mlp.onnx")
    hetu2onnx.export(ex, [x], [out], path, input_shapes={x: xv.shape})

    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv})
    np.testing.assert_allclose(orig, imported, rtol=1e-5, atol=1e-6)


def test_lenet_roundtrip(tmp_path):
    """LeNet-shaped conv+pool+fc round-trip with state through the executor
    (VERDICT done-criterion: round-trips LeNet and matches outputs)."""
    xv = RNG.randn(4, 1, 28, 28).astype(np.float32)

    x = ht.Variable(name="x", trainable=False)
    c1 = ht.Variable("c1", value=(RNG.randn(6, 1, 5, 5) * 0.2).astype(np.float32))
    c2 = ht.Variable("c2", value=(RNG.randn(16, 6, 5, 5) * 0.2).astype(np.float32))
    w = ht.Variable("w", value=(RNG.randn(16 * 7 * 7, 10) * 0.1).astype(np.float32))
    h = ht.relu_op(ht.conv2d_op(x, c1, padding=2, stride=1))
    h = ht.max_pool2d_op(h, 2, 2, 0, 2)
    h = ht.relu_op(ht.conv2d_op(h, c2, padding=2, stride=1))
    h = ht.max_pool2d_op(h, 2, 2, 0, 2)
    h = ht.array_reshape_op(h, (-1, 16 * 7 * 7))
    out = ht.matmul_op(h, w)
    ex = ht.Executor([out], ctx=ht.cpu(0))
    (orig,) = ex.run("default", feed_dict={x: xv},
                     convert_to_numpy_ret_vals=True)

    path = str(tmp_path / "lenet.onnx")
    hetu2onnx.export(ex, [x], [out], path, input_shapes={x: xv.shape})
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv})
    np.testing.assert_allclose(orig, imported, rtol=1e-4, atol=1e-5)


def test_batchnorm_roundtrip(tmp_path):
    """BN exports inference-mode running stats; the imported graph's eval
    output matches the original executor's eval output."""
    xv = RNG.randn(8, 3, 6, 6).astype(np.float32)
    yv = np.eye(2, dtype=np.float32)[RNG.randint(0, 2, 8)]

    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    scale = ht.Variable("scale", value=np.ones(4, np.float32))
    bias = ht.Variable("bias", value=np.zeros(4, np.float32))
    cw = ht.Variable("cw", value=(RNG.randn(4, 3, 3, 3) * 0.2).astype(np.float32))
    fw = ht.Variable("fw", value=(RNG.randn(4 * 6 * 6, 2) * 0.2).astype(np.float32))
    h = ht.batch_normalization_op(ht.conv2d_op(x, cw, padding=1), scale, bias)
    flat = ht.array_reshape_op(ht.relu_op(h), (-1, 4 * 6 * 6))
    out = ht.matmul_op(flat, fw)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(out, y_), [0])
    train = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train], "eval": [out]}, ctx=ht.cpu(0),
                     seed=0)
    for _ in range(3):  # move the running stats off their init values
        ex.run("train", feed_dict={x: xv, y_: yv})
    (orig,) = ex.run("eval", feed_dict={x: xv}, convert_to_numpy_ret_vals=True)

    path = str(tmp_path / "bn.onnx")
    hetu2onnx.export(ex, [x], [out], path, input_shapes={x: xv.shape})
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv})
    np.testing.assert_allclose(orig, imported, rtol=1e-4, atol=1e-5)


def test_export_cuts_at_input_boundary(tmp_path):
    """Declaring a mid-graph node as an input must cut the upstream subgraph:
    no dead upstream nodes, no upstream feeds demanded as model inputs."""
    x = ht.Variable(name="x", trainable=False)
    w = ht.Variable("w", value=RNG.randn(6, 6).astype(np.float32) * 0.3)
    h = ht.relu_op(ht.matmul_op(x, w))
    out = ht.sigmoid_op(h)
    path = str(tmp_path / "cut.onnx")
    hetu2onnx.export(None, [h], [out], path, input_shapes={h: (4, 6)})
    m = P.load_model(path)
    assert [n.op_type for n in m.graph.node] == ["Sigmoid"]
    assert [vi.name for vi in m.graph.input] == [h.name]
    assert not m.graph.initializer  # w is upstream of the cut

    hv = RNG.randn(4, 6).astype(np.float32)
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map[h.name]: hv})
    np.testing.assert_allclose(imported, 1 / (1 + np.exp(-hv)), rtol=1e-5)


def test_onnx_file_is_wellformed(tmp_path):
    """The written file re-parses from raw bytes and declares standard
    model-level fields (ir_version, opset import, graph IO)."""
    x = ht.Variable(name="x", trainable=False)
    w = ht.Variable("w", value=RNG.randn(3, 2).astype(np.float32))
    out = ht.matmul_op(x, w)
    path = str(tmp_path / "wf.onnx")
    hetu2onnx.export(None, [x], [out], path, input_shapes={x: (4, 3)})
    m = P.load_model(path)
    assert m.ir_version == 8
    assert m.opset_import[0].version == hetu2onnx.OPSET_VERSION
    assert m.graph.input[0].name == "x"
    assert P.value_info_shape(m.graph.input[0]) == (4, 3)
    assert len(m.graph.initializer) == 1
    assert m.graph.node[-1].op_type == "MatMul"


def test_transformer_block_roundtrip(tmp_path):
    """A full graph-API attention + FFN block (the nlp example's
    multihead_attention/feed_forward) survives export -> import: BatchMatMul
    (batched numpy-matmul semantics, incl. trans_B), LayerNorm, Softmax,
    causal-mask broadcast, Dropout. Trained-parameter values come from the
    executor state, like the MLP/LeNet round trips."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "nlp"))
    import hetu_transformer as htf

    B, T, D, H = 2, 4, 8, 2
    xv = RNG.randn(B, T, D).astype(np.float32)
    maskv = np.triu(np.full((T, T), -1e9, np.float32), k=1)[None, None]

    x = ht.Variable(name="x", trainable=False)
    mask = ht.Variable(name="mask", trainable=False)
    h = htf.multihead_attention(x, B, T, D, H, mask, "blk", dropout_prob=0.0)
    h = h + x
    h = htf.layer_norm(h, D, "ln1")
    out = ht.add_op(htf.feed_forward(h, B, T, D, 16, "ffn",
                                     dropout_prob=0.0), h)
    ex = ht.Executor([out], ctx=ht.cpu(0))
    (orig,) = ex.run("default", feed_dict={x: xv, mask: maskv},
                     convert_to_numpy_ret_vals=True)

    path = str(tmp_path / "block.onnx")
    hetu2onnx.export(ex, [x, mask], [out], path,
                     input_shapes={x: xv.shape, mask: maskv.shape})
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv, in_map["mask"]: maskv})
    np.testing.assert_allclose(orig, imported, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", ["rnn", "lstm"])
def test_recurrent_roundtrip(name, tmp_path):
    """RNN and LSTM (statically unrolled over 28 time steps: per-step
    slice, fused gate matmuls, sigmoid/tanh, elementwise carries) survive
    export -> import — the reference's recurrent ONNX capability
    (/root/reference/tests/onnx/rnn_hetu_onnx_tf.py:1)."""
    from conftest import import_example_models
    model = getattr(import_example_models("cnn"), name)

    B = 4
    xv = RNG.randn(B, 28 * 28).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[RNG.randint(0, 10, B)]
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    loss, logits = model(x, y_, 10, dimhidden=24)
    ex = ht.Executor([logits], ctx=ht.cpu(0))
    (orig,) = ex.run("default", feed_dict={x: xv, y_: yv},
                     convert_to_numpy_ret_vals=True)

    path = str(tmp_path / f"{name}.onnx")
    hetu2onnx.export(ex, [x], [logits], path, input_shapes={x: xv.shape})
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv})
    np.testing.assert_allclose(orig, imported, rtol=1e-4, atol=1e-5)


def test_vit_roundtrip(tmp_path):
    """Full ViT forward (patch conv, [CLS] BroadcastShape concat, MHA
    blocks, LayerNorm, slice head) survives export -> import."""
    from conftest import import_example_models
    vit = import_example_models("cnn").vit

    B = 2
    xv = RNG.randn(B, 3, 32, 32).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[RNG.randint(0, 10, B)]
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    loss, probs = vit(x, y_, 10, batch=B, d=32, heads=2, layers=2, dff=48)
    ex = ht.Executor([probs], ctx=ht.cpu(0))
    (orig,) = ex.run("default", feed_dict={x: xv, y_: yv},
                     convert_to_numpy_ret_vals=True)

    path = str(tmp_path / "vit.onnx")
    hetu2onnx.export(ex, [x], [probs], path, input_shapes={x: xv.shape})
    in_map, outs = onnx2hetu.load(path)
    (imported,) = _run(outs, {in_map["x"]: xv})
    np.testing.assert_allclose(orig, imported, rtol=1e-4, atol=1e-5)
