"""hetu-elastic: live worker/PS membership changes (docs/FAULT_TOLERANCE.md
"Elastic membership").

Layers under test, cheapest first: the pure accounting math (v2 shard IO,
key-range repartition, exactly-once era partitions), the scheduler's
two-phase resize protocol over raw sockets, stale-epoch rejection at the
server, live key-range migration onto a joining server, and the end-to-end
scale-down / scale-up worlds with exact sample accounting (multi-process
PSClient workers; the Executor integration rides test_elastic_executor).
"""
import multiprocessing as mp
import os
import queue as pyqueue
import shutil
import tempfile
import threading
import time

import numpy as np
import pytest

from hetu_tpu import elastic

_PORT_BASE = int(os.environ.get("HETU_TEST_ELASTIC_PORT", "14300"))
_port_iter = iter(range(_PORT_BASE, _PORT_BASE + 10000, 11))


# ---------------------------------------------------------------------------
# pure accounting: v2 shard IO + key-range repartition
# ---------------------------------------------------------------------------

def _mk_sparse_shard(rows, width, otype, seed, row0=0):
    rng = np.random.RandomState(seed)
    nslots = elastic._SLOT_COUNTS[otype]
    return {"kind": 1, "rows": rows, "len": rows * width, "width": width,
            "otype": otype, "step": 7, "lrs": np.asarray([0.1, 0.9, 0.999,
                                                          1e-7], np.float32),
            "data": rng.randn(rows * width).astype(np.float32),
            "accum": (rng.randn(rows * width).astype(np.float32)
                      if nslots >= 1 else np.empty(0, np.float32)),
            "accum2": (rng.randn(rows * width).astype(np.float32)
                       if nslots >= 2 else np.empty(0, np.float32)),
            "versions": np.arange(row0, row0 + rows, dtype=np.int64)}


def test_v2_shard_roundtrip(tmp_path):
    sh = _mk_sparse_shard(10, 4, otype=4, seed=0)
    path = str(tmp_path / "param_3_shard0.bin")
    elastic.write_v2_shard(path, sh)
    back = elastic.read_v2_shard(path)
    for k in ("kind", "rows", "len", "width", "otype", "step"):
        assert back[k] == sh[k], k
    for k in ("lrs", "data", "accum", "accum2", "versions"):
        np.testing.assert_array_equal(back[k], sh[k])


def test_repartition_sparse_rows_move_with_slots():
    # 2 -> 3 shards of a 10-row Adam table: every row's data/m/v/version
    # must land on its new owner bit-for-bit
    width = 4
    a = _mk_sparse_shard(5, width, 4, seed=1, row0=0)
    b = _mk_sparse_shard(5, width, 4, seed=2, row0=5)
    out = elastic.repartition_key([a, b], 3)
    full = {k: np.concatenate([a[k], b[k]])
            for k in ("data", "accum", "accum2", "versions")}
    # worker.h row_range(10, s) with S=3: [0,3), [3,6), [6,10)
    bounds = [(0, 3), (3, 6), (6, 10)]
    assert [s["rows"] for s in out] == [3, 3, 4]
    for sh, (lo, hi) in zip(out, bounds):
        np.testing.assert_array_equal(sh["data"],
                                      full["data"][lo * width:hi * width])
        np.testing.assert_array_equal(sh["accum"],
                                      full["accum"][lo * width:hi * width])
        np.testing.assert_array_equal(sh["accum2"],
                                      full["accum2"][lo * width:hi * width])
        np.testing.assert_array_equal(sh["versions"], full["versions"][lo:hi])
        assert sh["step"] == 7


def test_repartition_dense_formula_matches_worker_partitioner():
    # dense 2 -> 3: new shard lengths must follow dense_range exactly
    total = 103
    full = np.arange(total, dtype=np.float32)
    shards = []
    for lo, hi in elastic._range_split(total, 2):
        shards.append({"kind": 0, "rows": 0, "len": hi - lo, "width": 1,
                       "otype": 0, "step": 0,
                       "lrs": np.asarray([0.1], np.float32),
                       "data": full[lo:hi],
                       "accum": np.empty(0, np.float32),
                       "accum2": np.empty(0, np.float32),
                       "versions": np.empty(0, np.int64)})
    out = elastic.repartition_key(shards, 3)
    for sh, (lo, hi) in zip(out, elastic._range_split(total, 3)):
        assert sh["len"] == hi - lo
        np.testing.assert_array_equal(sh["data"], full[lo:hi])


# ---------------------------------------------------------------------------
# exactly-once era accounting
# ---------------------------------------------------------------------------

def test_era_partitions_exactly_once_across_resizes():
    # world {0,1} from step 0; worker 1 leaves (progress 5) while worker 0
    # drains at step 7; later worker 2 joins (assigned start 11) while
    # worker 0 drains at step 9. Every sample is consumed at most once and
    # the final chunks cover exactly the unconsumed rest.
    n, bs = 960, 4
    eras = [
        {"version": 1, "n_workers": 2, "n_servers": 1,
         "members": [0, 1], "start_steps": [0, 0], "end_steps": [7, 5]},
        {"version": 2, "n_workers": 1, "n_servers": 1,
         "members": [0], "start_steps": [7], "end_steps": [9]},
        {"version": 3, "n_workers": 2, "n_servers": 2,
         "members": [0, 2], "start_steps": [9, 11], "end_steps": [-1, -1]},
    ]
    chunks, tail = elastic.era_partitions(n, bs, eras)
    assert len(chunks) == 2
    # consumed so far: era0 = 7 and 5 batches; era1 = 2 batches
    consumed = elastic.consumed_samples(
        n, bs, eras[:2] + [dict(eras[2])], {0: 9, 2: 11})
    everything = np.concatenate([consumed, *chunks, tail])
    assert everything.size == n
    assert np.unique(everything).size == n  # disjoint AND complete
    assert consumed.size == (7 + 5 + 2) * bs


def test_era_partitions_era0_matches_init_states_split():
    """The launch era's chunks must follow Dataloader.init_states'
    ``n // nrank`` split (that IS how era-0 data was sharded), not the
    batch-aligned bounds later eras use — with a non-divisible dataset the
    two formulas disagree and mixing them double-consumes the straddle."""
    from hetu_tpu.dataloader import Dataloader
    n, bs, m = 110, 10, 2
    eras = [
        {"version": 1, "members": [0, 1], "start_steps": [0, 0],
         "end_steps": [3, 2]},
        {"version": 2, "members": [0], "start_steps": [3],
         "end_steps": [-1]},
    ]
    chunks, tail = elastic.era_partitions(n, bs, eras)
    # what the two loaders ACTUALLY consumed in era 0 (init_states split)
    raw = np.arange(n, dtype=np.float32).reshape(n, 1)
    consumed = []
    for rank, steps in ((0, 3), (1, 2)):
        dl = Dataloader(raw, bs, name="t")
        dl.init_states(rank, m)
        consumed += [dl.get_arr().ravel().astype(np.int64)
                     for _ in range(steps)]
    everything = np.concatenate(consumed + chunks + [tail])
    assert everything.size == n
    assert np.unique(everything).size == n, \
        "era-0 accounting disagrees with init_states' actual split"


def test_era_partitions_epoch_wrap_falls_back():
    eras = [{"version": 1, "members": [0, 1], "start_steps": [0, 0],
             "end_steps": [100, 100]},       # 100 batches >> per-chunk
            {"version": 2, "members": [0], "start_steps": [100],
             "end_steps": [-1]}]
    assert elastic.era_partitions(64, 4, eras) is None


def test_dataloader_elastic_partition():
    from hetu_tpu.dataloader import Dataloader
    raw = np.arange(40, dtype=np.float32).reshape(40, 1)
    dl = Dataloader(raw, batch_size=2, name="train")
    dl.init_states(0, 2)
    for _ in range(3):
        dl.get_arr()
    idx = np.arange(25, 33)
    dl.load_elastic_partition(idx)
    assert dl.batch_num == 4
    got = np.concatenate([dl.get_arr().ravel() for _ in range(4)])
    np.testing.assert_array_equal(got, np.arange(25, 33, dtype=np.float32))
    # state_dict/load_state_dict keep working on the new partition
    sd = dl.state_dict()
    dl2 = Dataloader(raw, batch_size=2, name="train")
    dl2.load_elastic_partition(idx)
    dl2.load_state_dict(sd)
    np.testing.assert_array_equal(dl2.get_arr(), dl.get_arr())


# ---------------------------------------------------------------------------
# satellites: typed scheduler error, fault kinds, scale policy
# ---------------------------------------------------------------------------

def test_query_servers_scheduler_unreachable():
    from hetu_tpu.ps.supervisor import SchedulerUnreachable, query_servers
    port = next(_port_iter)  # nothing listens here
    with pytest.raises(SchedulerUnreachable) as ei:
        query_servers("127.0.0.1", port, timeout=0.3)
    assert f"127.0.0.1:{port}" in str(ei.value)
    # still an OSError so PSSupervisor._poll_once keeps polling through it
    assert isinstance(ei.value, OSError)


def test_fault_injector_elastic_kinds(monkeypatch):
    from hetu_tpu.resilience import FaultInjector
    fi = FaultInjector("worker_lost@5:1,ps_join@7")
    assert fi.entries[0]["kind"] == "worker_lost"
    assert fi.entries[0]["arg"] == 1.0
    assert fi.entries[1] == {"kind": "ps_join", "step": 7, "arg": None,
                             "fired": False}
    # gated exactly like every destructive kind
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    monkeypatch.setenv("HETU_FAULT_SPEC", "worker_lost@1")
    assert FaultInjector.from_env() is None
    # worker_lost with a NON-matching rank filter is consumed, not fired
    monkeypatch.setenv("WORKER_ID", "0")
    fi = FaultInjector("worker_lost@2:1")
    fi.inject_host(2)  # must not SIGKILL this process
    assert fi.entries[0]["fired"]


def test_scale_policy_recommends_growth():
    pol = elastic.ScalePolicy(max_servers=3, apply_ms_hi=1.0,
                              req_rate_hi=100.0, sustain=2, cooldown_s=0.0)
    mk = lambda req, ns, ap: [[0, 0, -1, 0, 1, req, ns, ap, -1, 0]]
    t = 100.0
    assert pol.observe(mk(0, 0, 0), now=t) is None          # no baseline
    # hot: 1000 reqs/s between polls
    assert pol.observe(mk(1000, 0, 0), now=t + 1) is None    # sustain 1/2
    d = pol.observe(mk(2000, 0, 0), now=t + 2)               # sustain 2/2
    assert d == {"action": "grow_server", "n_servers": 2}
    # at max_servers the policy stays quiet
    pol2 = elastic.ScalePolicy(max_servers=1, req_rate_hi=100.0, sustain=1,
                               cooldown_s=0.0)
    pol2.observe(mk(0, 0, 0), now=t)
    assert pol2.observe(mk(1000, 0, 0), now=t + 1) is None


# ---------------------------------------------------------------------------
# live-cluster helpers
# ---------------------------------------------------------------------------

def _env(role, idx, port, n_workers, n_servers):
    env = {"DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "DMLC_NUM_WORKER": str(n_workers),
           "DMLC_NUM_SERVER": str(n_servers),
           "DMLC_ROLE": role,
           "JAX_PLATFORMS": "cpu"}
    if role == "server":
        env.update({"SERVER_ID": str(idx), "DMLC_PS_SERVER_URI": "127.0.0.1",
                    "DMLC_PS_SERVER_PORT": "0"})
    elif role == "worker":
        env["WORKER_ID"] = str(idx)
    return env


class _Cluster:
    """scheduler + N light servers; workers are the caller's business."""

    def __init__(self, n_workers, n_servers):
        from hetu_tpu.ps.local_cluster import (spawn_light_role,
                                               spawn_light_server)
        self.port = next(_port_iter)
        self.n_workers, self.n_servers = n_workers, n_servers
        self.stopdir = tempfile.mkdtemp(prefix="hetu_el_stop_")
        self.stopfile = os.path.join(self.stopdir, "stop")
        self.infra = [spawn_light_role(
            "scheduler", _env("scheduler", 0, self.port, n_workers,
                              n_servers))]
        for s in range(n_servers):
            self.infra.append(spawn_light_server(
                s, _env("server", s, self.port, n_workers, n_servers),
                self.stopfile))

    def spawn_server(self, sid, n_servers_new):
        from hetu_tpu.ps.local_cluster import spawn_light_server
        p = spawn_light_server(
            sid, _env("server", sid, self.port, self.n_workers,
                      n_servers_new), self.stopfile)
        self.infra.append(p)
        return p

    def checkout_worker(self, rank):
        """Identity-tagged kShutdown for a raw-socket fake worker, so the
        scheduler's teardown wait completes instead of timing out."""
        try:
            with _connect_retry(self.port, deadline_s=2) as s:
                s.sendall(elastic._MSG_HDR.pack(3, 0, 0, 1, 0, -1, 0)
                          + elastic._arg_i32([1, rank]))
        except OSError:
            pass

    def close(self, worker_ranks=()):
        from hetu_tpu.ps.local_cluster import reap_light_procs
        for r in worker_ranks:
            self.checkout_worker(r)
        with open(self.stopfile, "w") as f:
            f.write("stop")
        reap_light_procs(self.infra, timeout=10)
        shutil.rmtree(self.stopdir, ignore_errors=True)


def _connect_retry(port, deadline_s=30.0):
    import socket
    deadline = time.time() + deadline_s
    while True:
        try:
            return socket.create_connection(("127.0.0.1", port), timeout=30)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)  # the light scheduler is still booting


def _register_fake_worker(port, rank, results):
    """kRegister over a raw socket (no native lib): makes the scheduler's
    initial assembly complete so the resize protocol can be driven from
    plain sockets."""
    with _connect_retry(port) as s:
        s.settimeout(30)
        meta = elastic._arg_i32([1, rank, 0])
        host = elastic._arg_str("127.0.0.1")
        s.sendall(elastic._MSG_HDR.pack(0, 0, 0, 2, 0, -1, 0) + meta + host)
        head = elastic._MSG_HDR.unpack(
            elastic._recv_exact(s, elastic._MSG_HDR.size))
        for _ in range(head[3]):
            _, _, n = elastic._ARG_HDR.unpack(
                elastic._recv_exact(s, elastic._ARG_HDR.size))
            elastic._recv_exact(s, n)
    results[rank] = True


def test_resize_protocol_two_phase():
    """Propose/drain/finish against a real scheduler + server, with fake
    raw-socket workers: capacity grows at propose, the drain barrier parks
    committers until finish, the committed world carries per-member step
    accounting, and the log records the era history."""
    cl = _Cluster(n_workers=2, n_servers=1)
    try:
        regs = {}
        ths = [threading.Thread(target=_register_fake_worker,
                                args=(cl.port, r, regs)) for r in (0, 1)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=30)
        assert regs == {0: True, 1: True}
        st = elastic.resize_state("127.0.0.1", cl.port)
        assert st["world_version"] == 1 and st["pending_version"] == 0
        assert st["members"] == [0, 1]

        ver = elastic.propose_resize("127.0.0.1", cl.port, 2, 2)
        assert ver == 2
        # idempotent re-propose; conflicting proposal is an error
        assert elastic.propose_resize("127.0.0.1", cl.port, 2, 2) == 2
        with pytest.raises(RuntimeError, match="pending"):
            elastic.propose_resize("127.0.0.1", cl.port, 3, 2)
        st = elastic.resize_state("127.0.0.1", cl.port)
        assert st["pending_version"] == 2 and st["drain_needed"] == 2
        assert not st["new_servers_ready"]
        cl.spawn_server(1, 2)
        deadline = time.time() + 30
        while not elastic.resize_state("127.0.0.1",
                                       cl.port)["new_servers_ready"]:
            assert time.time() < deadline, "joining server never registered"
            time.sleep(0.05)

        # two committers drain at DIFFERENT steps and park until finish
        worlds = {}

        def commit(rank, step):
            worlds[rank] = elastic.commit_resize("127.0.0.1", cl.port,
                                                 rank, step)
        t0 = threading.Thread(target=commit, args=(0, 7))
        t1 = threading.Thread(target=commit, args=(1, 5))
        t0.start()
        t1.start()
        deadline = time.time() + 30
        while elastic.resize_state("127.0.0.1", cl.port)["drain_count"] < 2:
            assert time.time() < deadline, "drain barrier never filled"
            time.sleep(0.05)
        assert t0.is_alive() and t1.is_alive()  # parked, not returned
        assert elastic.finish_resize("127.0.0.1", cl.port) == 2
        t0.join(timeout=30)
        t1.join(timeout=30)
        assert worlds[0]["world_version"] == 2
        assert worlds[0]["members"] == [0, 1]
        assert worlds[0]["n_servers"] == 2
        assert worlds[0]["dp_rank"] == 0 and worlds[1]["dp_rank"] == 1

        log = elastic.resize_log("127.0.0.1", cl.port)
        assert len(log) == 2
        assert log[0]["members"] == [0, 1]
        assert log[0]["start_steps"] == [0, 0]
        assert log[0]["end_steps"] == [7, 5]   # per-member drain steps
        assert log[1]["members"] == [0, 1]
        assert log[1]["start_steps"] == [7, 5]
        assert log[1]["end_steps"] == [-1, -1]  # era still open

        # a commit with NO pending resize returns immediately
        w = elastic.commit_resize("127.0.0.1", cl.port, 0, 9, timeout=10)
        assert w["world_version"] == 2
    finally:
        cl.close(worker_ranks=(0, 1))


def test_resize_abort_releases_workers():
    cl = _Cluster(n_workers=1, n_servers=1)
    try:
        regs = {}
        _register_fake_worker(cl.port, 0, regs)
        assert elastic.propose_resize("127.0.0.1", cl.port, 1, 2) == 2
        out = {}

        def commit():
            out["w"] = elastic.commit_resize("127.0.0.1", cl.port, 0, 3)
        t = threading.Thread(target=commit)
        t.start()
        deadline = time.time() + 30
        while elastic.resize_state("127.0.0.1", cl.port)["drain_count"] < 1:
            assert time.time() < deadline
            time.sleep(0.05)
        # coordinator gives up (e.g. the joining server never came): abort
        assert elastic.finish_resize("127.0.0.1", cl.port, abort=True) == 1
        t.join(timeout=30)
        assert out["w"]["world_version"] == 1   # world unchanged
        st = elastic.resize_state("127.0.0.1", cl.port)
        assert st["pending_version"] == 0 and st["n_servers"] == 1
    finally:
        cl.close(worker_ranks=(0,))


def test_snapshot_epochs_count_only_tagged_aborts():
    """Regression: the scheduler's snapshot_epochs counter advances ONLY
    on hetusave's snapshot-tagged abort (sent after its job manifest
    committed) — an identical-world resize aborted for any other reason
    (drain timeout, failed migration, a snapshot that died pre-commit)
    must never be miscounted as a completed coordinated epoch."""
    cl = _Cluster(n_workers=1, n_servers=1)
    try:
        regs = {}
        _register_fake_worker(cl.port, 0, regs)

        def park_then_abort(**abort_kw):
            out = {}

            def commit():
                out["w"] = elastic.commit_resize("127.0.0.1", cl.port, 0, 3)

            t = threading.Thread(target=commit)
            t.start()
            deadline = time.time() + 30
            while elastic.resize_state("127.0.0.1",
                                       cl.port)["drain_count"] < 1:
                assert time.time() < deadline
                time.sleep(0.05)
            elastic.finish_resize("127.0.0.1", cl.port, abort=True,
                                  **abort_kw)
            t.join(timeout=30)
            assert out["w"]["world_version"] == 1

        def epochs():
            return elastic.resize_state("127.0.0.1",
                                        cl.port)["snapshot_epochs"]

        assert epochs() == 0
        # identical-world propose aborted UNTAGGED (the failed-snapshot /
        # drain-timeout shape): not a completed epoch
        elastic.propose_resize("127.0.0.1", cl.port, 1, 1)
        park_then_abort()
        assert epochs() == 0
        # hetusave's post-commit tagged release: exactly one epoch
        elastic.propose_resize("127.0.0.1", cl.port, 1, 1)
        park_then_abort(snapshot=True)
        assert epochs() == 1
    finally:
        cl.close(worker_ranks=(0,))


# ---------------------------------------------------------------------------
# multi-process worker bodies (module level: spawn pickles by reference)
# ---------------------------------------------------------------------------

N_SAMPLES = 96
BATCH = 4
PLEN = 4


def _worker_env(rank, port, n_workers, n_servers):
    env = _env("worker", rank, port, n_workers, n_servers)
    env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2")
    return env


def _chunk_batches(chunk, start):
    """Sequential batches of a partition from local batch cursor `start`."""
    nb = chunk.size // BATCH
    for i in range(start, nb):
        yield chunk[i * BATCH:(i + 1) * BATCH]


def _survivor_body(rank, port, q):
    """Scale-down survivor: consumes 6 batches, waits for the resize, then
    consumes everything that remains. Pushes grad = ones(PLEN)*sum(batch)
    under server-side SGD(+=), so the final param value IS the sample-sum
    ledger."""
    os.environ.update(_worker_env(rank, port, 2, 1))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    try:
        client.SetWorldVersion(1)
        client.InitTensor(0, sparse=False, length=PLEN, width=1,
                          init_type="constant", init_a=0.0, opt_type="sgd",
                          lrs=(1.0,))
        client.BarrierWorker()   # both workers see the table before pushes
        samples = np.arange(1, N_SAMPLES + 1, dtype=np.float32)
        chunk = samples[:N_SAMPLES // 2] if rank == 0 \
            else samples[N_SAMPLES // 2:]
        step = 0
        for batch in _chunk_batches(chunk, 0):
            if step >= 6:
                break
            client.Push(0, np.full(PLEN, batch.sum(), np.float32))
            client.Wait(0)
            step += 1
        # wait for the proposed shrink, then drain-commit at OUR step
        deadline = time.time() + 60
        while True:
            st = elastic.resize_state("127.0.0.1", port)
            if st["pending_version"] > 1:
                break
            assert time.time() < deadline, "no resize ever proposed"
            time.sleep(0.05)
        world = elastic.commit_resize("127.0.0.1", port, rank, step)
        client.SetWorldVersion(world["world_version"])
        eras = elastic.resize_log("127.0.0.1", port)
        chunks, _tail = elastic.era_partitions(N_SAMPLES, BATCH, eras)
        mine = samples[chunks[world["dp_rank"]]]
        for batch in _chunk_batches(mine, 0):
            client.Push(0, np.full(PLEN, batch.sum(), np.float32))
            client.Wait(0)
            step += 1
        out = client.Pull(0, np.empty(PLEN, np.float32))
        client.Wait(0)
        q.put((rank, "ok", out.copy(), world["world_version"]))
    except Exception:  # noqa: BLE001
        import traceback
        q.put((rank, "fail", traceback.format_exc(), None))
    finally:
        client.close(raise_on_error=False)


def _departing_body(rank, port, q, progress_path):
    """Scale-down victim: pushes exactly 5 batches of its chunk, records
    its progress (the cursor/state_dict stand-in the launcher reads), and
    dies without checking out — a SIGKILL'd preempted host."""
    os.environ.update(_worker_env(rank, port, 2, 1))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    client.SetWorldVersion(1)
    client.InitTensor(0, sparse=False, length=PLEN, width=1,
                      init_type="constant", init_a=0.0, opt_type="sgd",
                      lrs=(1.0,))
    client.BarrierWorker()
    samples = np.arange(1, N_SAMPLES + 1, dtype=np.float32)
    chunk = samples[N_SAMPLES // 2:]
    for step, batch in enumerate(_chunk_batches(chunk, 0)):
        if step >= 5:
            break
        client.Push(0, np.full(PLEN, batch.sum(), np.float32))
        client.Wait(0)
    with open(progress_path, "w") as f:
        f.write("5")
    q.put((rank, "dying", None, None))
    q.close()
    q.join_thread()  # flush the feeder: os._exit would otherwise eat it
    os._exit(137)


def test_scale_down_exact_sample_accounting(tmp_path):
    """Lose a worker mid-run: the survivor re-partitions over the
    remaining samples and the final PS value equals the full-epoch sum —
    every sample consumed exactly once, none twice, none lost."""
    cl = _Cluster(n_workers=2, n_servers=1)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    progress = str(tmp_path / "progress_r1")
    procs = []
    try:
        procs.append(ctx.Process(target=_survivor_body,
                                 args=(0, cl.port, q)))
        procs.append(ctx.Process(target=_departing_body,
                                 args=(1, cl.port, q, progress)))
        for p in procs:
            p.start()
        # the victim reports, records progress 5, and dies
        rank, status, _, _ = q.get(timeout=120)
        assert (rank, status) == (1, "dying")
        procs[1].join(timeout=30)
        assert procs[1].exitcode == 137
        # the launcher-side shrink: dead rank's progress rides the proposal
        coord = elastic.ElasticCoordinator("127.0.0.1", cl.port,
                                           drain_timeout_s=60)
        with open(progress) as f:
            dead_step = int(f.read())
        report = coord.resize(1, 1, removed=[1], removed_steps=[dead_step])
        assert report["members"] == [0]
        rank, status, out, ver = q.get(timeout=120)
        assert status == "ok", out
        assert ver == 2
        # exact accounting: server-side SGD(+=) accumulated every sample
        # exactly once => sum(1..96) in every param element
        np.testing.assert_array_equal(
            out, np.full(PLEN, np.arange(1, N_SAMPLES + 1).sum(),
                         np.float32))
        procs[0].join(timeout=30)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        cl.close()


def _scaleup_first_body(rank, port, q):
    """Scale-up founding worker: trains an Adam dense param + Adam sparse
    table alone for 4 steps, drain-commits through the grow (1w/1s ->
    2w/2s), proves migration preserved values/counters bit-for-bit, then
    consumes its post-resize partition."""
    os.environ.update(_worker_env(rank, port, 1, 1))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    try:
        client.SetWorldVersion(1)
        client.InitTensor(0, sparse=False, length=PLEN, width=1,
                          init_type="normal", init_a=0.0, init_b=1.0,
                          seed=5, opt_type="adam", lrs=(0.1, 0.9, 0.999,
                                                        1e-7))
        client.InitTensor(1, sparse=True, length=24, width=3,
                          init_type="normal", init_a=0.0, init_b=1.0,
                          seed=6, opt_type="adam", lrs=(0.1, 0.9, 0.999,
                                                        1e-7))
        samples = np.arange(1, N_SAMPLES + 1, dtype=np.float32)
        consumed = []
        step = 0
        rng = np.random.RandomState(3)
        for batch in _chunk_batches(samples, 0):
            if step >= 4:
                break
            client.Push(0, np.full(PLEN, 0.01 * batch.sum(), np.float32))
            client.Wait(0)
            rows = rng.randint(0, 24, 6).astype(np.int64)
            client.SparsePush(1, rows, np.ones((6, 3), np.float32))
            client.Wait(1)
            consumed.append(batch)
            step += 1
        # values at the drain boundary (the migration must preserve these)
        dense_pre = client.Pull(0, np.empty(PLEN, np.float32))
        client.Wait(0)
        all_rows = np.arange(24, dtype=np.int64)
        sparse_pre = client.SparsePull(1, all_rows,
                                       np.empty((24, 3), np.float32))
        client.Wait(1)
        updates_pre = client.ServerStats(0)["updates"]

        deadline = time.time() + 90
        while elastic.resize_state("127.0.0.1", port)["pending_version"] <= 1:
            assert time.time() < deadline, "no grow ever proposed"
            time.sleep(0.05)
        world = elastic.commit_resize("127.0.0.1", port, rank, step)
        client.SetWorldVersion(world["world_version"])
        n = client.RefreshServers()
        assert n == 2, n
        assert world["n_servers"] == 2

        # bit-exact state across the key-range move (rows + Adam slots
        # migrated; only their SERVER changed)
        dense_post = client.Pull(0, np.empty(PLEN, np.float32))
        client.Wait(0)
        sparse_post = client.SparsePull(1, all_rows,
                                        np.empty((24, 3), np.float32))
        client.Wait(1)
        np.testing.assert_array_equal(dense_pre, dense_post)
        np.testing.assert_array_equal(sparse_pre, sparse_post)
        updates_post = (client.ServerStats(0)["updates"]
                        + client.ServerStats(1)["updates"])
        assert updates_post == updates_pre, (updates_pre, updates_post)

        # post-resize: consume MY partition of the remaining samples
        eras = elastic.resize_log("127.0.0.1", port)
        chunks, _ = elastic.era_partitions(N_SAMPLES, BATCH, eras)
        mine = samples[chunks[world["dp_rank"]]]
        for batch in _chunk_batches(mine, 0):
            consumed.append(batch)
            client.Push(0, np.full(PLEN, 0.01 * batch.sum(), np.float32))
            client.Wait(0)
        q.put((rank, "ok", np.concatenate(consumed), None))
    except Exception:  # noqa: BLE001
        import traceback
        q.put((rank, "fail", traceback.format_exc(), None))
    finally:
        client.close(raise_on_error=False)


def _scaleup_joiner_body(rank, port, q):
    """Late joiner: reconstructs the era history from the scheduler's log,
    takes its partition, trains it to exhaustion. InitTensor is idempotent
    server-side, so re-declaring the tensors is safe."""
    os.environ.update(_worker_env(rank, port, 2, 2))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    try:
        eras = elastic.resize_log("127.0.0.1", port)
        client.SetWorldVersion(eras[-1]["version"])
        client.InitTensor(0, sparse=False, length=PLEN, width=1,
                          init_type="normal", init_a=0.0, init_b=1.0,
                          seed=5, opt_type="adam", lrs=(0.1, 0.9, 0.999,
                                                        1e-7))
        samples = np.arange(1, N_SAMPLES + 1, dtype=np.float32)
        chunks, _ = elastic.era_partitions(N_SAMPLES, BATCH, eras)
        pos = eras[-1]["members"].index(rank)
        mine = samples[chunks[pos]]
        consumed = []
        for batch in _chunk_batches(mine, 0):
            consumed.append(batch)
            client.Push(0, np.full(PLEN, 0.01 * batch.sum(), np.float32))
            client.Wait(0)
        q.put((rank, "ok", np.concatenate(consumed) if consumed
               else np.empty(0, np.float32), None))
    except Exception:  # noqa: BLE001
        import traceback
        q.put((rank, "fail", traceback.format_exc(), None))
    finally:
        client.close(raise_on_error=False)


def test_scale_up_worker_and_server_join(tmp_path):
    """Gain a worker AND a PS server mid-run: key ranges migrate onto the
    joining server with bit-exact values and update counters, the joiner
    reconstructs its partition from the world log, and the union of both
    workers' consumed samples is exactly the whole epoch."""
    cl = _Cluster(n_workers=1, n_servers=1)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_scaleup_first_body, args=(0, cl.port, q))]
    try:
        procs[0].start()
        coord = elastic.ElasticCoordinator(
            "127.0.0.1", cl.port, workdir=str(tmp_path),
            drain_timeout_s=90)

        def spawn_server(sid):
            cl.spawn_server(sid, 2)

        def spawn_worker(r):
            p = ctx.Process(target=_scaleup_joiner_body, args=(r, cl.port, q))
            procs.append(p)
            p.start()

        # wait for the founder to make some progress (it drains when the
        # proposal lands — ordering is handled by the protocol, this sleep
        # only makes the test exercise a mid-run resize rather than an
        # immediate one)
        time.sleep(1.0)
        report = coord.resize(2, 2, spawn_server=spawn_server,
                              spawn_worker=spawn_worker)
        assert report["migration"] is not None
        assert report["migration"]["updates_before"] == \
            report["migration"]["updates_after"]
        assert report["joined_workers"] == [1]

        got = {}
        for _ in range(2):
            rank, status, consumed, _ = q.get(timeout=180)
            assert status == "ok", consumed
            got[rank] = consumed
        allc = np.concatenate([got[0], got[1]])
        # exactly once: union of both workers' samples is the whole epoch
        assert np.unique(allc).size == allc.size == N_SAMPLES
        for p in procs:
            p.join(timeout=30)
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        cl.close()


# ---------------------------------------------------------------------------
# stale-epoch rejection at the server
# ---------------------------------------------------------------------------

def _stale_epoch_body(rank, port, q):
    os.environ.update(_worker_env(rank, port, 1, 1))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from hetu_tpu.ps.client import PSClient
    client = PSClient.from_env()
    try:
        client.InitTensor(0, sparse=False, length=8, width=1,
                          init_type="constant", init_a=1.0)
        addrs, _ = elastic._query_book("127.0.0.1", port)
        # the server moves to world 5; this worker still stamps world 4
        elastic.server_set_world(addrs[0], 5)
        client.SetWorldVersion(4)
        try:
            client.Push(0, np.ones(8, np.float32))
            client.Wait(0)
            q.put((rank, "fail", "stale-epoch push was NOT rejected", None))
            return
        except RuntimeError as e:
            assert "stale world" in str(e), e
        # the rejected push left the param untouched
        client.SetWorldVersion(5)
        out = client.Pull(0, np.empty(8, np.float32))
        client.Wait(0)
        np.testing.assert_array_equal(out, np.ones(8, np.float32))
        # synced worker traffic flows again
        client.Push(0, np.ones(8, np.float32))
        client.Wait(0)
        # unversioned legacy traffic (world 0) is always accepted
        client.SetWorldVersion(0)
        client.Push(0, np.ones(8, np.float32))
        client.Wait(0)
        out = client.Pull(0, np.empty(8, np.float32))
        client.Wait(0)
        np.testing.assert_array_equal(out, np.full(8, 3.0, np.float32))
        q.put((rank, "ok", None, None))
    except Exception:  # noqa: BLE001
        import traceback
        q.put((rank, "fail", traceback.format_exc(), None))
    finally:
        client.close(raise_on_error=False)


def test_stale_epoch_request_rejected(tmp_path):
    cl = _Cluster(n_workers=1, n_servers=1)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_stale_epoch_body, args=(0, cl.port, q))
    try:
        p.start()
        rank, status, err, _ = q.get(timeout=120)
        assert status == "ok", err
        p.join(timeout=30)
    finally:
        if p.is_alive():
            p.terminate()
        cl.close()
