"""Executor semantics: gradients, optimizers, state, dataloaders, save/load.

Mirrors reference tests/test_transformer_ops.py's Executor+gradients pattern
with numpy as the oracle.
"""
import os

import numpy as np
import pytest

import hetu_tpu as ht


def test_gradients_linear():
    # loss = mean((x @ w - y)^2) -> dw = 2/N x^T (x @ w - y)
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 3).astype(np.float32)
    yv = rng.randn(8, 2).astype(np.float32)
    wv = rng.randn(3, 2).astype(np.float32)

    x = ht.Variable(name="x", trainable=False)
    y = ht.Variable(name="y", trainable=False)
    w = ht.Variable(name="w", value=wv)
    diff = ht.matmul_op(x, w) - y
    loss = ht.reduce_mean_op(ht.reduce_sum_op(diff * diff, [1]), [0])
    (gw,) = ht.gradients(loss, [w])

    ex = ht.Executor([loss, gw], ctx=ht.cpu(0))
    loss_val, gw_val = ex.run("default", feed_dict={x: xv, y: yv},
                              convert_to_numpy_ret_vals=True)
    resid = xv @ wv - yv
    np.testing.assert_allclose(loss_val, np.mean(np.sum(resid**2, 1)), rtol=1e-5)
    np.testing.assert_allclose(gw_val, 2.0 / 8 * xv.T @ resid, rtol=1e-4, atol=1e-5)


def test_sgd_training_step():
    rng = np.random.RandomState(1)
    xv = rng.randn(4, 3).astype(np.float32)
    yv = rng.randn(4, 1).astype(np.float32)
    wv = rng.randn(3, 1).astype(np.float32)

    x = ht.Variable(name="x", trainable=False)
    y = ht.Variable(name="y", trainable=False)
    w = ht.Variable(name="w", value=wv.copy())
    diff = ht.matmul_op(x, w) - y
    loss = ht.reduce_mean_op(ht.reduce_sum_op(diff * diff, [1]), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=0.1)
    train_op = opt.minimize(loss)

    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    ex.run("train", feed_dict={x: xv, y: yv})
    new_w = np.asarray(ex.state["params"][id(w)])
    expect = wv - 0.1 * (2.0 / 4 * xv.T @ (xv @ wv - yv))
    np.testing.assert_allclose(new_w, expect, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("opt_name", ["momentum", "nesterov", "adagrad", "adam"])
def test_optimizers_converge(opt_name):
    rng = np.random.RandomState(2)
    true_w = rng.randn(5, 1).astype(np.float32)
    xv = rng.randn(64, 5).astype(np.float32)
    yv = xv @ true_w

    x = ht.Variable(name="x", trainable=False)
    y = ht.Variable(name="y", trainable=False)
    w = ht.init.zeros((5, 1), name="w")
    diff = ht.matmul_op(x, w) - y
    loss = ht.reduce_mean_op(ht.reduce_sum_op(diff * diff, [1]), [0])
    opt = {
        "momentum": lambda: ht.optim.MomentumOptimizer(0.05),
        "nesterov": lambda: ht.optim.MomentumOptimizer(0.05, nesterov=True),
        "adagrad": lambda: ht.optim.AdaGradOptimizer(0.5),
        "adam": lambda: ht.optim.AdamOptimizer(0.1),
    }[opt_name]()
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    losses = []
    for _ in range(150):
        (lv, _) = ex.run("train", feed_dict={x: xv, y: yv},
                         convert_to_numpy_ret_vals=True)
        losses.append(float(lv))
    assert losses[-1] < 1e-2, f"{opt_name} failed to converge: {losses[-5:]}"


def test_lr_scheduler_traced():
    rng = np.random.RandomState(3)
    xv = rng.randn(4, 2).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    w = ht.init.ones((2, 1), name="w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    sched = ht.lr.StepScheduler(0.1, step_size=2, gamma=0.5)
    opt = ht.optim.SGDOptimizer(learning_rate=sched)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    w0 = np.asarray(ex.state["params"][id(w)])
    ex.run("train", feed_dict={x: xv})
    w1 = np.asarray(ex.state["params"][id(w)])
    # lr at step 0 must be 0.1
    g = np.mean(xv, 0).reshape(2, 1) / 1.0
    np.testing.assert_allclose(w0 - w1, 0.1 * g, rtol=1e-4, atol=1e-6)


def test_dataloader_and_epoch():
    n, bs = 20, 5
    data_x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    data_y = np.ones((n, 1), dtype=np.float32)
    x = ht.dataloader_op([ht.Dataloader(data_x, bs, "train")])
    y = ht.dataloader_op([ht.Dataloader(data_y, bs, "train")])
    w = ht.init.ones((2, 1), name="w")
    diff = ht.matmul_op(x, w) - y
    loss = ht.reduce_mean_op(ht.reduce_sum_op(diff * diff, [1]), [0])
    opt = ht.optim.SGDOptimizer(1e-4)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    assert ex.get_batch_num("train") == 4
    for _ in range(4):
        ex.run("train")
    assert ex.state["step"] == 4


def test_dropout_train_vs_eval():
    xv = np.ones((64, 64), dtype=np.float32)
    x = ht.Variable(name="x", trainable=False)
    w = ht.init.ones((64, 1), name="w")
    d = ht.dropout_op(x, 0.5)
    out = ht.matmul_op(d, w)
    loss = ht.reduce_mean_op(out, [0, 1])
    opt = ht.optim.SGDOptimizer(0.0)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [out, train_op], "eval": [out]}, ctx=ht.cpu(0))
    (train_out, _) = ex.run("train", feed_dict={x: xv})
    (eval_out,) = ex.run("eval", feed_dict={x: xv})
    # eval: dropout is identity
    np.testing.assert_allclose(eval_out.asnumpy(), np.full((64, 1), 64.0))
    # train: inverted dropout keeps expectation but not exact value
    assert abs(train_out.asnumpy().mean() - 64.0) > 1e-3
    assert 40.0 < train_out.asnumpy().mean() < 90.0


def test_batchnorm_state_updates():
    rng = np.random.RandomState(4)
    xv = (rng.randn(16, 3, 4, 4) * 3 + 5).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    scale = ht.init.ones((3,), name="bn_scale")
    bias = ht.init.zeros((3,), name="bn_bias")
    bn = ht.batch_normalization_op(x, scale, bias, momentum=0.5, eps=1e-5)
    loss = ht.reduce_mean_op(bn, [0, 1, 2, 3])
    opt = ht.optim.SGDOptimizer(0.0)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [bn, train_op], "eval": [bn]}, ctx=ht.cpu(0))
    (out, _) = ex.run("train", feed_dict={x: xv})
    # train output is batch-normalized: near-zero mean per channel
    o = out.asnumpy()
    np.testing.assert_allclose(o.mean((0, 2, 3)), np.zeros(3), atol=1e-4)
    state = ex.state["op_state"][id(bn)]
    np.testing.assert_allclose(np.asarray(state["mean"]),
                               0.5 * xv.mean((0, 2, 3)), rtol=1e-4)


def test_save_load(tmp_path):
    xv = np.random.RandomState(5).randn(4, 3).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    w = ht.init.random_normal((3, 2), stddev=1.0, name="w_saveload")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    opt = ht.optim.AdamOptimizer(0.01)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    ex.run("train", feed_dict={x: xv})
    ex.run("train", feed_dict={x: xv})
    w_after = np.asarray(ex.state["params"][id(w)])
    path = str(tmp_path / "ckpt")
    ex.save(path)
    assert os.path.exists(os.path.join(path, "w_saveload.npy"))

    # fresh executor, same graph
    ex2 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0))
    ex2.load(path)
    np.testing.assert_allclose(np.asarray(ex2.state["params"][id(w)]), w_after)
    assert ex2.state["step"] == 2


def test_variable_value_and_fetch():
    w = ht.Variable(name="wfetch", value=np.ones((2, 2), np.float32) * 3)
    loss = ht.reduce_mean_op(w, [0, 1])
    ex = ht.Executor([loss], ctx=ht.cpu(0))
    (val,) = ex.fetch_dense_parameter_value([w])
    np.testing.assert_allclose(val.asnumpy(), 3 * np.ones((2, 2)))


def test_bf16_compute_mode():
    """dtype=bfloat16: compute runs in bf16 (MXU-rate path), master params
    and optimizer updates stay f32, loss tracks the f32 run loosely."""
    import jax.numpy as jnp
    import numpy as np
    import hetu_tpu as ht

    rng = np.random.RandomState(0)
    xv = rng.randn(32, 16).astype(np.float32)
    yv = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 32)]
    wv = (rng.randn(16, 4) * 0.1).astype(np.float32)

    def build():
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y", trainable=False)
        w = ht.Variable("w", value=wv.copy())
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.5).minimize(loss)
        return x, y_, w, loss, train_op

    x, y_, w, loss, train_op = build()
    ex32 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=3)
    l32 = [float(np.mean(ex32.run("train", feed_dict={x: xv, y_: yv},
                                  convert_to_numpy_ret_vals=True)[0]))
           for _ in range(5)]

    x, y_, w, loss, train_op = build()
    ex16 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=3,
                       dtype=jnp.bfloat16)
    l16 = [float(np.mean(ex16.run("train", feed_dict={x: xv, y_: yv},
                                  convert_to_numpy_ret_vals=True)[0]))
           for _ in range(5)]
    # master params stay f32
    assert ex16.state["params"][id(w)].dtype == jnp.float32
    # bf16 training tracks f32 within bf16 tolerance and actually learns
    np.testing.assert_allclose(l32, l16, rtol=0.05, atol=0.02)
    assert l16[-1] < l16[0]


def test_profile_summary(monkeypatch):
    """HETU_PROFILE=1 produces a per-phase breakdown; off by default."""
    monkeypatch.setenv("HETU_PROFILE", "1")
    x = ht.Variable(name="x", trainable=False)
    w = ht.Variable("wprof", value=np.ones((3, 2), np.float32))
    out = ht.matmul_op(x, w)
    ex = ht.Executor([out], ctx=ht.cpu(0))
    for _ in range(3):
        ex.run("default", feed_dict={x: np.ones((4, 3), np.float32)})
    prof = ex.subexecutors["default"].profile_summary()
    assert prof["steps"] == 3
    for key in ("prestep_ms_per_step", "dispatch_ms_per_step",
                "poststep_ms_per_step", "trace_build_ms_per_step"):
        assert prof[key] >= 0.0

    monkeypatch.delenv("HETU_PROFILE")
    ex2 = ht.Executor([out], ctx=ht.cpu(0))
    ex2.run("default", feed_dict={x: np.ones((4, 3), np.float32)})
    assert ex2.subexecutors["default"].profile_summary() is None


def test_bf16_conv_bn_training():
    """Regression for the round-2 bench crash: conv under jax.grad in bf16
    compute mode (the conv transpose rule must see matching dtypes), with
    BatchNorm running stats staying f32. Exercises exactly the config
    bench.py runs (conv + BN + pool + matmul, Momentum)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    xv = rng.randn(8, 3, 8, 8).astype(np.float32)
    yv = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]

    def build():
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y", trainable=False)
        w1 = ht.Variable("w1", value=(rng.randn(8, 3, 3, 3) * 0.1).astype(np.float32))
        scale = ht.Variable("scale", value=np.ones(8, np.float32))
        bias = ht.Variable("bias", value=np.zeros(8, np.float32))
        w2 = ht.Variable("w2", value=(rng.randn(8 * 4 * 4, 4) * 0.1).astype(np.float32))
        h = ht.conv2d_op(x, w1, padding=1, stride=1)
        h = ht.batch_normalization_op(h, scale, bias)
        h = ht.relu_op(h)
        h = ht.max_pool2d_op(h, 2, 2, 0, 2)
        h = ht.array_reshape_op(h, [-1, 8 * 4 * 4])
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, w2), y_), [0])
        train_op = ht.optim.MomentumOptimizer(0.1).minimize(loss)
        return x, y_, scale, loss, train_op

    rng = np.random.RandomState(1)
    x, y_, scale, loss, train_op = build()
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=3,
                     dtype=jnp.bfloat16)
    losses = [float(np.mean(ex.run("train", feed_dict={x: xv, y_: yv},
                                   convert_to_numpy_ret_vals=True)[0]))
              for _ in range(8)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # master params and BN running stats stay f32
    assert ex.state["params"][id(scale)].dtype == jnp.float32
    for st in jax.tree.leaves(ex.state["op_state"]):
        if hasattr(st, "dtype") and jnp.issubdtype(st.dtype, jnp.floating):
            assert st.dtype == jnp.float32


def test_dump_hlo_exposes_the_compiled_step(tmp_path):
    """dump_hlo returns the (stable)HLO of the whole jitted step and writes
    it to disk; the optimized stage reflects XLA's pass pipeline."""
    import hetu_tpu as ht

    x = ht.Variable(name="x", trainable=False)
    w = ht.Variable("w", value=np.eye(4, dtype=np.float32))
    out = ht.relu_op(ht.matmul_op(x, w))
    ex = ht.Executor([out], ctx=ht.cpu(0))
    ex.run("default", feed_dict={x: np.ones((2, 4), np.float32)})

    sub = ex.subexecutors["default"]
    txt = sub.dump_hlo(str(tmp_path / "step.mlir"))
    assert txt and "dot" in txt  # the matmul is in the program
    assert (tmp_path / "step.mlir").read_text() == txt
    opt = sub.dump_hlo(stage="optimized")
    assert opt and opt != txt
