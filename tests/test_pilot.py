"""hetupilot — bounded self-tuning controller (docs/FAULT_TOLERANCE.md
"Self-tuning with guardrails").

The acceptance proofs live here: a seeded sustained-slow cluster run
where the watch's plan-divergence recommendation drives EXACTLY ONE
actuation era through the elastic two-phase barrier and commits on a
real measured improvement; a deliberately-regressing forced delta that
rolls back within K windows with the PS param AND its server optimizer
slots restored bit-for-bit, then blacklisted; a crash mid-actuation
whose next incarnation seals the open era as ``interrupted`` and keeps
training from the pre-actuation world; and a plan_flap anti-oscillation
run (5-seed soak in the slow tier) where the hysteretic governor keeps
the controller budget-bounded with exactly-once push accounting and a
final loss within tolerance of a never-actuated twin. The rest are the
satellites: governor refusal strings, ledger round-trip + torn-tail
tolerance, the FORCE/KILL test-mode gates, the jax-free CLI, and the
heturun run_summary fold.
"""
import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from test_ps import run_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_telemetry(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    yield str(tmp_path / "tel")
    telemetry.shutdown()


# ---------------------------------------------------------------------------
# governor: the hysteretic gate's exact refusal strings
# ---------------------------------------------------------------------------

def test_delta_signature_shapes():
    from hetu_tpu.pilot import delta_signature
    assert delta_signature({"kind": "comm_mode_flip", "target": "w1",
                            "arg": "AllReduce"}) \
        == "comm_mode_flip:w1:AllReduce"
    # None target/arg render as empty segments (the FORCE grammar inverse)
    assert delta_signature({"kind": "comm_quant", "target": None,
                            "arg": "int8"}) == "comm_quant::int8"


def test_governor_refusals_are_the_ledger_vocabulary():
    from hetu_tpu.pilot import Governor, delta_signature
    d = {"kind": "comm_quant", "target": None, "arg": "int8"}
    g = Governor(spacing=10, cooldown=100, budget=1)
    assert g.consider(d, 0) == "ok"
    assert g.consider(d, 0, n_workers=2) == "multi-worker"
    assert g.consider(d, 0, resize_pending=True) == "resize-pending"
    assert g.consider(d, 0, chaos_climbing=True) == "chaos-climbing"
    g.ban(delta_signature(d), 0)
    assert g.consider(d, 50) == "blacklisted"
    assert g.consider(d, 100) == "ok"        # cool-down expired
    g.note_actuation(100)
    assert g.consider(d, 105) == "budget-exhausted"   # budget=1 wins
    g2 = Governor(spacing=10, cooldown=0, budget=5)
    g2.note_actuation(100)
    assert g2.consider(d, 105) == "spacing"
    for r in ("budget-exhausted", "spacing", "blacklisted", "multi-worker",
              "resize-pending", "chaos-climbing"):
        assert r in Governor.REFUSALS


# ---------------------------------------------------------------------------
# ledger: crash-ordered persistence
# ---------------------------------------------------------------------------

def test_ledger_roundtrip_open_eras_and_torn_tail(tmp_path):
    from hetu_tpu.pilot import ActuationLedger
    d = {"kind": "comm_quant", "target": None, "arg": "int8"}
    led = ActuationLedger(str(tmp_path / "pilot.jsonl"))
    led.append(era=1, phase="propose", step=10, delta=d, baseline_ms=20.0)
    led.append(era=1, phase="actuate", step=10, delta=d)
    led.append(era=1, phase="verdict", verdict="rollback", step=18, delta=d,
               before_ms=20.0, after_ms=30.0, ratio=1.5)
    led.append(phase="abstain", signature="x", reason="spacing", step=19)
    led.append(era=2, phase="propose", step=40, delta=d, baseline_ms=21.0)
    led.append(era=2, phase="actuate", step=40, delta=d)
    with open(led.path, "a") as f:
        f.write('{"torn": tr')     # crash mid-write
    recs = led.records()
    assert len(recs) == 6          # torn tail tolerated
    assert led.last_era() == 2
    assert ActuationLedger.open_eras(recs) == [2]
    s = ActuationLedger.summarize(recs)
    assert (s["eras"], s["rollbacks"], s["open"], s["abstains"]) \
        == (2, 1, 1, 1)
    assert s["history"][0]["after_ms"] == 30.0
    assert s["history"][0]["baseline_ms"] == 20.0


def test_summarize_dir_absent_is_none(tmp_path):
    from hetu_tpu.pilot import summarize_dir
    assert summarize_dir(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# FORCE grammar + test-mode gates
# ---------------------------------------------------------------------------

def test_force_requires_test_mode(monkeypatch):
    from hetu_tpu.pilot import Pilot, PilotError
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    with pytest.raises(PilotError, match="HETU_TEST_MODE"):
        Pilot._parse_force("comm_quant::int8@5")


def test_force_grammar(monkeypatch):
    from hetu_tpu.pilot import Pilot, PilotError
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    delta, at = Pilot._parse_force("comm_mode_flip:w1:AllReduce@12")
    assert at == 12
    assert (delta["kind"], delta["target"], delta["arg"]) \
        == ("comm_mode_flip", "w1", "AllReduce")
    delta, at = Pilot._parse_force("comm_quant::int8@3")
    assert delta["target"] is None and delta["arg"] == "int8"
    with pytest.raises(PilotError, match="@step"):
        Pilot._parse_force("comm_quant::int8")     # no @step
    with pytest.raises(ValueError, match="comm_quant"):
        Pilot._parse_force("full_replan@5")        # unknown kind names
    assert Pilot._parse_force(None) is None        # the catalogue


# ---------------------------------------------------------------------------
# interrupted-era sealing + the allow gate (no cluster, stub executor)
# ---------------------------------------------------------------------------

def test_interrupted_era_sealed_on_construction(tmp_path):
    from hetu_tpu.pilot import ActuationLedger, Pilot
    d = {"kind": "comm_mode_flip", "target": "w1", "arg": "AllReduce"}
    led = ActuationLedger(str(tmp_path / "pilot.jsonl"))
    led.append(era=1, phase="propose", step=30, delta=d, baseline_ms=15.0)
    led.append(era=1, phase="actuate", step=30, delta=d)
    # crash: no verdict. The next incarnation's state came from config
    # (+ restore), i.e. the PRE-actuation era — sealing, not reverting
    pil = Pilot(SimpleNamespace(), directory=str(tmp_path))
    recs = pil.ledger.records()
    v = [r for r in recs if r.get("phase") == "verdict"]
    assert len(v) == 1 and v[0]["verdict"] == "interrupted"
    assert v[0]["era"] == 1
    assert pil.governor.spent == 1                 # counts the budget
    assert pil.governor.banned_until("comm_mode_flip:w1:AllReduce") \
        is not None
    # idempotent: a second incarnation must not double-seal
    pil2 = Pilot(SimpleNamespace(), directory=str(tmp_path))
    assert len([r for r in pil2.ledger.records()
                if r.get("phase") == "verdict"]) == 1


def test_allow_gate_refuses_unlisted_kinds(tmp_path):
    from hetu_tpu.pilot import Pilot
    pil = Pilot(SimpleNamespace(), directory=str(tmp_path),
                allow="comm_quant")
    pil.feed_recommendation(
        {"kind": "comm_mode_flip", "target": "w1", "arg": "AllReduce"},
        {"step": 7})
    assert pil._pending is None
    abst = [r for r in pil.ledger.records() if r.get("phase") == "abstain"]
    assert len(abst) == 1 and abst[0]["reason"] == "kind-not-allowed"
    # an allowed kind is kept pending for the next step boundary
    pil.feed_recommendation({"kind": "comm_quant", "target": None,
                             "arg": "int8"}, {"step": 8})
    assert pil._pending is not None
    # a second recommendation while one is pending is dropped
    pil.feed_recommendation({"kind": "comm_quant", "target": None,
                             "arg": "off"}, {"step": 9})
    assert pil._pending[0]["arg"] == "int8"


def test_from_env_resolution(tmp_path, monkeypatch):
    from hetu_tpu.pilot import Pilot
    monkeypatch.delenv("HETU_PILOT_DIR", raising=False)
    monkeypatch.delenv("HETU_PILOT_FORCE", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    monkeypatch.setenv("HETU_PILOT_K", "4")
    monkeypatch.setenv("HETU_PILOT_SPACING", "9")
    monkeypatch.setenv("HETU_PILOT_BUDGET", "2")
    monkeypatch.setenv("HETU_PILOT_ALLOW", "comm_quant, remesh")
    pil = Pilot.from_env(SimpleNamespace())
    assert pil.dir == os.path.join(str(tmp_path / "tel"), "pilot")
    assert pil.k == 4 and pil.governor.spacing == 9
    assert pil.governor.budget == 2
    assert pil.allow == ("comm_quant", "remesh")


# ---------------------------------------------------------------------------
# jax-free CLI + run_summary fold
# ---------------------------------------------------------------------------

def test_hetupilot_check_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetupilot"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "hetupilot self-test: PASS" in out.stdout, out.stdout


def _write_commit_ledger(directory):
    from hetu_tpu.pilot import ActuationLedger
    d = {"kind": "comm_mode_flip", "target": "w1", "arg": "AllReduce"}
    led = ActuationLedger(os.path.join(directory, "pilot.jsonl"))
    led.append(era=1, phase="propose", step=12, delta=d,
               cause={"leg": "ps_pull"}, baseline_ms=180.0)
    led.append(era=1, phase="actuate", step=12, delta=d)
    led.append(era=1, phase="verdict", verdict="commit", step=20, delta=d,
               before_ms=180.0, after_ms=6.0, ratio=0.0333)


def test_hetupilot_report_cli(tmp_path):
    _write_commit_ledger(str(tmp_path))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetupilot"),
         str(tmp_path)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "commits 1" in out.stdout, out.stdout
    assert "comm_mode_flip w1 -> AllReduce" in out.stdout, out.stdout
    outj = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetupilot"),
         str(tmp_path), "--json"], capture_output=True, text=True)
    rep = json.loads(outj.stdout)
    assert rep["commits"] == 1 and rep["eras"] == 1
    assert rep["history"][0]["after_ms"] == 6.0
    # no ledger -> usage error, not a crash
    empty = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetupilot"),
         str(tmp_path / "nowhere")], capture_output=True, text=True)
    assert empty.returncode == 2


def test_run_summary_records_pilot(tmp_path):
    from hetu_tpu import runner
    with open(tmp_path / "metrics-r0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_info", "rank": 0,
                            "comm_mode": "PS"}) + "\n")
        f.write(json.dumps({"kind": "step", "rank": 0, "step": 20,
                            "step_ms": 6.0}) + "\n")
    _write_commit_ledger(os.path.join(str(tmp_path), "pilot"))
    runner._tel_dir = str(tmp_path)
    try:
        runner._write_telemetry_summary(0, False, 1)
    finally:
        runner._tel_dir = None
    summary = json.load(open(tmp_path / "run_summary.json"))
    assert summary["pilot"]["commits"] == 1
    assert summary["pilot"]["history"][0]["delta"]["kind"] \
        == "comm_mode_flip"


# ---------------------------------------------------------------------------
# live cluster proofs — worker bodies (module level: spawn pickles by ref)
# ---------------------------------------------------------------------------

def _dense_ps_build(ht, tag, sub, plan=None, watch=0, slo=None,
                    opt=None):
    """One dense softmax job whose single fc weight lives on the PS
    (comm_mode='PS'): the flip target. Disjoint server tensor ids per
    executor (the bench_wdl_ps convention)."""
    os.environ["HETU_PS_ID_BASE"] = str(tag * 1000)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.xavier_uniform((8, 2), name=f"w{tag}")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    train_op = (opt or ht.optim.SGDOptimizer(0.1)).minimize(loss)
    ex = ht.Executor({sub: [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="PS", bsp=True, prefetch=False,
                     telemetry="metrics", seed=0, plan=plan, watch=watch,
                     slo=slo)
    return ex, x, y_


def _drive(ex, sub, x, y_, steps, rng):
    losses = []
    for _ in range(steps):
        bx = rng.randn(16, 8).astype(np.float32)
        by = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        out = ex.run(sub, feed_dict={x: bx, y_: by})
        losses.append(float(out[0].asnumpy()))
    return losses


def _calibrated_plan(ht, comm_quant, params, sub="calib"):
    """Measure the clean job's steady-state legs and wrap them in a Plan
    (the test_watch calibration shape) — what hetuplan WOULD have
    promised had it planned this exact job."""
    from hetu_tpu import telemetry
    from hetu_tpu.analysis.planner import ParamDecision, Plan
    from hetu_tpu.telemetry import trail
    ex0, x0, y0 = _dense_ps_build(ht, 0, sub)
    assert ex0.pilot is None     # HETU_PILOT set but the watch is unarmed
    _drive(ex0, sub, x0, y0, 8, np.random.RandomState(0))
    telemetry.get().flush()
    legs_seen = []
    with open(os.path.join(os.environ["HETU_TELEMETRY_DIR"],
                           "metrics-r0.jsonl")) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == "step" and r.get("sub") == sub \
                    and "compile_ms" not in (r.get("phases") or {}):
                legs_seen.append(trail.step_legs(r["phases"]))
    assert len(legs_seen) >= 5, len(legs_seen)
    mean = {leg: sum(l[leg] for l in legs_seen) / len(legs_seen)
            for leg in trail.LEGS}
    ex0.close()
    bd = {"compute_ms": mean["compute"], "allreduce_ms": 0.0,
          "ps_ms": mean["ps_pull"] + mean["ps_push"],
          "host_ms": mean["feed"] + mean["poststep"], "bubble_frac": 0.0}
    decisions = [ParamDecision(
        name=p["param"], size_elems=16, nbytes=64, dim=2,
        sparse=p["sparse"], density=1.0, touched_rows=0.0,
        mode=p["mode"], reason=p.get("reason", "")) for p in params]
    return Plan(devices=1, mesh={"dp": 1, "tp": 1, "pp": 1},
                comm_mode="PS", comm_quant=comm_quant, zero1=False,
                remat=False,
                predicted_step_ms=sum(v for k, v in bd.items()
                                      if k.endswith("_ms")),
                breakdown=bd, memory={}, params=decisions, candidates=[])


def _pilot_commit_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import ps as ps_pkg
    from hetu_tpu import telemetry
    from hetu_tpu.elastic import resize_state, sched_addr_from_env
    from hetu_tpu.pilot import ActuationLedger
    from hetu_tpu.resilience import FaultInjector, Supervisor

    # comm_quant "int8" in the plan so recommend() skips its first branch
    # and names the dense PS param — the comm_mode_flip delta under test
    plan = _calibrated_plan(ht, "int8",
                            [{"param": "w1", "mode": "PS", "sparse": False,
                              "reason": "dense fc"}])
    ex, x, y_ = _dense_ps_build(ht, 1, "train", plan=plan, watch=1)
    pil = ex.pilot
    assert pil is not None and ex.plan_watch is not None
    # a sustained slow half-period: plan_flap with a huge period re-arms
    # the one-shot server apply delay at EVERY boundary
    ex.attach_supervisor(Supervisor(
        fault_injector=FaultInjector("plan_flap@1:1000000")))
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(40):
        losses += _drive(ex, "train", x, y_, 1, rng)
        if ActuationLedger.summarize(
                pil.ledger.records())["commits"] >= 1:
            break
    s = ActuationLedger.summarize(pil.ledger.records())
    assert s["commits"] == 1, s
    assert s["eras"] == 1 and s["rollbacks"] == 0, s   # exactly one era
    h = s["history"][0]
    assert h["delta"]["kind"] == "comm_mode_flip", h
    assert h["delta"]["target"] == "w1", h
    # REAL measured improvement: the flip removed the slowed PS pushes
    assert h["ratio"] < 1.0 and h["after_ms"] < h["baseline_ms"], h
    assert h["baseline_ms"] >= 100.0, h     # the 150 ms flap dominated
    # the flip really happened: w1 is device-resident now
    assert all(q.node.name != "w1"
               for q in ex.ps_runtime.params.values())
    assert "w1" in [n.name for n in ex.param_nodes]
    # era attribution: the scheduler counted ONE pilot_commit epoch
    st = resize_state(*sched_addr_from_env())
    assert st["pilot_commit_epochs"] == 1, st
    assert st["pilot_rollback_epochs"] == 0, st
    # training stayed healthy through the actuation
    assert np.isfinite(losses).all()
    assert np.isfinite(_drive(ex, "train", x, y_, 2, rng)).all()
    # exactly-once accounting survived capture + flip + commit barrier
    ex.ps_runtime.drain()
    comm = ps_pkg.get_worker_communicate()
    cs = comm.ClientStats()
    applied = sum(
        int(comm.ServerStats(srv)["updates"])
        - max(int(comm.ServerStats(srv)["restored_updates"]), 0)
        for srv in range(1))
    assert int(cs["pushes_ok"]) == applied, (cs["pushes_ok"], applied)
    ex.close()
    telemetry.shutdown()


def test_pilot_live_commit_on_seeded_divergence(tmp_path, monkeypatch):
    """Acceptance: seeded sustained PS slowness -> the watch's
    recommendation -> EXACTLY ONE actuation era through the two-phase
    barrier -> measured after/before improvement -> commit, all in the
    ledger and the scheduler's era counters."""
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.setenv("HETU_WATCH_MIN_MS", "5")
    monkeypatch.setenv("HETU_PLAN_FLAP_MS", "150")
    monkeypatch.setenv("HETU_PILOT", "1")
    monkeypatch.setenv("HETU_PILOT_DIR", str(tmp_path / "pilot"))
    monkeypatch.setenv("HETU_PILOT_SPACING", "0")
    monkeypatch.setenv("HETU_PILOT_BASELINE", "3")
    monkeypatch.setenv("HETU_PILOT_K", "3")
    monkeypatch.setenv("HETU_PILOT_WARMUP", "1")
    monkeypatch.setenv("HETU_PILOT_BUDGET", "1")
    monkeypatch.delenv("HETU_PILOT_FORCE", raising=False)
    monkeypatch.delenv("HETU_PILOT_KILL", raising=False)
    run_cluster(_pilot_commit_worker, tmp_path, n_workers=1, n_servers=1)

    # the ledger tells the whole story, phase-ordered
    recs = [json.loads(l) for l in
            open(tmp_path / "pilot" / "pilot.jsonl")]
    phases = [r["phase"] for r in recs if r.get("era") == 1]
    assert phases == ["propose", "actuate", "verdict"], recs
    assert [r for r in recs if r.get("phase") == "verdict"][0]["verdict"] \
        == "commit"
    # the jax-free CLI renders it
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetupilot"),
         str(tmp_path / "pilot")], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "commits 1" in out.stdout and "rollbacks 0" in out.stdout
    # the gauges rode the final telemetry snapshot
    mrecs = [json.loads(l) for l in
             open(tmp_path / "metrics-r0.jsonl")]
    final = [r for r in mrecs if r.get("kind") == "final"][-1]["metrics"]
    assert final.get("hetu_pilot_actuations_total") == 1, final
    assert final.get("hetu_pilot_rollbacks_total", 0) == 0
    assert final.get("hetu_pilot_state") == 0.0     # idle after commit


def _pilot_rollback_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.elastic import resize_state, sched_addr_from_env

    # momentum makes the bit-identity claim sharp: the server-side
    # velocity shard evolves every step, so a sloppy restore cannot pass
    ex, x, y_ = _dense_ps_build(
        ht, 0, "train", watch=1, slo="step_ms<100000",
        opt=ht.optim.MomentumOptimizer(0.1, momentum=0.9))
    pil = ex.pilot
    assert pil is not None
    rng = np.random.RandomState(1)
    _drive(ex, "train", x, y_, 6, rng)       # steps 0..5; FORCE is @6
    p = next(q for q in ex.ps_runtime.params.values()
             if q.node.name == "w0")
    pre_w = np.array(ex.ps_runtime.pull_dense_value(p), copy=True)
    pre_slots = pil._pull_server_slots(p)
    assert pre_slots is not None and pre_slots["accum"].size == pre_w.size
    assert np.abs(pre_slots["accum"]).max() > 0   # nontrivial velocity
    _drive(ex, "train", x, y_, 1, rng)       # boundary 6 actuates the flip
    assert pil.state == "measuring" and pil._era is not None
    assert all(q.node.name != "w0"
               for q in ex.ps_runtime.params.values())
    _drive(ex, "train", x, y_, 2, rng)       # steps 7,8 -> K=2 windows
    # the verdict boundary with NO ensuing training step: what it
    # restores is exactly what we can observe
    pil.step_boundary(ex.subexecutors["train"], 9)
    assert pil.state == "idle", "verdict never fired"
    v = [r for r in pil.ledger.records() if r.get("phase") == "verdict"]
    assert len(v) == 1 and v[0]["verdict"] == "rollback", v
    assert v[0]["ratio"] > 0.0               # REGRESS_RATIO=0.0 forced it
    # bit-identical: the param is back on the server with its captured
    # bits, and so is the server-side optimizer slot
    p2 = next(q for q in ex.ps_runtime.params.values()
              if q.node.name == "w0")
    post_w = np.array(ex.ps_runtime.pull_dense_value(p2), copy=True)
    assert np.array_equal(pre_w, post_w), \
        float(np.abs(pre_w - post_w).max())
    post_slots = pil._pull_server_slots(p2)
    assert np.array_equal(pre_slots["accum"], post_slots["accum"]), \
        float(np.abs(pre_slots["accum"] - post_slots["accum"]).max())
    # blacklisted for the cool-down + attributed in the era counters
    assert pil.governor.banned_until("comm_mode_flip:w0:AllReduce") \
        is not None
    st = resize_state(*sched_addr_from_env())
    assert st["pilot_rollback_epochs"] == 1, st
    assert st["pilot_commit_epochs"] == 0, st
    # training continues from the restored world
    assert np.isfinite(_drive(ex, "train", x, y_, 2, rng)).all()
    ex.close()
    telemetry.shutdown()


def test_pilot_rollback_is_bit_identical_and_blacklisted(tmp_path,
                                                         monkeypatch):
    """Acceptance: a deliberately-regressing delta (REGRESS_RATIO=0.0
    makes ANY measured ratio a regression) rolls back within K windows
    — param and server optimizer slots restored bit-for-bit through the
    pilot_rollback-tagged barrier — and its signature is blacklisted."""
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.setenv("HETU_PILOT", "1")
    monkeypatch.setenv("HETU_PILOT_DIR", str(tmp_path / "pilot"))
    monkeypatch.setenv("HETU_PILOT_FORCE", "comm_mode_flip:w0:AllReduce@6")
    monkeypatch.setenv("HETU_PILOT_REGRESS_RATIO", "0.0")
    monkeypatch.setenv("HETU_PILOT_SPACING", "0")
    monkeypatch.setenv("HETU_PILOT_BASELINE", "2")
    monkeypatch.setenv("HETU_PILOT_K", "2")
    monkeypatch.setenv("HETU_PILOT_WARMUP", "0")
    monkeypatch.setenv("HETU_PILOT_BUDGET", "1")
    monkeypatch.setenv("HETU_PILOT_COOLDOWN", "10000")
    monkeypatch.delenv("HETU_PILOT_KILL", raising=False)
    run_cluster(_pilot_rollback_worker, tmp_path, n_workers=1, n_servers=1)
    mrecs = [json.loads(l) for l in open(tmp_path / "metrics-r0.jsonl")]
    final = [r for r in mrecs if r.get("kind") == "final"][-1]["metrics"]
    assert final.get("hetu_pilot_rollbacks_total") == 1, final


def _pilot_crash_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    ex, x, y_ = _dense_ps_build(ht, 0, "train", watch=1,
                                slo="step_ms<100000")
    assert ex.pilot is not None
    _drive(ex, "train", x, y_, 10, np.random.RandomState(2))
    raise AssertionError("unreachable: the armed kill never fired")


def _pilot_recover_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    ex, x, y_ = _dense_ps_build(ht, 0, "train", watch=1,
                                slo="step_ms<100000")
    pil = ex.pilot
    assert pil is not None
    # __init__ already sealed the crashed incarnation's open era: this
    # incarnation's state was rebuilt from config, i.e. the
    # PRE-actuation plan — a known era, nothing to revert
    v = [r for r in pil.ledger.records() if r.get("phase") == "verdict"]
    assert len(v) == 1, v
    assert v[0]["verdict"] == "interrupted" and v[0]["era"] == 1, v
    assert pil.governor.spent == 1           # the era consumed the budget
    assert pil.governor.banned_until("comm_quant::int8") is not None
    # training proceeds from the pre-actuation world
    assert np.isfinite(
        _drive(ex, "train", x, y_, 4, np.random.RandomState(3))).all()
    assert pil.state == "idle"
    ex.close()
    telemetry.shutdown()


def test_pilot_crash_mid_actuation_restores_to_known_era(tmp_path,
                                                         monkeypatch):
    """Acceptance: HETU_PILOT_KILL=actuate dies INSIDE the barrier (after
    capture, before the delta applied); the untagged abort never counts
    the era, the ledger holds an open era, and the next incarnation
    seals it ``interrupted``, spends its budget, blacklists the delta and
    keeps training."""
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.setenv("HETU_PILOT", "1")
    monkeypatch.setenv("HETU_PILOT_DIR", str(tmp_path / "pilot"))
    monkeypatch.setenv("HETU_PILOT_FORCE", "comm_quant::int8@4")
    monkeypatch.setenv("HETU_PILOT_KILL", "actuate")
    monkeypatch.setenv("HETU_PILOT_SPACING", "0")
    monkeypatch.setenv("HETU_PILOT_BASELINE", "2")
    with pytest.raises(RuntimeError, match="died without reporting"):
        run_cluster(_pilot_crash_worker, tmp_path, n_workers=1,
                    n_servers=1)
    from hetu_tpu.pilot import ActuationLedger
    led = ActuationLedger(str(tmp_path / "pilot" / "pilot.jsonl"))
    recs = led.records()
    assert ActuationLedger.open_eras(recs) == [1], recs
    assert not [r for r in recs if r.get("phase") == "verdict"]
    # incarnation 2: fresh cluster, same ledger, kill and force disarmed
    monkeypatch.delenv("HETU_PILOT_KILL")
    monkeypatch.delenv("HETU_PILOT_FORCE")
    run_cluster(_pilot_recover_worker, tmp_path, n_workers=1, n_servers=1)
    s = ActuationLedger.summarize(led.records())
    assert s["interrupted"] == 1 and s["open"] == 0, s


# ---------------------------------------------------------------------------
# anti-oscillation: plan_flap chaos must leave the controller bounded
# ---------------------------------------------------------------------------

def _pilot_flap_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import ps as ps_pkg
    from hetu_tpu import telemetry
    from hetu_tpu.pilot import ActuationLedger
    from hetu_tpu.resilience import FaultInjector, Supervisor

    seed = int(os.environ["HETU_PILOT_TEST_SEED"])
    # comm_quant "off" + a dense PS param: the first recommendation is
    # the cheap wire-level comm_quant delta — the flap's favourite bait
    plan = _calibrated_plan(ht, "off",
                            [{"param": "w1", "mode": "PS", "sparse": False,
                              "reason": "dense fc"}])
    flap = f"plan_flap@{1 + seed % 3}:4"
    ex, x, y_ = _dense_ps_build(ht, 1, "train", plan=plan, watch=1)
    pil = ex.pilot
    assert pil is not None
    ex.attach_supervisor(Supervisor(fault_injector=FaultInjector(flap)))
    rng = np.random.RandomState(seed)
    losses = _drive(ex, "train", x, y_, 36, rng)
    s = ActuationLedger.summarize(pil.ledger.records())
    # budget-bounded, and NO oscillation: under a flapping signal the
    # same delta must never actuate twice (cool-down > run length)
    assert s["eras"] <= 2, s
    sigs = [f'{r["delta"]["kind"]}:{r["delta"].get("target") or ""}'
            f':{r["delta"].get("arg") or ""}'
            for r in pil.ledger.records() if r.get("phase") == "actuate"]
    assert len(sigs) == len(set(sigs)), f"oscillated: {sigs}"
    ex.close()

    # the never-actuated twin: same data, same chaos, no controller
    ex2, x2, y2 = _dense_ps_build(ht, 2, "twin")
    assert ex2.pilot is None            # watch unarmed -> no controller
    ex2.attach_supervisor(Supervisor(fault_injector=FaultInjector(flap)))
    rng2 = np.random.RandomState(seed)
    twin = _drive(ex2, "twin", x2, y2, 36, rng2)
    assert np.isfinite(losses).all() and np.isfinite(twin).all()
    # a rollback forfeits at most its K measuring windows of training —
    # the final loss stays within tolerance of the twin's
    assert abs(np.mean(losses[-5:]) - np.mean(twin[-5:])) < 0.35, \
        (np.mean(losses[-5:]), np.mean(twin[-5:]))
    # exactly-once accounting across every actuation/rollback barrier
    ex2.ps_runtime.drain()
    comm = ps_pkg.get_worker_communicate()
    cs = comm.ClientStats()
    applied = sum(
        int(comm.ServerStats(srv)["updates"])
        - max(int(comm.ServerStats(srv)["restored_updates"]), 0)
        for srv in range(1))
    assert int(cs["pushes_ok"]) == applied, (cs["pushes_ok"], applied)
    ex2.close()
    telemetry.shutdown()


def _flap_env(monkeypatch, tmp_path, seed):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.delenv("HETU_PILOT_FORCE", raising=False)
    monkeypatch.delenv("HETU_PILOT_KILL", raising=False)
    monkeypatch.setenv("HETU_WATCH_MIN_MS", "5")
    monkeypatch.setenv("HETU_PLAN_FLAP_MS", "60")
    monkeypatch.setenv("HETU_PILOT", "1")
    monkeypatch.setenv("HETU_PILOT_DIR", str(tmp_path / "pilot"))
    monkeypatch.setenv("HETU_PILOT_SPACING", "2")
    monkeypatch.setenv("HETU_PILOT_BASELINE", "2")
    monkeypatch.setenv("HETU_PILOT_K", "2")
    monkeypatch.setenv("HETU_PILOT_WARMUP", "0")
    monkeypatch.setenv("HETU_PILOT_BUDGET", "2")
    monkeypatch.setenv("HETU_PILOT_COOLDOWN", "50")
    monkeypatch.setenv("HETU_PILOT_TEST_SEED", str(seed))


def test_pilot_flap_never_oscillates(tmp_path, monkeypatch):
    """plan_flap alternates slow/fast half-periods every 4 steps: the
    hysteretic governor must keep the controller budget-bounded with no
    repeat actuation of the same signature, exactly-once accounting and
    a final loss within tolerance of the never-actuated twin."""
    _flap_env(monkeypatch, tmp_path, seed=1)
    run_cluster(_pilot_flap_worker, tmp_path, n_workers=1, n_servers=1)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 3, 4, 5, 6])
def test_pilot_flap_soak_5seed(tmp_path, monkeypatch, seed):
    """The 5-seed acceptance soak: different data + flap phases, same
    zero-oscillation and exactly-once guarantees every time."""
    _flap_env(monkeypatch, tmp_path, seed=seed)
    run_cluster(_pilot_flap_worker, tmp_path, n_workers=1, n_servers=1)
