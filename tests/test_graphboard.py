"""Graphboard renders the ResNet train graph and serves it
(reference ``python/graphboard/graph2fig.py:11-31``)."""
import os
import urllib.request
import xml.etree.ElementTree as ET

import numpy as np

import hetu_tpu as ht
from hetu_tpu import graphboard

from test_models import _import_example_models


def _resnet_executor():
    models = _import_example_models("cnn")
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    loss, y = models.resnet18(x, y_, 10)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)


def test_graphboard_renders_resnet_train_graph(tmp_path):
    ex = _resnet_executor()
    out = graphboard.render(ex, name="train", out_dir=str(tmp_path / "gb"))
    svg_path = os.path.join(out, "output.svg")
    dot_path = os.path.join(out, "output.dot")
    assert os.path.exists(svg_path) and os.path.exists(dot_path)

    # valid XML, with one rect per topo node (+1 background)
    root = ET.parse(svg_path).getroot()
    ns = "{http://www.w3.org/2000/svg}"
    rects = root.iter(f"{ns}rect")
    topo = ex.subexecutors["train"].topo
    assert sum(1 for _ in rects) == len(topo) + 1
    svg_text = open(svg_path).read()
    assert "Conv2d" in svg_text and "Optimizer" in svg_text

    dot = open(dot_path).read()
    assert dot.startswith("digraph")
    n_edges = sum(len(n.inputs) for n in topo)
    assert dot.count(" -> ") == n_edges


def test_graphboard_serves_http(tmp_path):
    ex = _resnet_executor()
    url = graphboard.show(ex, port=19997, name="train",
                          out_dir=str(tmp_path / "gb"))
    try:
        page = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "graphboard" in page and "<svg" in page
        svg = urllib.request.urlopen(url + "output.svg", timeout=10).read()
        assert b"Conv2d" in svg
    finally:
        graphboard.close()
