"""HuggingFace BERT numerical parity (models/hf_bert.py).

The strongest possible "this really is BERT" evidence: instantiate a
random-weight ``transformers`` BERT (no network needed), import its weights,
and pin OUR forward to ITS forward logit-for-logit — encoder hidden states,
MLM prediction logits, NSP logits, pooled classifier logits, with and
without padding masks. Everything runs f32 on CPU with the unfused 'dot'
attention so the comparison is exact-arithmetic-shaped (tolerance covers
reduction-order noise only).

Beyond reference parity: the reference has no pretrained-checkpoint
interop (its nlp suite trains from scratch only — examples/nlp/).
"""
import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from hetu_tpu.models import bert as hbert
from hetu_tpu.models.hf_bert import (config_from_hf, export_to_hf,
                                     params_from_hf)


def small_hf_config(**over):
    kw = dict(vocab_size=211, hidden_size=64, num_hidden_layers=3,
              num_attention_heads=4, intermediate_size=128,
              max_position_embeddings=48, type_vocab_size=2,
              hidden_act="gelu", layer_norm_eps=1e-12)
    kw.update(over)
    return transformers.BertConfig(**kw)


def make_batch(rng, cfg_hf, B=3, T=16, ragged=False):
    ids = rng.integers(0, cfg_hf.vocab_size, size=(B, T)).astype(np.int64)
    seg = (rng.integers(0, cfg_hf.type_vocab_size, size=(B, T))
           .astype(np.int64))
    mask = np.ones((B, T), np.int64)
    if ragged:
        for b in range(B):
            n = rng.integers(T // 2, T + 1)
            mask[b, n:] = 0
    return ids, seg, mask


@pytest.fixture(scope="module")
def pretraining_pair():
    torch.manual_seed(0)
    model = transformers.BertForPreTraining(small_hf_config()).eval()
    params, cfg = params_from_hf(model)
    cfg = hbert.BertConfig.hf(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff, max_seq_len=cfg.max_seq_len,
        type_vocab_size=cfg.type_vocab_size, ln_eps=cfg.ln_eps,
        dtype=jnp.float32, attn_impl="dot", fused_mlm_ce=False, remat=False)
    return model, params, cfg


def test_encoder_hidden_states_match(pretraining_pair):
    model, params, cfg = pretraining_pair
    rng = np.random.default_rng(1)
    ids, seg, mask = make_batch(rng, model.config)
    with torch.no_grad():
        ref = model.bert(
            input_ids=torch.tensor(ids),
            token_type_ids=torch.tensor(seg),
            attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    h = hbert.encode(params, jnp.asarray(ids, jnp.int32),
                     jnp.asarray(seg, jnp.int32), cfg,
                     input_mask=jnp.asarray(mask, jnp.int32))
    np.testing.assert_allclose(np.asarray(h), ref, atol=2e-4, rtol=2e-4)


def test_mlm_and_nsp_logits_match(pretraining_pair):
    model, params, cfg = pretraining_pair
    rng = np.random.default_rng(2)
    ids, seg, mask = make_batch(rng, model.config)
    with torch.no_grad():
        out = model(input_ids=torch.tensor(ids),
                    token_type_ids=torch.tensor(seg),
                    attention_mask=torch.tensor(mask))
    h = hbert.encode(params, jnp.asarray(ids, jnp.int32),
                     jnp.asarray(seg, jnp.int32), cfg,
                     input_mask=jnp.asarray(mask, jnp.int32))
    T = ids.shape[1]
    all_pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32),
                               ids.shape)
    ours_mlm = np.asarray(hbert.mlm_logits(params, h, all_pos, cfg))
    np.testing.assert_allclose(ours_mlm, out.prediction_logits.numpy(),
                               atol=3e-4, rtol=3e-4)
    ours_nsp = np.asarray(hbert.nsp_logits(params, h))
    np.testing.assert_allclose(ours_nsp,
                               out.seq_relationship_logits.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_ragged_padding_masks_match(pretraining_pair):
    model, params, cfg = pretraining_pair
    rng = np.random.default_rng(3)
    ids, seg, mask = make_batch(rng, model.config, ragged=True)
    with torch.no_grad():
        ref = model.bert(
            input_ids=torch.tensor(ids),
            token_type_ids=torch.tensor(seg),
            attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    h = np.asarray(hbert.encode(
        params, jnp.asarray(ids, jnp.int32), jnp.asarray(seg, jnp.int32),
        cfg, input_mask=jnp.asarray(mask, jnp.int32)))
    # only real (unpadded) positions are contractually defined: HF lets
    # padded queries attend normally, and downstream consumers mask them
    real = mask.astype(bool)
    np.testing.assert_allclose(h[real], ref[real], atol=2e-4, rtol=2e-4)


def test_sequence_classifier_matches():
    torch.manual_seed(4)
    model = transformers.BertForSequenceClassification(
        small_hf_config(num_labels=5)).eval()
    params, cfg = params_from_hf(model)
    cfg = hbert.BertConfig.hf(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model, n_heads=cfg.n_heads,
        n_layers=cfg.n_layers, d_ff=cfg.d_ff, max_seq_len=cfg.max_seq_len,
        type_vocab_size=cfg.type_vocab_size, ln_eps=cfg.ln_eps,
        dtype=jnp.float32, attn_impl="dot", remat=False)
    rng = np.random.default_rng(5)
    ids, seg, mask = make_batch(rng, model.config)
    with torch.no_grad():
        ref = model(input_ids=torch.tensor(ids),
                    token_type_ids=torch.tensor(seg),
                    attention_mask=torch.tensor(mask)).logits.numpy()
    ours = np.asarray(hbert.classify_logits(
        params, jnp.asarray(ids, jnp.int32), jnp.asarray(seg, jnp.int32),
        cfg, input_mask=jnp.asarray(mask, jnp.int32)))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


def test_import_refuses_preln_config():
    torch.manual_seed(6)
    model = transformers.BertForPreTraining(small_hf_config()).eval()
    bad = hbert.BertConfig(vocab_size=211, d_model=64, n_heads=4,
                           n_layers=3, d_ff=128, max_seq_len=48)
    with pytest.raises(ValueError, match="post-LN"):
        params_from_hf(model, bad)


def test_import_refuses_relative_position_embeddings():
    # relative_key adds distance terms inside attention; a silent import
    # would drop them and diverge from the checkpoint
    torch.manual_seed(8)
    model = transformers.BertModel(small_hf_config(
        position_embedding_type="relative_key")).eval()
    with pytest.raises(NotImplementedError, match="position_embedding"):
        params_from_hf(model)


def test_import_refuses_truncated_config():
    # a cfg with fewer layers than the checkpoint must refuse, not
    # silently import a truncated model
    torch.manual_seed(6)
    model = transformers.BertForPreTraining(small_hf_config()).eval()
    truncated = hbert.BertConfig.hf(
        vocab_size=211, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=48, ln_eps=1e-12)
    with pytest.raises(ValueError, match="n_layers"):
        params_from_hf(model, truncated)


def test_train_then_export_roundtrip(pretraining_pair):
    """The deploy direction: train a pretrain step on imported weights,
    export the UPDATED params into a fresh torch BertForPreTraining, and
    the HF forward must match ours — TPU-trained weights deploy through
    transformers."""
    model, params, cfg = pretraining_pair
    rng = np.random.default_rng(9)
    B, T, P = 2, 16, 4
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "segment_ids": jnp.zeros((B, T), jnp.int32),
        "input_mask": jnp.ones((B, T), jnp.int32),
        "mlm_positions": jnp.asarray(rng.integers(1, T, (B, P)), jnp.int32),
        "mlm_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (B, P)), jnp.int32),
        "mlm_weights": jnp.ones((B, P), jnp.float32),
        "nsp_label": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32),
    }
    import jax
    step = hbert.make_pretrain_step(cfg, lr=1e-3)
    trained = jax.tree.map(jnp.array, params)
    _, _, trained, _ = step(trained, hbert.init_opt_state(trained), batch)

    fresh = transformers.BertForPreTraining(small_hf_config()).eval()
    export_to_hf(trained, cfg, fresh)
    ids, seg, mask = make_batch(np.random.default_rng(10), model.config)
    with torch.no_grad():
        out = fresh(input_ids=torch.tensor(ids),
                    token_type_ids=torch.tensor(seg),
                    attention_mask=torch.tensor(mask))
    h = hbert.encode(trained, jnp.asarray(ids, jnp.int32),
                     jnp.asarray(seg, jnp.int32), cfg,
                     input_mask=jnp.asarray(mask, jnp.int32))
    T = ids.shape[1]
    all_pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), ids.shape)
    np.testing.assert_allclose(
        np.asarray(hbert.mlm_logits(trained, h, all_pos, cfg)),
        out.prediction_logits.numpy(), atol=3e-4, rtol=3e-4)
    np.testing.assert_allclose(
        np.asarray(hbert.nsp_logits(trained, h)),
        out.seq_relationship_logits.numpy(), atol=2e-4, rtol=2e-4)


def test_export_refuses_layer_mismatch(pretraining_pair):
    # 3-layer params into a 2-layer target: raise, never truncate
    model, params, cfg = pretraining_pair
    small = transformers.BertForPreTraining(
        small_hf_config(num_hidden_layers=2)).eval()
    with pytest.raises(ValueError, match="no slot"):
        export_to_hf(params, cfg, small)


def test_export_drops_heads_into_plain_bertmodel(pretraining_pair):
    # deploying pretrain params as a bare encoder (BertModel) is
    # legitimate: heads are droppable, the trunk must still match
    model, params, cfg = pretraining_pair
    bare = transformers.BertModel(small_hf_config()).eval()
    export_to_hf(params, cfg, bare)
    rng = np.random.default_rng(11)
    ids, seg, mask = make_batch(rng, model.config)
    with torch.no_grad():
        ref = bare(input_ids=torch.tensor(ids),
                   token_type_ids=torch.tensor(seg),
                   attention_mask=torch.tensor(mask)).last_hidden_state
    h = hbert.encode(params, jnp.asarray(ids, jnp.int32),
                     jnp.asarray(seg, jnp.int32), cfg,
                     input_mask=jnp.asarray(mask, jnp.int32))
    np.testing.assert_allclose(np.asarray(h), ref.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_hf_arch_trains_a_step(pretraining_pair):
    """The imported architecture is trainable through the standard pretrain
    step (gradients flow through post-LN blocks, biases, embedding LN)."""
    model, params, cfg = pretraining_pair
    rng = np.random.default_rng(7)
    B, T, P = 2, 16, 4
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "segment_ids": jnp.zeros((B, T), jnp.int32),
        "input_mask": jnp.ones((B, T), jnp.int32),
        "mlm_positions": jnp.asarray(
            rng.integers(1, T, (B, P)), jnp.int32),
        "mlm_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (B, P)), jnp.int32),
        "mlm_weights": jnp.ones((B, P), jnp.float32),
        "nsp_label": jnp.asarray(rng.integers(0, 2, (B,)), jnp.int32),
    }
    import jax
    step = hbert.make_pretrain_step(cfg, lr=1e-3)
    # deep-copy: the step donates its params, and the module-scoped
    # fixture's buffers must survive for the other tests
    params2 = jax.tree.map(jnp.array, params)
    opt = hbert.init_opt_state(params2)
    loss1, _, params2, opt = step(params2, opt, batch)
    loss2, _, params2, opt = step(params2, opt, batch)
    assert float(loss2) < float(loss1)
