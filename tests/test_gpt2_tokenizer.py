"""GPT-2 byte-level BPE parity vs transformers.GPT2Tokenizer (the slow /
reference implementation), over a locally constructed vocabulary — no
network. Inputs stress the byte-level machinery: emoji (4-byte UTF-8),
CJK, control characters, contractions, digit runs, whitespace runs."""
import json
import os

import pytest

transformers = pytest.importorskip("transformers")

from hetu_tpu.tokenizers.gpt2_tokenizer import GPT2Tokenizer, bytes_to_unicode


@pytest.fixture(scope="module")
def vocab_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("bpe")
    b2u = bytes_to_unicode()
    vocab = {c: i for i, c in enumerate(sorted(b2u.values()))}
    merges = ["t h", "th e", "h e", "i n", "a n", "e r", "Ġ t", "Ġt h",
              "Ġth e", "Ġ a", "Ġa n", "an d", "Ġan d", "r e", "o u",
              "1 2", "12 3", "' s", "e e"]
    # an emoji merge: pair the first two UTF-8 byte proxies of 😀 so the
    # multi-byte path gets a real merge to apply
    emo = "".join(b2u[b] for b in "😀".encode("utf-8"))
    merges.append(f"{emo[0]} {emo[1]}")
    for m in merges:
        tok = m.replace(" ", "")
        if tok not in vocab:
            vocab[tok] = len(vocab)
    with open(d / "vocab.json", "w") as f:
        json.dump(vocab, f)
    with open(d / "merges.txt", "w") as f:
        f.write("#version: 0.2\n" + "\n".join(merges) + "\n")
    return str(d)


@pytest.fixture(scope="module")
def pair(vocab_dir):
    ours = GPT2Tokenizer(os.path.join(vocab_dir, "vocab.json"),
                         os.path.join(vocab_dir, "merges.txt"))
    ref = transformers.GPT2Tokenizer(os.path.join(vocab_dir, "vocab.json"),
                                     os.path.join(vocab_dir, "merges.txt"))
    return ours, ref

TEXTS = [
    "the thin man and the thinner man ran there",
    "The 123 quick 9 brown foxes' dens,  jumped!\n\nover\tthe lazy dog.",
    "it's the engineer's 123rd theorem",
    "emoji 😀 and 😀😀 stacked",
    "中文字符 mixed with the latin and ß ü ø",
    "   leading spaces and trailing   ",
    "a\x00b control\x07chars",
    "supercalifragilisticexpialidocious antidisestablishmentarianism",
    "",
    "'s't're've'm'll'd leading contractions",
]


@pytest.mark.parametrize("text", TEXTS)
def test_tokenize_matches_hf(pair, text):
    ours, ref = pair
    assert ours.tokenize(text) == ref.tokenize(text)


@pytest.mark.parametrize("text", TEXTS)
def test_encode_roundtrip(pair, text):
    ours, ref = pair
    ids = ours.encode(text)
    assert ids == ref.encode(text)   # GPT2Tokenizer.encode adds no specials
    assert ours.decode(ids) == text  # byte-level BPE is lossless


def test_special_token_parity(pair, vocab_dir):
    # <|endoftext|> must survive as ONE token with the same appended id
    # HF assigns (vocab_size), never split by BPE
    ours, ref = pair
    text = "the end<|endoftext|>the<|endoftext|>"
    assert ours.tokenize(text) == ref.tokenize(text)
    assert ours.encode(text) == ref.encode(text)
    assert ours.decode(ours.encode(text)) == text
    eot = ours.encode("<|endoftext|>")
    assert eot == ref.encode("<|endoftext|>") and len(eot) == 1


@pytest.mark.parametrize("bos,eos,unk", [("<b>", "<e>", "<u>"),
                                         ("<z>", "<m>", "<a>")])
def test_custom_special_tokens_attribute_order_ids(vocab_dir, bos, eos, unk):
    # HF appends specials in ATTRIBUTE order (bos, eos, unk, ...), NOT
    # alphabetically — the second parametrization is the ordering that
    # would expose a sorted-append bug (z before a)
    ours = GPT2Tokenizer(os.path.join(vocab_dir, "vocab.json"),
                         os.path.join(vocab_dir, "merges.txt"),
                         special_tokens=(bos, eos, unk))
    ref = transformers.GPT2Tokenizer(
        os.path.join(vocab_dir, "vocab.json"),
        os.path.join(vocab_dir, "merges.txt"),
        unk_token=unk, bos_token=bos, eos_token=eos)
    text = f"th{eos}the{bos}x{unk}"
    assert ours.tokenize(text) == ref.tokenize(text)
    assert ours.encode(text) == ref.encode(text)


@pytest.mark.parametrize("header,trailing", [(False, True), (True, False),
                                             (False, False)])
def test_merges_parsing_matches_hf(vocab_dir, header, trailing):
    # HF drops the first and last merges-file lines unconditionally; files
    # without a #version header or trailing newline must still match
    with open(os.path.join(vocab_dir, "merges.txt")) as f:
        lines = f.read().split("\n")   # header + merges + ""
    body = [ln for ln in lines[1:] if ln]
    content = ("#version: 0.2\n" if header else "") + "\n".join(body)
    content += "\n" if trailing else ""
    alt = os.path.join(vocab_dir, f"merges_{header}_{trailing}.txt")
    with open(alt, "w") as f:
        f.write(content)
    vjson = os.path.join(vocab_dir, "vocab.json")
    ours = GPT2Tokenizer(vjson, alt)
    ref = transformers.GPT2Tokenizer(vjson, alt)
    for text in TEXTS[:4]:
        assert ours.tokenize(text) == ref.tokenize(text)


def test_overlapping_specials_longest_match(vocab_dir):
    # a special that prefixes another must not tear the longer one apart
    ours = GPT2Tokenizer(os.path.join(vocab_dir, "vocab.json"),
                         os.path.join(vocab_dir, "merges.txt"),
                         special_tokens=("<|end|>", "<|endoftext|>"))
    toks = ours.tokenize("x<|endoftext|>y<|end|>")
    assert "<|endoftext|>" in toks and "<|end|>" in toks
    ids = ours.encode("x<|endoftext|>y<|end|>")
    assert ours.decode(ids) == "x<|endoftext|>y<|end|>"


def test_random_bytes_parity(pair):
    ours, ref = pair
    import random
    rng = random.Random(0)
    for _ in range(50):
        raw = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40)))
        text = raw.decode("utf-8", errors="ignore")
        assert ours.encode(text) == ref.encode(text)
        assert ours.decode(ours.encode(text)) == text
