"""Fused linear+softmax-CE kernel vs the materializing oracle: values and
all three gradients, including non-block-divisible N and V (padding/tail
masking) and bf16 inputs. Runs the Pallas kernels in interpret mode on the
CPU backend."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hetu_tpu.kernels.fused_ce import fused_linear_nll, linear_nll_reference


def _data(rng, n, v, d, dtype=jnp.float32):
    h = jnp.asarray(rng.randn(n, d), dtype) * 0.5
    w = jnp.asarray(rng.randn(v, d), dtype) * 0.3
    b = jnp.asarray(rng.randn(v), jnp.float32) * 0.1
    t = jnp.asarray(rng.randint(0, v, n), jnp.int32)
    return h, w, b, t


@pytest.mark.parametrize("n,v,d,bn,bv", [
    (64, 256, 32, 32, 64),     # clean tiles
    (50, 300, 16, 32, 128),    # both axes ragged (pad + tail mask)
    (16, 40, 8, 128, 512),     # blocks larger than the problem
])
def test_forward_matches_reference(n, v, d, bn, bv):
    h, w, b, t = _data(np.random.RandomState(0), n, v, d)
    out = fused_linear_nll(h, w, b, t, block_n=bn, block_v=bv)
    ref = linear_nll_reference(h, w, b, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bert_vocab_30522_forced_path():
    """The EXACT BERT-base vocab (30522 = 59*512 + 314: ragged against the
    default 512 vocab block) through the fused kernel — the bench's BERT
    cell must not discover a padding/tail-mask bug on its one hardware
    run. Small N/D keep interpret mode fast; the vocab axis is full."""
    h, w, b, t = _data(np.random.RandomState(1), 8, 30522, 16)
    out = fused_linear_nll(h, w, b, t, block_n=8, block_v=512)
    ref = linear_nll_reference(h, w, b, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gradients_match_reference():
    h, w, b, t = _data(np.random.RandomState(1), 48, 200, 24)
    ct = jnp.asarray(np.random.RandomState(2).rand(48), jnp.float32)

    def loss_fused(h, w, b):
        return jnp.vdot(fused_linear_nll(h, w, b, t, block_n=16,
                                         block_v=64), ct)

    def loss_ref(h, w, b):
        return jnp.vdot(linear_nll_reference(h, w, b, t), ct)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(h, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(h, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)


def test_bf16_inputs():
    h, w, b, t = _data(np.random.RandomState(3), 32, 128, 16, jnp.bfloat16)
    out = fused_linear_nll(h, w, b, t, block_n=16, block_v=64)
    ref = linear_nll_reference(h, w, b, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    # grads keep the input dtypes
    g = jax.grad(lambda h, w, b: jnp.sum(
        fused_linear_nll(h, w, b, t, block_n=16, block_v=64)),
        argnums=(0, 1, 2))(h, w, b)
    assert g[0].dtype == jnp.bfloat16 and g[1].dtype == jnp.bfloat16


def test_weighted_mean_composes():
    """The MLM-style weighted mean (callers weight and normalize the
    per-row nll) differentiates through the kernel correctly."""
    h, w, b, t = _data(np.random.RandomState(4), 40, 96, 16)
    wt = jnp.asarray((np.random.RandomState(5).rand(40) > 0.3), jnp.float32)

    def mlm_loss(fn):
        def f(h, w, b):
            per = fn(h, w, b, t) if fn is linear_nll_reference else \
                fn(h, w, b, t, 16, 32)
            return jnp.sum(per * wt) / jnp.maximum(jnp.sum(wt), 1.0)
        return f

    lf = mlm_loss(fused_linear_nll)(h, w, b)
    lr = mlm_loss(linear_nll_reference)(h, w, b)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    gf = jax.grad(mlm_loss(fused_linear_nll), argnums=(0, 1))(h, w, b)
    gr = jax.grad(mlm_loss(linear_nll_reference), argnums=(0, 1))(h, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)
