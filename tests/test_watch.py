"""hetuwatch — runtime plan-divergence sentinel, live residual streaming,
SLO watch (docs/OBSERVABILITY.md pillar 6).

The two acceptance proofs live here: a seeded ``ps_slow`` cluster run
where the sentinel names ps_pull + the slowed server within K detection
windows while a calibrated clean twin reports ZERO divergence events,
and a 3-seed hetuchaos soak (drop/delay/partition) whose measured step
legs, replayed through a clean-calibrated detector, produce zero
oscillation (the latch fires at most once and never churns). The rest
are the satellites: arming grammar, SLO grammar + build-time validation,
latch hysteresis, elastic world-version abstain, off-mode zero watch
work, the plan stamp + watch stream + gauges on an armed run, the
jax-free CLI, calibration ingestion of live watch rows, the hetuprof
gate's telemetry-dir source, and run_summary plan enrichment.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from test_ps import run_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_telemetry(tmp_path, monkeypatch):
    from hetu_tpu import telemetry
    telemetry.shutdown()
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path / "tel"))
    yield str(tmp_path / "tel")
    telemetry.shutdown()


def _phases(pull_ms=3.0, push_ms=3.0, dispatch_ms=12.0, jig=1.0):
    """Executor-shaped phase dict: 1 ms feed + pull in prestep, 1 ms
    poststep + push — step_legs decomposes it back."""
    return {"prestep_ms": (1.0 + pull_ms) * jig,
            "dispatch_ms": dispatch_ms * jig,
            "poststep_ms": (1.0 + push_ms) * jig,
            "ps_pull_ms": pull_ms * jig, "ps_push_ms": push_ms * jig}


_PRED = {"feed": 1.0, "ps_pull": 3.0, "compute": 12.0, "ps_push": 3.0,
         "poststep": 1.0}


# ---------------------------------------------------------------------------
# arming + SLO grammar
# ---------------------------------------------------------------------------

def test_resolve_watch_grammar(monkeypatch):
    from hetu_tpu.telemetry.watch import DEFAULT_CADENCE, resolve_watch
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_WATCH_EVERY", raising=False)
    assert resolve_watch(None) == 0          # env unset -> off
    for off in (0, "0", "off", "false", "", "none", False):
        assert resolve_watch(off) == 0
    assert resolve_watch(True) == DEFAULT_CADENCE
    assert resolve_watch("on") == DEFAULT_CADENCE
    assert resolve_watch(7) == 7 and resolve_watch("7") == 7
    monkeypatch.setenv("HETU_WATCH", "1")
    monkeypatch.setenv("HETU_WATCH_EVERY", "25")
    assert resolve_watch(None) == 25
    with pytest.raises(ValueError):
        resolve_watch(-3)


def test_slo_spec_grammar():
    from hetu_tpu.telemetry.watch import parse_slo_spec
    rules = parse_slo_spec("step_ms<25, ps_pull_frac<0.3,compute_ms<=40")
    assert [(r["metric"], r["op"], r["limit"]) for r in rules] == [
        ("step_ms", "<", 25.0), ("ps_pull_frac", "<", 0.3),
        ("compute_ms", "<=", 40.0)]
    assert parse_slo_spec("") == [] and parse_slo_spec(None) == []
    for bad in ("nope<1", "step_ms~25", "step_ms<abc", "step_ms"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


def test_slo_validated_at_build(fresh_telemetry):
    import hetu_tpu as ht
    from hetu_tpu.graph.executor import HetuConfig
    x = ht.Variable(name="x", trainable=False)
    with pytest.raises(ValueError):
        HetuConfig(eval_node_list=[x], slo="bogus_metric<1")


# ---------------------------------------------------------------------------
# latch: fire once, stay silent, re-arm only after K clean
# ---------------------------------------------------------------------------

def test_latch_fire_once_and_rearm():
    from hetu_tpu.telemetry.watch import _Latch
    lt = _Latch(k=3)
    assert [lt.observe("breach") for _ in range(3)] == [None, None, "fired"]
    # latched: a persisting breach NEVER re-fires
    assert all(lt.observe("breach") is None for _ in range(10))
    # dead-zone observations reset the clean streak without firing
    assert lt.observe("clean") is None and lt.observe("dead") is None
    assert [lt.observe("clean") for _ in range(3)] == [None, None,
                                                      "recovered"]
    # re-armed: a fresh sustained breach fires again
    assert [lt.observe("breach") for _ in range(3)] == [None, None, "fired"]


def test_divergence_fires_within_k_naming_leg():
    from hetu_tpu.telemetry.watch import PlanWatch
    pw = PlanWatch(predicted=dict(_PRED), predicted_step_ms=20.0, k=3)
    evs = []
    for s in range(20):
        _, e = pw.observe(s, _phases(jig=1.05 if s % 2 else 0.95))
        evs += e
    assert evs == [], f"clean stream fired: {evs}"
    for s in range(20, 40):
        _, e = pw.observe(s, _phases(pull_ms=12.0))
        evs += e
    fired = [e for e in evs if e["name"] == "plan_divergence"]
    assert len(fired) == 1, evs
    assert fired[0]["leg"] == "ps_pull"
    assert fired[0]["step"] <= 20 + 3, fired[0]   # within K observations
    # persisting divergence stays latched — ONE event total
    assert [e["name"] for e in evs].count("plan_divergence") == 1


def test_flapping_never_oscillates():
    from hetu_tpu.telemetry.watch import PlanWatch
    pw = PlanWatch(predicted=dict(_PRED), k=3, window=1)
    evs = []
    for s in range(80):
        _, e = pw.observe(s, _phases(pull_ms=12.0 if s % 2 else 3.0))
        evs += e
    assert evs == [], f"flapping oscillated the detector: {evs}"


def test_slo_breach_latches_and_recovers():
    from hetu_tpu.telemetry.watch import PlanWatch
    pw = PlanWatch(slo="step_ms<18,ps_pull_frac<0.9", k=3)
    evs = []
    for s in range(10):                      # 20 ms steps, 18 ms budget
        _, e = pw.observe(s, _phases())
        evs += e
    assert [e["name"] for e in evs] == ["slo_breach"], evs
    assert evs[0]["slo"] == "step_ms<18" and evs[0]["value"] == 20.0
    for s in range(10, 20):                  # back under budget
        _, e = pw.observe(s, _phases(dispatch_ms=8.0))
        evs += e
    assert [e["name"] for e in evs] == ["slo_breach", "slo_recovered"], evs


# ---------------------------------------------------------------------------
# elastic abstain: a world-version flip resets the residual window
# ---------------------------------------------------------------------------

def test_world_version_flip_resets_window():
    from hetu_tpu.telemetry.watch import PlanWatch
    pw = PlanWatch(predicted=dict(_PRED), k=3)
    evs = []
    for s in range(2):                        # 2 of the 3 needed breaches
        _, e = pw.observe(s, _phases(pull_ms=12.0))
        evs += e
    row, e = pw.observe(2, _phases(pull_ms=12.0), world_version=1)
    # the straddling step is dropped entirely: abstain row, no residuals
    assert row.get("abstain") == "world_version" and "residual" not in row
    assert [x["name"] for x in e] == ["watch_abstain"]
    # stale-era streak is gone: 2 more breaches in the new world stay quiet
    for s in range(3, 5):
        _, e = pw.observe(s, _phases(pull_ms=12.0), world_version=1)
        evs += e
    assert evs == [], f"stale-era legs crossed the resize: {evs}"
    # ...and the new world fires after its OWN K windows
    _, e = pw.observe(5, _phases(pull_ms=12.0), world_version=1)
    assert any(x["name"] == "plan_divergence" for x in e), e
    assert pw.abstains == 1


# ---------------------------------------------------------------------------
# off-mode: zero watch work (the telemetry/scope precedent)
# ---------------------------------------------------------------------------

def _tiny_mlp(ht):
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((8, 2), stddev=0.1, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    opt = ht.optim.SGDOptimizer(0.1)
    return x, y_, loss, opt.minimize(loss)


def _feeds(rng, bs=16):
    return (rng.randn(bs, 8).astype(np.float32),
            np.eye(2, dtype=np.float32)[rng.randint(0, 2, bs)])


def test_off_mode_zero_watch_calls(fresh_telemetry, monkeypatch):
    import hetu_tpu as ht
    from hetu_tpu.telemetry import watch as watch_mod
    calls = []
    monkeypatch.setattr(watch_mod.PlanWatch, "observe",
                        lambda self, *a, **k: calls.append("observe"))
    monkeypatch.setattr(watch_mod, "export_watch",
                        lambda *a, **k: calls.append("export"))
    x, y_, loss, train_op = _tiny_mlp(ht)
    # telemetry ON, plan adopted, watch left at its default (off): the
    # sentinel must cost exactly one attribute check per step
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     telemetry="metrics", plan="auto")
    assert ex.config.watch == 0 and ex.plan_watch is None
    rng = np.random.RandomState(0)
    for _ in range(3):
        xv, yv = _feeds(rng)
        ex.run("train", feed_dict={x: xv, y_: yv})
    assert calls == [], f"watch-off run touched the sentinel: {calls}"


# ---------------------------------------------------------------------------
# armed run: plan stamp, watch stream, gauges, CLI, gate, calibration
# ---------------------------------------------------------------------------

def test_armed_run_stamps_and_streams(fresh_telemetry):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.telemetry import profiler
    x, y_, loss, train_op = _tiny_mlp(ht)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     telemetry="metrics", plan="auto", watch=1,
                     slo="step_ms<100000")
    assert ex.plan_watch is not None and ex.plan_watch.every == 1
    rng = np.random.RandomState(0)
    for _ in range(6):
        xv, yv = _feeds(rng)
        ex.run("train", feed_dict={x: xv, y_: yv})
    tel = telemetry.get()
    tel.flush()

    recs = [json.loads(l) for l in
            open(os.path.join(fresh_telemetry, "metrics-r0.jsonl"))]
    # ONE plan stamp: the adopted layout, per-leg prediction, rationale
    plans = [r for r in recs if r.get("kind") == "plan"]
    assert len(plans) == 1
    stamp = plans[0]
    assert set(stamp["predicted_legs"]) == {"feed", "ps_pull", "compute",
                                            "ps_push", "poststep"}
    assert "breakdown" in stamp and "comm_mode" in stamp
    assert isinstance(stamp["params"], list)
    # watch rows on every post-compile step: residuals + EWMA + families
    rows = [r for r in recs if r.get("kind") == "watch"]
    assert len(rows) == 5, [r.get("step") for r in rows]   # step 0 compiled
    assert all("residual" in r and "ewma" in r and "divergence" in r
               for r in rows)
    assert rows[0]["worst_leg"] in stamp["predicted_legs"]
    fams = rows[-1].get("families")
    assert fams and "MatMul" in fams
    # gauges rode the final snapshot
    final = [r for r in recs if r.get("kind") == "final"][-1]["metrics"]
    assert 'hetu_plan_residual{leg="compute"}' in final
    assert "hetu_plan_divergence" in final

    # jax-free CLI renders the same stream
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuwatch"),
         fresh_telemetry], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "watch rows: 5" in out.stdout, out.stdout
    assert "plan:" in out.stdout
    outj = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuwatch"),
         fresh_telemetry, "--json"], capture_output=True, text=True)
    rep = json.loads(outj.stdout)
    assert rep["rows"] == 5 and rep["plan"]["comm_mode"] == \
        stamp["comm_mode"]

    # hetuprof --gate accepts the telemetry dir as a metrics source
    cells, meta = profiler.load_summary(fresh_telemetry)
    assert not meta["incomplete"] and "plan_watch" in cells
    cell = cells["plan_watch"]
    assert cell["watch_rows"] == 5 and "divergence" in cell
    assert profiler.metric_direction("plan_watch.divergence") == -1
    assert profiler.metric_direction(
        "plan_watch.residual_ps_pull") == -1
    assert profiler.metric_direction(
        "plan_watch.divergence_events") is None
    base = os.path.join(fresh_telemetry, "..", "base.json")
    with open(base, "w") as f:
        json.dump(cells, f)
    res = profiler.gate_files(base, fresh_telemetry)
    assert res.status == profiler.GATE_OK, vars(res)

    # hetulint --plan --calibrate ingests the live stream: the watch
    # rows' family residuals reach the cost model without a roofline run
    from hetu_tpu.analysis.cost_model import load_calibration
    cal = load_calibration(fresh_telemetry)
    assert "MatMul" in cal.family_residual
    assert cal.step_ms and cal.legs_ms.get("compute") is not None


def test_calibration_watch_rows_without_step_records(tmp_path):
    """A pruned watch-only stream still calibrates: legs/step_ms fall
    back to the watch rows themselves."""
    from hetu_tpu.analysis.cost_model import load_calibration
    with open(tmp_path / "metrics-r0.jsonl", "w") as f:
        for s in range(4):
            f.write(json.dumps({
                "kind": "watch", "step": s, "step_ms": 20.0,
                "legs": {"feed": 1.0, "ps_pull": 3.0, "compute": 12.0,
                         "ps_push": 3.0, "poststep": 1.0},
                "families": {"MatMul": 1.3, "EmbeddingLookup": 2.0},
            }) + "\n")
        f.write(json.dumps({"kind": "watch", "step": 4,
                            "abstain": "world_version"}) + "\n")
    cal = load_calibration(str(tmp_path))
    assert cal.family_residual == {"MatMul": 1.3, "EmbeddingLookup": 2.0}
    assert cal.legs_ms["compute"] == 12.0 and cal.step_ms == 20.0


def test_run_summary_records_plan(tmp_path):
    from hetu_tpu import runner
    with open(tmp_path / "metrics-r0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "run_info", "rank": 0,
                            "comm_mode": "Hybrid"}) + "\n")
        f.write(json.dumps({
            "kind": "plan", "rank": 0, "mesh": {"dp": 2, "tp": 1, "pp": 1},
            "comm_mode": "Hybrid", "comm_quant": "off",
            "predicted_step_ms": 20.0,
            "predicted_legs": {"compute": 12.0},
            "params": [{"param": "embed", "mode": "PS", "sparse": True,
                        "reason": "sparse table"}]}) + "\n")
        f.write(json.dumps({"kind": "step", "rank": 0, "step": 7,
                            "step_ms": 20.0}) + "\n")
    final_steps, resizes, world_versions, plan = \
        runner._scan_rank_jsonl(str(tmp_path))
    assert final_steps == {"0": 7}
    assert plan["comm_mode"] == "Hybrid"
    assert plan["mesh"] == {"dp": 2, "tp": 1, "pp": 1}
    assert plan["params"][0]["param"] == "embed"
    # the launcher summary carries it
    runner._tel_dir = str(tmp_path)
    try:
        runner._write_telemetry_summary(0, False, 1)
    finally:
        runner._tel_dir = None
    summary = json.load(open(tmp_path / "run_summary.json"))
    assert summary["plan"]["predicted_step_ms"] == 20.0
    assert summary["final_steps"] == {"0": 7}


def test_hetuwatch_check_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuwatch"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "pipeline ok" in out.stdout, out.stdout


# ---------------------------------------------------------------------------
# acceptance proof 1: seeded ps_slow — the sentinel names ps_pull + the
# slowed server within K windows; the calibrated clean twin stays silent
# ---------------------------------------------------------------------------

def _watch_ps_slow_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    from hetu_tpu import telemetry
    from hetu_tpu.analysis.planner import Plan
    from hetu_tpu.resilience import FaultInjector, Supervisor
    from hetu_tpu.telemetry import trail

    def build(tag, sub, plan=None, watch=0):
        # disjoint server tensor ids per executor (the bench_wdl_ps
        # convention for multiple PS graphs in one worker process)
        os.environ["HETU_PS_ID_BASE"] = str(tag * 1000)
        embed = ht.init.random_normal((40, 8), stddev=0.1,
                                      name=f"embed{tag}", is_embed=True)
        idx = ht.Variable(name="idx", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        vec = ht.embedding_lookup_op(embed, idx)
        flat = ht.array_reshape_op(vec, (-1, 32))
        w = ht.init.xavier_uniform((32, 1), name=f"w{tag}")
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
        train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
        ex = ht.Executor({sub: [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="Hybrid", bsp=True, prefetch=True,
                         telemetry="metrics", seed=0, plan=plan,
                         watch=watch)
        return ex, idx, y_

    def drive(ex, sub, idx, y_, steps):
        rng = np.random.RandomState(0)
        for _ in range(steps):
            bidx = rng.randint(0, 40, (16, 4)).astype(np.float32)
            by = rng.randint(0, 2, (16, 1)).astype(np.float32)
            ex.run(sub, feed_dict={idx: bidx, y_: by})

    # phase 0 — calibration: measure the clean job's steady-state legs
    ex0, idx0, y0 = build(0, "calib")
    drive(ex0, "calib", idx0, y0, 8)
    legs_seen = []
    sub0 = ex0.subexecutors["calib"]
    # re-derive from the recorded stream (compile steps excluded)
    telemetry.get().flush()
    recs = [json.loads(l) for l in
            open(os.path.join(os.environ["HETU_TELEMETRY_DIR"],
                              "metrics-r0.jsonl"))]
    for r in recs:
        if r.get("kind") == "step" and r.get("sub") == "calib" \
                and "compile_ms" not in (r.get("phases") or {}):
            legs_seen.append(trail.step_legs(r["phases"]))
    assert len(legs_seen) >= 5, len(legs_seen)
    mean = {leg: sum(l[leg] for l in legs_seen) / len(legs_seen)
            for leg in trail.LEGS}
    ex0.close()

    # the calibrated plan: what the planner WOULD promise had it measured
    # this exact job (ps split symmetrized — predicted_legs' 50/50 prior)
    bd = {"compute_ms": mean["compute"], "allreduce_ms": 0.0,
          "ps_ms": mean["ps_pull"] + mean["ps_push"],
          "host_ms": mean["feed"] + mean["poststep"], "bubble_frac": 0.0}
    plan = Plan(devices=1, mesh={"dp": 1, "tp": 1, "pp": 1},
                comm_mode="Hybrid", comm_quant="off", zero1=False,
                remat=False, predicted_step_ms=sum(
                    v for k, v in bd.items() if k.endswith("_ms")),
                breakdown=bd, memory={}, params=[], candidates=[])

    # phase 1 — clean twin: same job, sentinel armed, no fault
    ex1, idx1, y1 = build(1, "clean", plan=plan, watch=1)
    assert ex1.plan_watch is not None
    drive(ex1, "clean", idx1, y1, 10)
    assert not ex1.plan_watch._det.latched
    ex1.close()

    # phase 2 — seeded twin: ps_slow on server 0's apply at step 3; BSP +
    # prefetch queues step 4's pull behind it (the test_trail shape)
    ex2, idx2, y2 = build(2, "seeded", plan=plan, watch=1)
    sup = Supervisor(fault_injector=FaultInjector("ps_slow@3:400"))
    ex2.attach_supervisor(sup)
    drive(ex2, "seeded", idx2, y2, 10)
    assert ex2.plan_watch._det.latched, "seeded divergence never latched"
    ex2.close()
    telemetry.shutdown()


def test_seeded_ps_slow_names_leg_and_server(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TRAIL_DIR", str(tmp_path))
    monkeypatch.setenv("HETU_TRAIL_DRAIN_EVERY", "1")
    monkeypatch.setenv("HETU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("HETU_TELEMETRY", raising=False)
    monkeypatch.delenv("HETU_WATCH", raising=False)
    monkeypatch.delenv("HETU_SLO_SPEC", raising=False)
    # absolute-excess floor at 5 ms: CPU scheduling jitter on the tiny
    # legs must not fire the clean twin; the 400 ms injected stall clears
    # any floor by two orders of magnitude
    monkeypatch.setenv("HETU_WATCH_MIN_MS", "5")
    run_cluster(_watch_ps_slow_worker, tmp_path, n_workers=1, n_servers=2)

    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), "metrics-r0.jsonl"))]
    evs = [r for r in recs if r.get("kind") == "event"
           and r.get("name") == "plan_divergence"]
    # clean twin: ZERO divergence events
    assert not [e for e in evs if e.get("sub") == "clean"], evs
    # seeded twin: exactly ONE latched event naming the leg + server
    seeded = [e for e in evs if e.get("sub") == "seeded"]
    assert len(seeded) == 1, seeded
    ev = seeded[0]
    assert ev["leg"] == "ps_pull", ev
    # fired within K=3 detection windows of the stall — nominally the
    # step-4 pull, but the one-shot apply delay can slide a step or two
    # on a loaded box (the test_trail window rationale)
    assert ev["step"] <= 6 + 3, ev
    assert ev.get("server") == 0, ev          # HETU_PS_SLOW_SERVER default
    assert "recommendation" in ev and "watch-divergence" in json.dumps(
        [r for r in recs if r.get("kind") == "finding"]), ev
    # the jax-free CLI tells the same story from the same dir
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuwatch"),
         str(tmp_path)], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "DIVERGENCE leg ps_pull" in out.stdout, out.stdout
    assert "server 0" in out.stdout, out.stdout


# ---------------------------------------------------------------------------
# acceptance proof 2: 3-seed chaos soak — drop/delay/partition faults
# never oscillate the latch
# ---------------------------------------------------------------------------

def test_chaos_soak_detector_no_oscillation(tmp_path, monkeypatch):
    """Replay each chaos job's MEASURED step legs through a detector
    calibrated on the seed's own fault-free twin: transport retries,
    backoff and a directed partition window may legitimately latch ONE
    divergence episode, but must never churn the latch (fire/recover
    cycling) — the zero-oscillation acceptance."""
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    monkeypatch.setenv("HETU_TELEMETRY", "metrics")
    from hetu_tpu import chaos, telemetry
    from hetu_tpu.telemetry import trail
    from hetu_tpu.telemetry import watch as watch_mod

    def leg_rows(d):
        rows = []
        with open(os.path.join(d, "metrics-r0.jsonl")) as f:
            for line in f:
                r = json.loads(line)
                if r.get("kind") == "step" \
                        and "compile_ms" not in (r.get("phases") or {}):
                    rows.append((r["step"], trail.step_legs(r["phases"]),
                                 r["step_ms"]))
        return rows

    for seed in (1, 2, 3):
        spec = chaos.random_spec(seed, servers=2)
        for arm, sp in (("clean", None), ("chaos", spec)):
            d = tmp_path / f"s{seed}-{arm}"
            monkeypatch.setenv("HETU_TELEMETRY_DIR", str(d))
            telemetry.shutdown()
            chaos.run_job(seed, steps=16, n_servers=2, chaos_spec=sp)
            telemetry.shutdown()
        clean = leg_rows(str(tmp_path / f"s{seed}-clean"))
        assert clean, "clean twin recorded no steps"
        pred = {leg: sum(l[leg] for _, l, _ in clean) / len(clean)
                for leg in watch_mod.LEGS}
        pw = watch_mod.PlanWatch(predicted=pred, every=1, k=3)
        evs = []
        for s, legs, sms in leg_rows(str(tmp_path / f"s{seed}-chaos")):
            _, e = pw.observe(s, legs=legs, step_ms=sms)
            evs += e
        names = [e["name"] for e in evs]
        fired = names.count("plan_divergence")
        recovered = names.count("plan_divergence_recovered")
        # at most one latched episode, never a churn
        assert fired <= 1, (seed, spec, evs)
        assert recovered <= fired, (seed, spec, evs)
