"""Ring allreduce/allgather over loopback TCP (reference
``src/communication/c_communication_nthread.cc`` legacy path; local-process
cluster strategy per SURVEY §4)."""
import multiprocessing as mp
import os

import numpy as np
import pytest

BASE_PORT = 14500


def _ring_body(rank, nranks, port, size, result_q):
    try:
        from hetu_tpu.ps.ring import RingCommunicator
        comm = RingCommunicator(rank, nranks, base_port=port)
        rng = np.random.RandomState(100 + rank)
        local = rng.randn(size).astype(np.float32)

        reduced = comm.allreduce(local.copy())
        expected = np.zeros(size, np.float32)
        for r in range(nranks):
            expected += np.random.RandomState(100 + r).randn(size).astype(
                np.float32)
        np.testing.assert_allclose(reduced, expected, rtol=1e-4, atol=1e-4)

        gathered = comm.allgather(local)
        assert gathered.shape == (nranks, size)
        for r in range(nranks):
            np.testing.assert_allclose(
                gathered[r],
                np.random.RandomState(100 + r).randn(size).astype(np.float32),
                rtol=1e-6)

        comm.barrier()
        comm.finalize()
        result_q.put((rank, "ok", ""))
    except Exception:  # noqa: BLE001 — deliver the traceback to the test
        import traceback
        result_q.put((rank, "fail", traceback.format_exc()))


@pytest.mark.parametrize("nranks,size", [
    (2, 1000),
    (4, 999),          # segment sizes differ (999 % 4 != 0)
    (4, 1 << 20),      # 4 MB: larger than socket buffers (deadlock check)
    (3, 7),            # tiny, n not divisible
])
def test_ring_collectives(nranks, size):
    global BASE_PORT
    BASE_PORT += 10  # fresh ports per case (TIME_WAIT)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_ring_body,
                         args=(r, nranks, BASE_PORT, size, q))
             for r in range(nranks)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(nranks):
            rank, status, err = q.get(timeout=60)
            results[rank] = (status, err)
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
    for rank, (status, err) in sorted(results.items()):
        assert status == "ok", f"rank {rank} failed:\n{err}"
    assert len(results) == nranks


def test_ring_single_rank_noop():
    from hetu_tpu.ps.ring import RingCommunicator
    comm = RingCommunicator(0, 1, base_port=14990)
    x = np.arange(5, dtype=np.float32)
    np.testing.assert_allclose(comm.allreduce(x.copy()), x)
    out = comm.allgather(x)
    np.testing.assert_allclose(out[0], x)
    comm.finalize()
