"""End-to-end PS/Hybrid training through the Executor against a live local
PS cluster (reference: examples/ctr run with --comm PS/Hybrid, SURVEY §2.5).

The embedding table lives on the parameter server; each step the executor
pulls the batch's rows, runs the jitted XLA step, and pushes row gradients.
"""
import numpy as np

from test_ps import run_cluster

NROWS = 40
WIDTH = 8
SLOTS = 4
BATCH = 16


def _build_model(ht):
    embed = ht.init.random_normal((NROWS, WIDTH), stddev=0.1, name="embed",
                                  is_embed=True)
    idx = ht.Variable(name="idx", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    vec = ht.embedding_lookup_op(embed, idx)            # (B, SLOTS, WIDTH)
    flat = ht.array_reshape_op(vec, (-1, SLOTS * WIDTH))
    w = ht.init.xavier_uniform((SLOTS * WIDTH, 1), name="w")
    prob = ht.sigmoid_op(ht.matmul_op(flat, w))
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
    return embed, idx, y_, loss, prob


def _gen_batch(rng):
    bidx = rng.randint(0, NROWS, (BATCH, SLOTS)).astype(np.float32)
    # label = majority of slots drawn from the upper half of the id range:
    # learnable as a per-row score summed across slots (unlike parity)
    by = ((bidx >= NROWS // 2).sum(axis=1) > SLOTS // 2)
    by = by.reshape(BATCH, 1).astype(np.float32)
    return bidx, by


def _hybrid_training(client, rank, tmpdir):
    import hetu_tpu as ht
    embed, idx, y_, loss, prob = _build_model(ht)
    opt = ht.optim.SGDOptimizer(0.1)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op], "validate": [loss, prob]},
                     ctx=ht.cpu(0), comm_mode="Hybrid")
    rng = np.random.RandomState(7 + rank)
    losses = []
    # success is bounded by STEPS (a fixed 200-step budget with a fixed
    # convergence margin), not by wall time — the harness timeout exists
    # only to catch hangs, so a slow host cannot flip the verdict
    # (at 120 steps this seed's margin is ~0.020, right on the bound)
    for _ in range(200):
        bidx, by = _gen_batch(rng)
        out = ex.run("train", feed_dict={idx: bidx, y_: by})
        losses.append(float(out[0].asnumpy()))
    client.BarrierWorker()
    np.save(f"{tmpdir}/hybrid_losses_{rank}.npy", np.asarray(losses))
    # learning happened (embedding rows + dense weights both moved)
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) - 0.02, (
        np.mean(losses[:10]), np.mean(losses[-10:]))
    # validate subexecutor shares the PS tables
    bidx, by = _gen_batch(rng)
    vloss = float(ex.run("validate", feed_dict={idx: bidx, y_: by})[0].asnumpy())
    assert np.isfinite(vloss)


def _ps_mode_dense(client, rank, tmpdir):
    # comm_mode='PS': dense params live on the server too (DDPushPull path)
    import hetu_tpu as ht
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    w = ht.init.random_normal((4, 2), stddev=0.5, name="w")
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
    opt = ht.optim.SGDOptimizer(0.2)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), comm_mode="PS")
    rng = np.random.RandomState(3 + rank)
    true_w = np.array([[2.0, -1.0], [-1.0, 2.0], [0.5, 0.5], [1.0, -2.0]],
                      np.float32)
    losses = []
    for _ in range(50):
        bx = rng.randn(BATCH, 4).astype(np.float32)
        logits = bx @ true_w
        by = np.eye(2, dtype=np.float32)[logits.argmax(1)]
        out = ex.run("train", feed_dict={x: bx, y_: by})
        losses.append(float(out[0].asnumpy()))
    client.BarrierWorker()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) - 0.05
    # both workers see the same server-resident weights
    value = ex.fetch_dense_parameter_value([w])[0].asnumpy()
    np.save(f"{tmpdir}/w_{rank}.npy", value)
    client.BarrierWorker()


def _hybrid_with_cache(client, rank, tmpdir):
    import hetu_tpu as ht
    embed, idx, y_, loss, prob = _build_model(ht)
    opt = ht.optim.SGDOptimizer(0.1)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="Hybrid", cstable_policy="LFUOpt",
                     cache_bound=2)
    rng = np.random.RandomState(11 + rank)
    losses = []
    # steps-bounded like _hybrid_training; this seed's margin at 150
    # steps measured ~0.12-0.13 — 6x the 0.02 bound, so the shorter
    # budget still decides deterministically despite bounded staleness
    for _ in range(150):
        bidx, by = _gen_batch(rng)
        out = ex.run("train", feed_dict={idx: bidx, y_: by})
        losses.append(float(out[0].asnumpy()))
    client.BarrierWorker()
    np.save(f"{tmpdir}/cache_losses_{rank}.npy", np.asarray(losses))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) - 0.02, (
        np.mean(losses[:10]), np.mean(losses[-10:]))


def _ps_checkpoint(client, rank, tmpdir):
    import hetu_tpu as ht
    embed, idx, y_, loss, prob = _build_model(ht)
    opt = ht.optim.SGDOptimizer(0.1)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="Hybrid")
    rng = np.random.RandomState(5)
    for _ in range(5):
        bidx, by = _gen_batch(rng)
        ex.run("train", feed_dict={idx: bidx, y_: by})
    client.BarrierWorker()
    ckpt = f"{tmpdir}/ckpt"
    ex.save(ckpt)
    before = ex.ps_runtime.pull_sparse_rows(
        ex.ps_runtime.params[id(embed)], np.arange(NROWS))
    for _ in range(3):
        bidx, by = _gen_batch(rng)
        ex.run("train", feed_dict={idx: bidx, y_: by})
    client.BarrierWorker()
    ex.load(ckpt)
    after = ex.ps_runtime.pull_sparse_rows(
        ex.ps_runtime.params[id(embed)], np.arange(NROWS))
    np.testing.assert_allclose(after, before, rtol=1e-6)


def _make_loader_model(ht, steps, seed, batch=BATCH):
    """Dataloader-fed embedding model (prefetch needs peekable batches)."""
    rng = np.random.RandomState(seed)
    bidx, by = [], []
    for _ in range(steps):
        bi, b = _gen_batch(rng)
        bidx.append(bi)
        by.append(b)
    bidx = np.concatenate(bidx)
    by = np.concatenate(by)
    embed = ht.init.random_normal((NROWS, WIDTH), stddev=0.1, name="embed",
                                  is_embed=True)
    idx = ht.dataloader_op([ht.Dataloader(bidx, batch, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(by, batch, "train")])
    vec = ht.embedding_lookup_op(embed, idx)
    flat = ht.array_reshape_op(vec, (-1, SLOTS * WIDTH))
    w = ht.init.xavier_uniform((SLOTS * WIDTH, 1), name="w")
    prob = ht.sigmoid_op(ht.matmul_op(flat, w))
    loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return loss, train_op


def _prefetch_overlap(client, rank, tmpdir):
    """prefetch=True (default): after the first step every pull is a
    prefetch hit issued while the previous step ran; pushes are async.

    The counts are EVENT-counted and exact, not statistical: issuance
    happens on the run() thread after every step, and consumption
    (``take_prefetched``) BLOCKS on the in-flight future — a slow host
    makes the hit slower, never a miss. Overlap is a performance
    property; the ledger proves the issuance/consumption pairing."""
    import hetu_tpu as ht
    steps = 40
    loss, train_op = _make_loader_model(ht, steps, seed=13 + rank)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                     comm_mode="Hybrid")
    losses = [float(ex.run("train")[0].asnumpy()) for _ in range(steps)]
    perf = ex.ps_runtime.perf
    # step 0 pulls synchronously; every later step consumes the prefetch
    # issued by its predecessor; the last issue is never consumed
    assert perf["prefetch_issued"] == steps, perf
    assert perf["prefetch_hits"] == steps - 1, perf
    assert perf["prefetch_misses"] == 0, perf
    assert perf["sync_pulls"] == 1, perf
    ex.ps_runtime.drain()
    assert perf["async_pushes"] == steps, perf
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]), (
        np.mean(losses[:10]), np.mean(losses[-10:]))
    client.BarrierWorker()


def _bsp_prefetch_losses(client, rank, tmpdir, prefetch):
    """BSP + single worker: prefetch rides the push stream (push -> barrier ->
    pull ordering), so training is bit-identical to the synchronous path."""
    import hetu_tpu as ht
    steps = 30
    loss, train_op = _make_loader_model(ht, steps, seed=21)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0,
                     comm_mode="Hybrid", bsp=True, prefetch=prefetch)
    losses = [float(ex.run("train")[0].asnumpy()) for _ in range(steps)]
    np.save(f"{tmpdir}/bsp_losses_{int(bool(prefetch))}.npy",
            np.asarray(losses))
    if prefetch:
        ex.ps_runtime.drain()
        assert ex.ps_runtime.perf["prefetch_hits"] >= steps - 2, \
            ex.ps_runtime.perf
    client.BarrierWorker()


def _bsp_prefetch_on(client, rank, tmpdir):
    _bsp_prefetch_losses(client, rank, tmpdir, prefetch=True)


def _bsp_prefetch_off(client, rank, tmpdir):
    _bsp_prefetch_losses(client, rank, tmpdir, prefetch=False)


def _shared_table_two_lookups(client, rank, tmpdir):
    """One PS table feeding TWO lookup ops (shared CTR embedding) must train
    identically to the single-lookup refactoring (lookup on the concatenated
    index sets) — the reference accumulates such grads as IndexedSlices
    (optimizer.py:64-82). Momentum runs server-side, so this also proves the
    host-side dedup-sum: the optimizer state must advance once per row per
    step regardless of how many lookups/slots referenced the row."""
    import os
    import hetu_tpu as ht
    S1, S2 = 2, 3
    rng0 = np.random.RandomState(11)
    table0 = rng0.randn(NROWS, WIDTH).astype(np.float32) * 0.1
    w0 = rng0.randn((S1 + S2) * WIDTH, 1).astype(np.float32) * 0.3

    def build(shared):
        embed = ht.Variable(name="embed", value=table0.copy(), is_embed=True)
        y_ = ht.Variable(name="y_", trainable=False)
        if shared:
            i1 = ht.Variable(name="i1", trainable=False)
            i2 = ht.Variable(name="i2", trainable=False)
            v1 = ht.embedding_lookup_op(embed, i1)      # (B, S1, W)
            v2 = ht.embedding_lookup_op(embed, i2)      # (B, S2, W)
            flat = ht.concat_op(
                ht.array_reshape_op(v1, (-1, S1 * WIDTH)),
                ht.array_reshape_op(v2, (-1, S2 * WIDTH)), axis=1)
            feeds = (i1, i2)
        else:
            ic = ht.Variable(name="ic", trainable=False)
            vec = ht.embedding_lookup_op(embed, ic)     # (B, S1+S2, W)
            flat = ht.array_reshape_op(vec, (-1, (S1 + S2) * WIDTH))
            feeds = (ic,)
        w = ht.Variable(name="w", value=w0.copy())
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
        opt = ht.optim.MomentumOptimizer(0.1, momentum=0.9)
        train_op = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="Hybrid")
        return ex, feeds, y_, embed

    os.environ["HETU_PS_ID_BASE"] = "0"
    exA, feedsA, yA, embA = build(shared=True)
    os.environ["HETU_PS_ID_BASE"] = "100"
    exB, feedsB, yB, embB = build(shared=False)

    rng = np.random.RandomState(7)
    for step in range(12):
        # duplicate rows across (and within) the two index sets on purpose
        i1 = rng.randint(0, NROWS, (BATCH, S1)).astype(np.float32)
        i2 = rng.randint(0, NROWS, (BATCH, S2)).astype(np.float32)
        by = (rng.rand(BATCH, 1) > 0.5).astype(np.float32)
        la = exA.run("train", feed_dict={feedsA[0]: i1, feedsA[1]: i2,
                                         yA: by})[0].asnumpy()
        lb = exB.run("train", feed_dict={
            feedsB[0]: np.concatenate([i1, i2], axis=1), yB: by})[0].asnumpy()
        np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6,
                                   err_msg=f"step {step}")
    pA = exA.ps_runtime.params[id(embA)]
    pB = exB.ps_runtime.params[id(embB)]
    rows = np.arange(NROWS)
    ta = exA.ps_runtime.pull_sparse_rows(pA, rows)
    tb = exB.ps_runtime.pull_sparse_rows(pB, rows)
    np.testing.assert_allclose(ta, tb, rtol=1e-5, atol=1e-6)
    assert not np.allclose(ta, table0)  # the table actually trained

    # cross-target: the same table ALSO feeds a validate head through its
    # own lookup node. Only the train-graph lookup may become a gradient
    # target (the validate lookup stages rows but never pushes grads).
    os.environ["HETU_PS_ID_BASE"] = "200"
    embed = ht.Variable(name="embed2", value=table0.copy(), is_embed=True)
    it = ht.Variable(name="it", trainable=False)
    iv = ht.Variable(name="iv", trainable=False)
    y2 = ht.Variable(name="y2", trainable=False)
    wt = ht.Variable(name="wt", value=w0[:S1 * WIDTH].copy())
    flat_t = ht.array_reshape_op(ht.embedding_lookup_op(embed, it),
                                 (-1, S1 * WIDTH))
    prob_t = ht.sigmoid_op(ht.matmul_op(flat_t, wt))
    loss_t = ht.reduce_mean_op(ht.binarycrossentropy_op(prob_t, y2), [0])
    train2 = ht.optim.MomentumOptimizer(0.1, momentum=0.9).minimize(loss_t)
    flat_v = ht.array_reshape_op(ht.embedding_lookup_op(embed, iv),
                                 (-1, S1 * WIDTH))
    prob_v = ht.sigmoid_op(ht.matmul_op(flat_v, wt))
    ex2 = ht.Executor({"train": [loss_t, train2], "validate": [prob_v]},
                      ctx=ht.cpu(0), comm_mode="Hybrid")
    for _ in range(3):
        i1 = rng.randint(0, NROWS, (BATCH, S1)).astype(np.float32)
        by = (rng.rand(BATCH, 1) > 0.5).astype(np.float32)
        l2 = ex2.run("train", feed_dict={it: i1, y2: by})[0].asnumpy()
        assert np.isfinite(l2)
    pv = ex2.run("validate", feed_dict={
        iv: rng.randint(0, NROWS, (BATCH, S1)).astype(np.float32)})[0].asnumpy()
    assert np.all(np.isfinite(pv))


def test_shared_table_two_lookups(tmp_path):
    run_cluster(_shared_table_two_lookups, tmp_path, n_workers=1, timeout=300)


def _server_opt_schedule_sparse(client, rank, tmpdir):
    """Momentum + StepScheduler on a PS-hosted embedding must match the
    device-resident oracle exactly: the per-step lr rides the push opts
    (SetPushOpts -> store.h UpdateOpts), so the schedule is no longer frozen
    at init (reference: server applies whatever lr arrives with the push,
    ps-lite optimizer.h:15-75). Every row is touched every step so device
    (dense momentum) and server (pushed-rows-only momentum) agree."""
    import hetu_tpu as ht
    SLOTS_ = 4
    B = NROWS // SLOTS_
    rng0 = np.random.RandomState(21)
    table0 = rng0.randn(NROWS, WIDTH).astype(np.float32) * 0.1
    w0 = rng0.randn(SLOTS_ * WIDTH, 1).astype(np.float32) * 0.3

    def build(comm_mode, **kw):
        embed = ht.Variable(name="embed", value=table0.copy(), is_embed=True)
        idx = ht.Variable(name="idx", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        vec = ht.embedding_lookup_op(embed, idx)
        flat = ht.array_reshape_op(vec, (-1, SLOTS_ * WIDTH))
        w = ht.Variable(name="w", value=w0.copy())
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
        opt = ht.optim.MomentumOptimizer(
            ht.lr.StepScheduler(0.2, step_size=3, gamma=0.5), momentum=0.9)
        train_op = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode=comm_mode, **kw)
        return ex, embed, idx, y_

    import os
    os.environ["HETU_PS_ID_BASE"] = "300"
    exP, embP, idxP, yP = build("Hybrid", bsp=True)
    exD, embD, idxD, yD = build(None)

    rng = np.random.RandomState(5)
    for step in range(8):
        bidx = rng.permutation(NROWS).reshape(B, SLOTS_).astype(np.float32)
        by = (rng.rand(B, 1) > 0.5).astype(np.float32)
        lp = exP.run("train", feed_dict={idxP: bidx, yP: by})[0].asnumpy()
        ld = exD.run("train", feed_dict={idxD: bidx, yD: by})[0].asnumpy()
        np.testing.assert_allclose(lp, ld, rtol=1e-5, atol=1e-6,
                                   err_msg=f"step {step}")
    pP = exP.ps_runtime.params[id(embP)]
    served = exP.ps_runtime.pull_sparse_rows(pP, np.arange(NROWS))
    device = np.asarray(exD.state["params"][id(embD)])
    np.testing.assert_allclose(served, device, rtol=1e-4, atol=1e-5)
    assert not np.allclose(served, table0)


def _server_opt_l2_wd_dense(client, rank, tmpdir):
    """comm_mode='PS' dense params with (a) Adam + l2reg + schedule and
    (b) AdamW + decoupled weight decay must match device oracles: l2reg and
    weight_decay ride the push opts and apply against the CURRENT server
    value under the param lock."""
    import os
    import hetu_tpu as ht
    rng0 = np.random.RandomState(31)
    w0 = rng0.randn(6, 3).astype(np.float32) * 0.5

    def build(opt, comm_mode, base):
        os.environ["HETU_PS_ID_BASE"] = str(base)
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        w = ht.Variable(name="w", value=w0.copy())
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
        train_op = opt.minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode=comm_mode)
        return ex, x, y_, w

    cases = [
        ("adam+l2reg+schedule",
         lambda: ht.optim.AdamOptimizer(
             ht.lr.StepScheduler(0.05, step_size=3, gamma=0.5), l2reg=0.02)),
        ("adamw+wd",
         lambda: ht.optim.AdamWOptimizer(0.05, weight_decay=0.1)),
    ]
    rng = np.random.RandomState(9)
    for i, (label, mk) in enumerate(cases):
        exP, xP, yP, wP = build(mk(), "PS", 400 + 10 * i)
        exD, xD, yD, wD = build(mk(), None, 400 + 10 * i + 5)
        for step in range(8):
            bx = rng.randn(BATCH, 6).astype(np.float32)
            by = np.eye(3, dtype=np.float32)[rng.randint(0, 3, BATCH)]
            lp = exP.run("train", feed_dict={xP: bx, yP: by})[0].asnumpy()
            ld = exD.run("train", feed_dict={xD: bx, yD: by})[0].asnumpy()
            np.testing.assert_allclose(
                lp, ld, rtol=1e-5, atol=1e-6, err_msg=f"{label} step {step}")
        served = exP.ps_runtime.pull_dense_value(
            exP.ps_runtime.params[id(wP)])
        device = np.asarray(exD.state["params"][id(wD)])
        np.testing.assert_allclose(served, device, rtol=1e-4, atol=1e-5,
                                   err_msg=label)


def _shared_table_union_prefetch(client, rank, tmpdir):
    """A shared table with dataloader-fed lookups prefetches the UNION of
    the peeked next batches: after step 0 every pre-step pull is a hit, and
    under BSP the losses match the prefetch-off run exactly."""
    import hetu_tpu as ht
    S1, S2, steps = 2, 3, 12
    rng0 = np.random.RandomState(17)
    i1 = rng0.randint(0, NROWS, (steps * BATCH, S1)).astype(np.float32)
    i2 = rng0.randint(0, NROWS, (steps * BATCH, S2)).astype(np.float32)
    by = (rng0.rand(steps * BATCH, 1) > 0.5).astype(np.float32)
    table0 = rng0.randn(NROWS, WIDTH).astype(np.float32) * 0.1
    w0 = rng0.randn((S1 + S2) * WIDTH, 1).astype(np.float32) * 0.3

    import os

    def run(prefetch, base):
        os.environ["HETU_PS_ID_BASE"] = str(base)
        embed = ht.Variable(name="embed", value=table0.copy(), is_embed=True)
        d1 = ht.dataloader_op([ht.Dataloader(i1, BATCH, "train")])
        d2 = ht.dataloader_op([ht.Dataloader(i2, BATCH, "train")])
        dy = ht.dataloader_op([ht.Dataloader(by, BATCH, "train")])
        v1 = ht.embedding_lookup_op(embed, d1)
        v2 = ht.embedding_lookup_op(embed, d2)
        flat = ht.concat_op(
            ht.array_reshape_op(v1, (-1, S1 * WIDTH)),
            ht.array_reshape_op(v2, (-1, S2 * WIDTH)), axis=1)
        w = ht.Variable(name="w", value=w0.copy())
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, dy), [0])
        train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="Hybrid", bsp=True, prefetch=prefetch)
        losses = [float(ex.run("train")[0].asnumpy()) for _ in range(steps)]
        perf = dict(ex.ps_runtime.perf)
        ex.ps_runtime.drain()
        return losses, perf

    on_losses, on_perf = run(True, 500)
    off_losses, _ = run(False, 600)
    np.testing.assert_allclose(on_losses, off_losses, rtol=1e-6, atol=1e-7)
    # union prefetch engaged: after the first step every pull hits
    assert on_perf["prefetch_hits"] >= steps - 1, on_perf
    assert on_perf["prefetch_misses"] == 0, on_perf


def test_shared_table_union_prefetch(tmp_path):
    run_cluster(_shared_table_union_prefetch, tmp_path, n_workers=1,
                timeout=300)


def test_server_opt_schedule_sparse(tmp_path):
    run_cluster(_server_opt_schedule_sparse, tmp_path, n_workers=1,
                timeout=300)


def test_server_opt_l2_wd_dense(tmp_path):
    run_cluster(_server_opt_l2_wd_dense, tmp_path, n_workers=1, timeout=300)


def test_prefetch_overlap(tmp_path):
    run_cluster(_prefetch_overlap, tmp_path, n_workers=1, timeout=300)


def test_bsp_prefetch_exact(tmp_path):
    run_cluster(_bsp_prefetch_on, tmp_path, n_workers=1, timeout=300)
    run_cluster(_bsp_prefetch_off, tmp_path, n_workers=1, timeout=300)
    a = np.load(f"{tmp_path}/bsp_losses_1.npy")
    b = np.load(f"{tmp_path}/bsp_losses_0.npy")
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_hybrid_training(tmp_path):
    # 900s is a hang bound, not a pacing bound: the 200-step body takes
    # ~1-4 min even on a loaded 1-2 core host
    run_cluster(_hybrid_training, tmp_path, n_workers=2, timeout=900)


def test_ps_mode_dense_training(tmp_path):
    run_cluster(_ps_mode_dense, tmp_path, n_workers=2, timeout=300)
    a = np.load(f"{tmp_path}/w_0.npy")
    b = np.load(f"{tmp_path}/w_1.npy")
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_hybrid_training_with_cache(tmp_path):
    run_cluster(_hybrid_with_cache, tmp_path, n_workers=2, timeout=900)


def test_ps_checkpoint_save_load(tmp_path):
    run_cluster(_ps_checkpoint, tmp_path, n_workers=1, timeout=300)
