"""Worker process for tests/test_multihost.py (not collected by pytest).

Joins a 2-process Gloo world (2 virtual CPU devices per process -> 4-device
global dp mesh), trains a linear model data-parallel with each process
feeding only its own half of the batch, and prints the final loss/weights as
one JSON line for the test to compare against a single-process oracle.
"""
import json
import sys

import numpy as np


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from hetu_tpu.parallel import multihost as mh

    assert mh.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid,
                         local_device_count=2)
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == nproc
    assert jax.device_count() == 2 * nproc

    mesh = mh.global_mesh()          # all 4 devices on the dp axis
    assert mesh.shape["dp"] == 2 * nproc

    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype(np.float32)
    W_true = rng.randn(4, 2).astype(np.float32)
    Y = X @ W_true
    rows = len(X) // nproc            # this host's slice of the global batch
    lo, hi = pid * rows, (pid + 1) * rows

    W = jnp.zeros((4, 2), jnp.float32)
    rep = NamedSharding(mesh, P())

    @jax.jit
    def step(W, x, y):
        def loss_fn(W):
            return jnp.mean((x @ W - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(W)
        return loss, W - 0.1 * g

    losses = []
    for _ in range(20):
        x = mh.host_local_batch(mesh, P("dp"), X[lo:hi])
        y = mh.host_local_batch(mesh, P("dp"), Y[lo:hi])
        loss, W = step(W, x, y)
        W = jax.device_put(W, rep)
        losses.append(float(loss))

    mh.barrier("final")
    # distributed checkpoint: every process writes only its own shards of a
    # dp-sharded array; the single-process test restores and checks it
    ckpt_dir = sys.argv[4] if len(sys.argv) > 4 else None
    if ckpt_dir:
        from hetu_tpu import checkpoint
        xsh = mh.host_local_batch(
            mesh, P("dp"), np.full((4, 2), pid + 1.0, np.float32))
        checkpoint.save(ckpt_dir, {"W": W, "xsh": xsh})
    # cross-host host-value allgather parity check
    pids = mh.process_allgather(np.array([pid], np.int32))
    seed = int(mh.broadcast_from_chief(np.array([1234 + pid], np.int32))[0])
    print(json.dumps({
        "pid": pid,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "w_sum": float(np.sum(mh.fetch_replicated(W))),
        "gathered_pids": np.asarray(pids).ravel().tolist(),
        "chief_seed": seed,
    }), flush=True)
    mh.shutdown()


if __name__ == "__main__":
    main()
