"""Coverage for the op-registry surface not exercised elsewhere: explicit
gradient ops (API parity with the reference's per-op Gradient classes,
checked against jax.vjp of the paired forward), remaining elementwise ops,
and the transfer/comm identity markers. Mirrors reference
``tests/test_gpu_op.py``'s one-kernel-one-oracle style."""
import numpy as np

import jax
import jax.numpy as jnp

import hetu_tpu as ht

RTOL, ATOL = 1e-4, 1e-5


from conftest import run_graph_helper as run_graph, feed_helper as feed


# ---------------------------------------------------------------------------
# elementwise / misc forwards
# ---------------------------------------------------------------------------

def test_exp_log_gelu_rsqrt():
    a, av = feed((4, 6), seed=1, name="a")
    pos = np.abs(av) + 0.5
    p, _ = feed(val=pos, name="p")
    np.testing.assert_allclose(run_graph(ht.exp_op(a), {a: av}), np.exp(av),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.log_op(p), {p: pos}), np.log(pos),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.rsqrt_op(p), {p: pos}),
                               1.0 / np.sqrt(pos), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(run_graph(ht.gelu_op(a), {a: av}),
                               np.asarray(jax.nn.gelu(av)), rtol=RTOL,
                               atol=ATOL)


def test_ones_zeros_like_divconst_matrixdot():
    a, av = feed((3, 5), seed=2, name="a")
    np.testing.assert_allclose(run_graph(ht.oneslike_op(a), {a: av}),
                               np.ones_like(av))
    np.testing.assert_allclose(run_graph(ht.zeroslike_op(a), {a: av}),
                               np.zeros_like(av))
    av_nz = av + np.sign(av) + 0.1
    np.testing.assert_allclose(run_graph(ht.div_const_op(2.0, a), {a: av_nz}),
                               2.0 / av_nz, rtol=RTOL, atol=ATOL)
    b, bv = feed((3, 5), seed=3, name="b")
    # reference MatrixDot kernel is an elementwise product
    np.testing.assert_allclose(run_graph(ht.matrix_dot_op(a, b),
                                         {a: av, b: bv}), av * bv,
                               rtol=RTOL, atol=ATOL)


def test_conv_bias_broadcast_and_reduce():
    x, xv = feed((2, 3, 4, 4), seed=4, name="x")
    b, bv = feed((3,), seed=5, name="b")
    out = run_graph(ht.conv2d_broadcastto_op(b, x), {x: xv, b: bv})
    np.testing.assert_allclose(out, np.broadcast_to(
        bv[None, :, None, None], xv.shape))
    out2 = run_graph(ht.conv2d_reducesum_op(x), {x: xv})
    np.testing.assert_allclose(out2, xv.sum(axis=(0, 2, 3)), rtol=RTOL,
                               atol=ATOL)


def test_instance_norm2d():
    x, xv = feed((2, 3, 5, 5), seed=6, name="x")
    out = run_graph(ht.instance_normalization2d_op(x, eps=1e-5), {x: xv})
    mean = xv.mean(axis=(2, 3), keepdims=True)
    var = xv.var(axis=(2, 3), keepdims=True)
    np.testing.assert_allclose(out, (xv - mean) / np.sqrt(var + 1e-5),
                               rtol=RTOL, atol=ATOL)


def test_transfer_markers_and_placeholder_alias():
    a, av = feed((2, 3), seed=7, name="a")
    np.testing.assert_allclose(
        run_graph(ht.datad2h_op(ht.datah2d_op(a)), {a: av}), av)
    p = ht.placeholder_op(name="p2")  # reference Variable alias
    np.testing.assert_allclose(run_graph(p + 0.0, {p: av}), av)


def test_allreduce_ops_identity_off_mesh():
    """Without a mesh the (group)allreduce markers are identities."""
    a, av = feed((4, 2), seed=8, name="a")
    np.testing.assert_allclose(
        run_graph(ht.allreduceCommunicate_op(a), {a: av}), av)
    np.testing.assert_allclose(
        run_graph(ht.groupallreduceCommunicate_op(a), {a: av}), av)


def test_dropout2d_channelwise():
    """dropout2d drops WHOLE channels; survivors are scaled by 1/keep."""
    x, xv = feed(val=np.ones((4, 8, 5, 5), np.float32), name="x")
    node = ht.dropout2d_op(x, 0.5)
    # optimizer present => tc.training True, so the mask is actually drawn
    train = ht.optim.SGDOptimizer(0.0).minimize(
        ht.reduce_mean_op(node * ht.Variable("w2d", value=np.ones(
            (4, 8, 5, 5), np.float32)), [0, 1, 2, 3]))
    ex = ht.Executor({"t": [node, train]}, ctx=ht.cpu(0), seed=0)
    out = ex.run("t", feed_dict={x: xv},
                 convert_to_numpy_ret_vals=True)[0]
    per_channel = out.reshape(4, 8, -1)
    for nc in per_channel.reshape(-1, per_channel.shape[-1]):
        assert np.all(nc == 0.0) or np.allclose(nc, 2.0), nc  # 1/keep = 2
    kept = (per_channel[..., 0] != 0).mean()
    assert 0.2 < kept < 0.8


def test_dropout_gradient_regenerates_forward_mask():
    """dropout(2d)_gradient_op rebuilds the forward op's mask from its RNG:
    positions zeroed in the forward are zeroed in the grad, survivors scale
    by 1/keep — so feeding the forward's own INPUT as the cotangent must
    reproduce the forward output exactly (same mask, same scaling)."""
    xval = np.ones((4, 6, 3, 3), np.float32)
    for fwd_ctor, grad_ctor in ((ht.dropout_op, ht.dropout_gradient_op),
                                (ht.dropout2d_op, ht.dropout2d_gradient_op)):
        x, _ = feed(val=xval, name="x")
        fwd = fwd_ctor(x, 0.5)
        g = ht.Variable(name="g", trainable=False)
        grad = grad_ctor(g, 0.5, fwd)
        # a training graph (optimizer present) so tc.training is True
        w = ht.Variable("wdrop", value=np.ones_like(xval))
        train = ht.optim.SGDOptimizer(0.0).minimize(
            ht.reduce_mean_op(fwd * w, [0, 1, 2, 3]))
        ex = ht.Executor({"t": [fwd, grad, train]}, ctx=ht.cpu(0), seed=0)
        fv, gv, _ = ex.run("t", feed_dict={x: xval, g: xval},
                           convert_to_numpy_ret_vals=True)
        np.testing.assert_allclose(gv, fv, rtol=RTOL, atol=ATOL)
        assert 0.0 < (fv != 0).mean() < 1.0  # mask actually dropped some


# ---------------------------------------------------------------------------
# explicit gradient ops vs jax.vjp of the paired forward
# ---------------------------------------------------------------------------

def test_conv2d_gradient_ops():
    rng = np.random.RandomState(9)
    xv = rng.randn(2, 3, 8, 8).astype(np.float32)
    wv = rng.randn(4, 3, 3, 3).astype(np.float32)
    dyv = rng.randn(2, 4, 8, 8).astype(np.float32)

    def fwd(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    _, vjp = jax.vjp(fwd, jnp.asarray(xv), jnp.asarray(wv))
    dx_ref, dw_ref = (np.asarray(v) for v in vjp(jnp.asarray(dyv)))

    w, _ = feed(val=wv, name="w")
    dy, _ = feed(val=dyv, name="dy")
    x, _ = feed(val=xv, name="x")
    dx = run_graph(ht.conv2d_gradient_of_data_op(w, dy, padding=1, stride=1),
                   {w: wv, dy: dyv})
    np.testing.assert_allclose(dx, dx_ref, rtol=RTOL, atol=1e-4)
    dw = run_graph(ht.conv2d_gradient_of_filter_op(x, dy, padding=1, stride=1),
                   {x: xv, dy: dyv})
    np.testing.assert_allclose(dw, dw_ref, rtol=RTOL, atol=1e-4)


def test_pool_gradient_ops():
    rng = np.random.RandomState(10)
    xv = rng.randn(2, 3, 8, 8).astype(np.float32)
    x, _ = feed(val=xv, name="x")
    for fwd_op, grad_op, jfwd in (
            (ht.max_pool2d_op, ht.max_pool2d_gradient_op,
             lambda v: jax.lax.reduce_window(v, -jnp.inf, jax.lax.max,
                                             (1, 1, 2, 2), (1, 1, 2, 2),
                                             "VALID")),
            (ht.avg_pool2d_op, ht.avg_pool2d_gradient_op,
             lambda v: jax.lax.reduce_window(v, 0.0, jax.lax.add,
                                             (1, 1, 2, 2), (1, 1, 2, 2),
                                             "VALID") / 4.0)):
        yv = np.asarray(jfwd(jnp.asarray(xv)))
        dyv = rng.randn(*yv.shape).astype(np.float32)
        _, vjp = jax.vjp(jfwd, jnp.asarray(xv))
        ref = np.asarray(vjp(jnp.asarray(dyv))[0])
        y, _ = feed(val=yv, name="y")
        dy, _ = feed(val=dyv, name="dy")
        out = run_graph(grad_op(y, dy, x, 2, 2, 0, 2),
                        {y: yv, dy: dyv, x: xv})
        np.testing.assert_allclose(out, ref, rtol=RTOL, atol=1e-4)


def test_activation_gradient_ops():
    rng = np.random.RandomState(11)
    xv = rng.randn(4, 6).astype(np.float32)
    gv = rng.randn(4, 6).astype(np.float32)
    x, _ = feed(val=xv, name="x")
    g, _ = feed(val=gv, name="g")
    np.testing.assert_allclose(
        run_graph(ht.relu_gradient_op(x, g), {x: xv, g: gv}),
        np.where(xv > 0, gv, 0.0), rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(
        run_graph(ht.leaky_relu_gradient_op(x, g, 0.1), {x: xv, g: gv}),
        np.where(xv > 0, gv, 0.1 * gv), rtol=RTOL, atol=ATOL)
    # softmax gradient takes the forward OUTPUT y
    yv = np.asarray(jax.nn.softmax(jnp.asarray(xv), axis=-1))
    y, _ = feed(val=yv, name="y")
    _, vjp = jax.vjp(lambda v: jax.nn.softmax(v, -1), jnp.asarray(xv))
    ref = np.asarray(vjp(jnp.asarray(gv))[0])
    np.testing.assert_allclose(
        run_graph(ht.softmax_gradient_op(y, g), {y: yv, g: gv}), ref,
        rtol=RTOL, atol=ATOL)


def test_shape_gradient_ops():
    rng = np.random.RandomState(12)
    xv = rng.randn(4, 6).astype(np.float32)
    x, _ = feed(val=xv, name="x")

    gv = rng.randn(24).astype(np.float32)
    g, _ = feed(val=gv, name="g")
    out = run_graph(ht.array_reshape_gradient_op(x, g), {x: xv, g: gv})
    np.testing.assert_allclose(out, gv.reshape(4, 6))

    # slice grad scatters back into the input shape
    dyv = rng.randn(2, 3).astype(np.float32)
    dy, _ = feed(val=dyv, name="dy")
    out = run_graph(ht.slice_gradient_op(dy, (1, 2), size=(4, 6)),
                    {dy: dyv})
    ref = np.zeros((4, 6), np.float32)
    ref[1:3, 2:5] = dyv
    np.testing.assert_allclose(out, ref)

    # concat grad slices each operand's chunk back out
    a2 = rng.randn(4, 2).astype(np.float32)
    gcat = rng.randn(4, 8).astype(np.float32)
    ga, _ = feed(val=gcat, name="ga")
    xa, _ = feed(val=a2, name="xa")
    out0 = run_graph(ht.concat_gradient_op(ga, xa, axis=1, idx=0),
                     {ga: gcat, xa: a2})
    np.testing.assert_allclose(out0, gcat[:, :2])
    out1 = run_graph(ht.concat_gradient_op(ga, xa, axis=1, idx=1),
                     {ga: gcat, xa: a2})
    np.testing.assert_allclose(out1, gcat[:, -2:])

    # pad grad crops the padding back off
    gp = rng.randn(6, 8).astype(np.float32)
    gpn, _ = feed(val=gp, name="gp")
    out = run_graph(ht.pad_gradient_op(gpn, [(1, 1), (1, 1)]), {gpn: gp})
    np.testing.assert_allclose(out, gp[1:5, 1:7])

    # split grad scatters the partition back
    gs = rng.randn(2, 6).astype(np.float32)
    gsn, _ = feed(val=gs, name="gs")
    out = run_graph(ht.split_gradient_op(gsn, axes=0, indices=1, splits=2),
                    {gsn: gs})
    ref = np.zeros((4, 6), np.float32)
    ref[2:] = gs
    np.testing.assert_allclose(out, ref)


def test_embedding_and_loss_gradient_ops():
    rng = np.random.RandomState(13)
    table_shape = (10, 4)
    idxv = rng.randint(0, 10, (6,)).astype(np.float32)
    vecv = rng.randn(6, 4).astype(np.float32)
    idx, _ = feed(val=idxv, name="idx")
    vec, _ = feed(val=vecv, name="vec")
    out = run_graph(ht.embedding_lookup_gradient_op(vec, idx, table_shape),
                    {vec: vecv, idx: idxv})
    ref = np.zeros(table_shape, np.float32)
    np.add.at(ref, idxv.astype(int), vecv)
    np.testing.assert_allclose(out, ref, rtol=RTOL, atol=ATOL)

    # bce / softmax-ce explicit gradients vs jax.vjp
    logits = rng.randn(5, 3).astype(np.float32)
    onehot = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 5)]
    dl = rng.randn(5).astype(np.float32)

    def sce(z):
        return -jnp.sum(jnp.asarray(onehot) * jax.nn.log_softmax(z), axis=-1)

    _, vjp = jax.vjp(sce, jnp.asarray(logits))
    ref = np.asarray(vjp(jnp.asarray(dl))[0])
    z, _ = feed(val=logits, name="z")
    yt, _ = feed(val=onehot, name="yt")
    dln, _ = feed(val=dl, name="dl")
    out = run_graph(ht.softmaxcrossentropy_gradient_op(z, yt, dln),
                    {z: logits, yt: onehot, dln: dl})
    np.testing.assert_allclose(out, ref, rtol=RTOL, atol=1e-4)

    probs = 1 / (1 + np.exp(-logits))
    labels = (rng.rand(5, 3) > 0.5).astype(np.float32)

    def bce(p):
        return -(jnp.asarray(labels) * jnp.log(p)
                 + (1 - jnp.asarray(labels)) * jnp.log(1 - p))

    dlm = rng.randn(5, 3).astype(np.float32)
    _, vjp = jax.vjp(bce, jnp.asarray(probs))
    ref = np.asarray(vjp(jnp.asarray(dlm))[0])
    p, _ = feed(val=probs, name="p")
    lb, _ = feed(val=labels, name="lb")
    dm, _ = feed(val=dlm, name="dm")
    out = run_graph(ht.binarycrossentropy_gradient_op(p, lb, dm),
                    {p: probs, lb: labels, dm: dlm})
    np.testing.assert_allclose(out, ref, rtol=RTOL, atol=1e-4)
