"""Worker for tests/test_multihost_hybrid.py (not collected by pytest).

The reference's Hybrid comm mode (dense grads AllReduce, sparse embeddings
through the PS — optimizer.py:129-136) at MULTI-HOST scale: each process is
simultaneously
- one host of a 2-process jax.distributed world (Gloo collectives over a
  4-device global dp mesh) for the dense parameters, and
- one DMLC worker of a live PS cluster for the embedding table
  (SparsePull rows for its batch, SparsePush the row gradients).
"""
import json
import sys

import numpy as np

N_ROWS, WIDTH, CLASSES = 32, 8, 2


def main():
    pid, nproc, jport = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from hetu_tpu.parallel import multihost as mh

    assert mh.initialize(coordinator_address=f"127.0.0.1:{jport}",
                         num_processes=nproc, process_id=pid,
                         local_device_count=2)
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from hetu_tpu.ps.client import PSClient

    client = PSClient.from_env()      # DMLC_* env from the test harness
    client.InitTensor(31, sparse=1, length=N_ROWS, width=WIDTH,
                      init_type="normal", init_a=0.0, init_b=0.3)

    mesh = mh.global_mesh()
    rep = NamedSharding(mesh, P())

    init_rows = np.zeros((N_ROWS, WIDTH), np.float32)
    client.SparsePull(31, np.arange(N_ROWS, dtype=np.int64), init_rows)
    client.Wait(31)

    # deterministic data: row ids + labels; each host feeds its own half
    rng = np.random.RandomState(0)
    true_emb = rng.randn(N_ROWS, WIDTH).astype(np.float32)
    true_w = rng.randn(WIDTH, CLASSES).astype(np.float32)
    all_ids = rng.randint(0, N_ROWS, (8,)).astype(np.int64)
    all_y = (true_emb[all_ids] @ true_w).argmax(1).astype(np.int32)
    rows_per_host = len(all_ids) // nproc
    lo, hi = pid * rows_per_host, (pid + 1) * rows_per_host

    # same seed on every host: dense params start (and stay) identical
    W = jnp.asarray(np.random.RandomState(7).randn(WIDTH, CLASSES) * 0.3,
                    jnp.float32)

    @jax.jit
    def step(W, emb, y):
        def loss_fn(W, emb):
            logits = emb @ W
            lp = jax.nn.log_softmax(logits, -1)
            return -jnp.mean(jnp.take_along_axis(lp, y[:, None], -1))
        (loss), grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(W, emb)
        return loss, grads[0], grads[1]

    lr = 0.5
    losses = []
    for it in range(60):
        ids = all_ids[lo:hi]
        rows = np.zeros((len(ids), WIDTH), np.float32)
        client.SparsePull(31, ids, rows)           # sparse: through the PS
        client.Wait(31)
        emb = mh.host_local_batch(mesh, P("dp"), rows)
        y = mh.host_local_batch(mesh, P("dp"), all_y[lo:hi])
        loss, gW, gemb = step(W, emb, y)
        # dense: GSPMD already summed over dp inside the jit; apply locally
        W = jax.device_put(W - lr * gW, rep)
        # sparse: push THIS HOST's row grads back to the PS (server += ).
        # gemb is dp-sharded; this process's shards are exactly its own
        # rows — order them by their global offset
        shards = sorted(gemb.addressable_shards, key=lambda s: s.index[0].start)
        local_rows = np.concatenate([np.asarray(s.data) for s in shards])
        client.SparsePush(31, ids, -lr * local_rows)
        client.Wait(31)
        mh.barrier(f"step{it}")                    # BSP: reference's bsp mode
        losses.append(float(loss))

    # final table rows as seen by this worker
    final_rows = np.zeros((N_ROWS, WIDTH), np.float32)
    client.SparsePull(31, np.arange(N_ROWS, dtype=np.int64), final_rows)
    client.Wait(31)
    print(json.dumps({
        "pid": pid,
        "first_loss": losses[0],
        "final_loss": losses[-1],
        "w_sum": float(np.sum(mh.fetch_replicated(W))),
        "table_digest": float(np.sum(final_rows * final_rows)),
        "table_moved": float(np.abs(final_rows - init_rows).max()),
    }), flush=True)
    client.close()
    mh.shutdown()


if __name__ == "__main__":
    main()
