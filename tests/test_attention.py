"""Flash attention + ring attention numerics vs the unfused oracle
(the reference framework's BatchMatMul+Softmax attention)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from hetu_tpu.utils import shard_map

from hetu_tpu.kernels.flash_attention import flash_attention, mha_reference
from hetu_tpu.parallel.ring_attention import ring_attention


def _rand_qkv(rng, b=2, h=2, s=256, d=64):
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _rand_qkv(np.random.RandomState(0))
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_backward_matches_reference():
    q, k, v = _rand_qkv(np.random.RandomState(1), s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(64, 64), (32, 64), (64, 32)])
def test_pallas_backward_kernels_match_blockwise(causal, block_q, block_k):
    """The TPU backward path (dq + fused dk/dv Pallas kernels, run here in
    interpret mode) must match the XLA blockwise backward (the oracle) and
    the autodiff of the unfused reference."""
    from hetu_tpu.kernels import flash_attention as fa

    q, k, v = _rand_qkv(np.random.RandomState(2), s=128)
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, lse = fa._fwd_pallas(q, k, v, None, scale, causal, block_q, block_k,
                              interpret=True)
    rng = np.random.RandomState(3)
    do = jnp.asarray(rng.randn(*out.shape), jnp.float32)
    res = (q, k, v, out, lse, None)

    dq_p, dk_p, dv_p = fa._bwd_pallas(res, do, scale=scale, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      interpret=True)
    dq_b, dk_b, dv_b = fa._bwd_blockwise(res, do, scale=scale, causal=causal,
                                         block_k=block_k)
    for a, b in zip((dq_p, dk_p, dv_p), (dq_b, dk_b, dv_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)

    def loss_ref(q, k, v):
        return jnp.vdot(mha_reference(q, k, v, causal), do)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq_p, dk_p, dv_p), gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("block_q,block_k", [(64, 128), (32, 256), (128, 64)])
def test_flash_causal_uneven_blocks(block_q, block_k):
    """block_q != block_k regression: the causal key-block bound must use
    ceil division — flooring drops the diagonal block when block_q < block_k
    and the first query rows silently output zeros."""
    q, k, v = _rand_qkv(np.random.RandomState(3))
    out = flash_attention(q, k, v, causal=True,
                          block_q=block_q, block_k=block_k)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def _padding_bias(rng, b, s, min_valid=8):
    """(b, s) key-padding bias: 0 for valid keys, -1e9 for a padded tail."""
    lens = rng.randint(min_valid, s + 1, b)
    pos = np.arange(s)[None, :]
    return jnp.asarray(np.where(pos < lens[:, None], 0.0, -1e9), jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_key_bias_matches_reference(causal):
    """The fused kernel must fold a key-padding bias exactly like the
    unfused form — masked BERT batches no longer leave the flash path."""
    rng = np.random.RandomState(5)
    q, k, v = _rand_qkv(rng, s=256)
    k_bias = _padding_bias(rng, q.shape[0], q.shape[2])
    out = flash_attention(q, k, v, causal=causal, k_bias=k_bias)
    ref = mha_reference(q, k, v, causal=causal, k_bias=k_bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_key_bias_gradients():
    rng = np.random.RandomState(6)
    q, k, v = _rand_qkv(rng, s=128)
    k_bias = _padding_bias(rng, q.shape[0], q.shape[2])

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, False, k_bias=k_bias) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, False, k_bias=k_bias) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_backward_kernels_with_bias(causal):
    """The TPU backward kernels (interpret mode) must handle the key bias
    identically to the blockwise oracle and the reference autodiff."""
    from hetu_tpu.kernels import flash_attention as fa

    rng = np.random.RandomState(7)
    q, k, v = _rand_qkv(rng, s=128)
    k_bias = _padding_bias(rng, q.shape[0], q.shape[2])
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, lse = fa._fwd_pallas(q, k, v, k_bias, scale, causal, 64, 64,
                              interpret=True)
    do = jnp.asarray(rng.randn(*out.shape), jnp.float32)
    res = (q, k, v, out, lse, k_bias)
    dq_p, dk_p, dv_p = fa._bwd_pallas(res, do, scale=scale, causal=causal,
                                      block_q=64, block_k=64, interpret=True)

    def loss_ref(q, k, v):
        return jnp.vdot(mha_reference(q, k, v, causal, k_bias=k_bias), do)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq_p, dk_p, dv_p), gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_masked_bert_encoder_flash_matches_dot():
    """End to end: a padded BERT batch through the encoder with
    attn_impl='flash' (interpret off-TPU) equals attn_impl='dot' — the mask
    no longer forces the unfused path."""
    from hetu_tpu.models import bert as bertlib
    from hetu_tpu.models import transformer as tfm

    outs = {}
    for impl in ("dot", "flash"):
        cfg = bertlib.BertConfig(vocab_size=128, d_model=64, n_heads=4,
                                 n_layers=2, d_ff=128, max_seq_len=64,
                                 dtype=jnp.float32, remat=False,
                                 attn_impl=impl)
        params = bertlib.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(8)
        ids = jnp.asarray(rng.randint(0, 128, (2, 64)), jnp.int32)
        seg = jnp.zeros((2, 64), jnp.int32)
        mask = jnp.asarray(
            np.arange(64)[None, :] < np.array([[40], [64]]), jnp.int32)
        # resolution: a key-padding bias keeps the requested fused impl
        bias = (1.0 - mask.astype(jnp.float32))[:, None, None, :] * -1e9
        assert tfm._resolve_attn_impl(cfg.trunk(), None, 64, bias) == impl
        outs[impl] = bertlib.encode(params, ids, seg, cfg, input_mask=mask)
    np.testing.assert_allclose(np.asarray(outs["flash"]),
                               np.asarray(outs["dot"]), rtol=2e-4, atol=2e-4)


def test_nonpadding_bias_still_falls_back_to_dot():
    """A full (B, nh, T, T) additive bias is NOT key-padding-shaped: an
    explicit fused request degrades loudly to 'dot'."""
    from hetu_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(attn_impl="flash")
    full_bias = jnp.zeros((2, 4, 64, 64), jnp.float32)
    with pytest.warns(UserWarning, match="non-key-padding"):
        assert tfm._resolve_attn_impl(cfg, None, 64, full_bias) == "dot"
    # masked + block-indivisible seq keeps the pre-existing graceful
    # fallback instead of tripping the kernel's divisibility error
    pad_bias = jnp.zeros((2, 1, 1, 192), jnp.float32)
    with pytest.warns(UserWarning, match="divisible by 128"):
        assert tfm._resolve_attn_impl(cfg, None, 192, pad_bias) == "dot"


def test_flash_nondivisible_raises():
    q, k, v = _rand_qkv(np.random.RandomState(2), s=96)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, None, 128, 64)


def _sp_mesh(n=4):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), ("sp",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = _sp_mesh(4)
    q, k, v = _rand_qkv(np.random.RandomState(3), b=1, h=2, s=128, d=32)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    out = ring(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_key_bias_matches_full(causal):
    """The key-padding bias rotates with its k/v chunk around the ring and
    must reproduce the full-attention oracle, padded tails included."""
    mesh = _sp_mesh(4)
    rng = np.random.RandomState(9)
    q, k, v = _rand_qkv(rng, b=2, h=2, s=128, d=32)
    k_bias = _padding_bias(rng, 2, 128)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None))
    out = ring(q, k, v, k_bias)
    ref = mha_reference(q, k, v, causal=causal, k_bias=k_bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_key_bias_gradients():
    mesh = _sp_mesh(4)
    rng = np.random.RandomState(10)
    q, k, v = _rand_qkv(rng, b=1, h=2, s=64, d=16)
    k_bias = _padding_bias(rng, 1, 64)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=False),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, None, "sp", None))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, k_bias) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, False, k_bias=k_bias) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ring_attention_gradients():
    mesh = _sp_mesh(4)
    q, k, v = _rand_qkv(np.random.RandomState(4), b=1, h=1, s=64, d=16)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, True) ** 2)

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bf16():
    q, k, v = _rand_qkv(np.random.RandomState(5), s=128)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
