"""Flash attention + ring attention numerics vs the unfused oracle
(the reference framework's BatchMatMul+Softmax attention)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from hetu_tpu.kernels.flash_attention import flash_attention, mha_reference
from hetu_tpu.parallel.ring_attention import ring_attention


def _rand_qkv(rng, b=2, h=2, s=256, d=64):
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = _rand_qkv(np.random.RandomState(0))
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_backward_matches_reference():
    q, k, v = _rand_qkv(np.random.RandomState(1), s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(64, 64), (32, 64), (64, 32)])
def test_pallas_backward_kernels_match_blockwise(causal, block_q, block_k):
    """The TPU backward path (dq + fused dk/dv Pallas kernels, run here in
    interpret mode) must match the XLA blockwise backward (the oracle) and
    the autodiff of the unfused reference."""
    from hetu_tpu.kernels import flash_attention as fa

    q, k, v = _rand_qkv(np.random.RandomState(2), s=128)
    scale = 1.0 / np.sqrt(q.shape[-1])
    out, lse = fa._fwd_pallas(q, k, v, scale, causal, block_q, block_k,
                              interpret=True)
    rng = np.random.RandomState(3)
    do = jnp.asarray(rng.randn(*out.shape), jnp.float32)
    res = (q, k, v, out, lse)

    dq_p, dk_p, dv_p = fa._bwd_pallas(res, do, scale=scale, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      interpret=True)
    dq_b, dk_b, dv_b = fa._bwd_blockwise(res, do, scale=scale, causal=causal,
                                         block_k=block_k)
    for a, b in zip((dq_p, dk_p, dv_p), (dq_b, dk_b, dv_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)

    def loss_ref(q, k, v):
        return jnp.vdot(mha_reference(q, k, v, causal), do)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip((dq_p, dk_p, dv_p), gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("block_q,block_k", [(64, 128), (32, 256), (128, 64)])
def test_flash_causal_uneven_blocks(block_q, block_k):
    """block_q != block_k regression: the causal key-block bound must use
    ceil division — flooring drops the diagonal block when block_q < block_k
    and the first query rows silently output zeros."""
    q, k, v = _rand_qkv(np.random.RandomState(3))
    out = flash_attention(q, k, v, causal=True,
                          block_q=block_q, block_k=block_k)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_nondivisible_raises():
    q, k, v = _rand_qkv(np.random.RandomState(2), s=96)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, None, 128, 64)


def _sp_mesh(n=4):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), ("sp",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = _sp_mesh(4)
    q, k, v = _rand_qkv(np.random.RandomState(3), b=1, h=2, s=128, d=32)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    out = ring(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients():
    mesh = _sp_mesh(4)
    q, k, v = _rand_qkv(np.random.RandomState(4), b=1, h=1, s=64, d=16)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, True) ** 2)

    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_bf16():
    q, k, v = _rand_qkv(np.random.RandomState(5), s=128)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
