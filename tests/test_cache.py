"""Embedding-cache tests against a live local PS cluster.

Mirrors the reference's tests/hetu_cache/hetu_cache_test.py strategy
(SURVEY.md §4.4): CacheSparseTable policies exercised against a local
parameter server, with bounded-staleness propagation checked across workers.
"""
import os
import time

import numpy as np

from test_ps import run_cluster

NROWS = 64
WIDTH = 8


def _mk_table(client, node_id, policy, bound, limit=16, init_a=1.0):
    client.InitTensor(node_id, sparse=2, length=NROWS, width=WIDTH,
                      init_type="constant", init_a=init_a)
    from hetu_tpu.cstable import CacheSparseTable
    return CacheSparseTable(limit, NROWS, WIDTH, node_id, policy=policy,
                            bound=bound)


def _lookup_update_roundtrip(client, rank, tmpdir):
    # single worker: lookup pulls initial values; update applies locally and
    # (bound=0) pushes every batch; a fresh lookup of evicted rows re-pulls
    table = _mk_table(client, 10, "LRU", bound=0, limit=8)
    keys = np.arange(4, dtype=np.uint64)
    dest = np.zeros((4, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.0)

    grads = np.full((4, WIDTH), 0.5, np.float32)
    table.embedding_update(keys, grads, sync=True)
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.5)

    # server saw the push (bound=0): bypass the cache and read raw
    table.bypass()
    dest2 = np.zeros((4, WIDTH), np.float32)
    table.embedding_lookup(keys, dest2, sync=True)
    np.testing.assert_allclose(dest2, 1.5)


def _policies(client, rank, tmpdir):
    for node_id, policy in ((11, "LRU"), (12, "LFU"), (13, "LFUOpt")):
        table = _mk_table(client, node_id, policy, bound=0, limit=8)
        # touch more keys than the limit: evictions must stay correct
        for lo in range(0, NROWS, 8):
            keys = np.arange(lo, lo + 8, dtype=np.uint64)
            dest = np.zeros((8, WIDTH), np.float32)
            table.embedding_lookup(keys, dest, sync=True)
            np.testing.assert_allclose(dest, 1.0, err_msg=policy)
            table.embedding_update(
                keys, np.full((8, WIDTH), 0.25, np.float32), sync=True)
        assert len(table) <= 8
        # all rows were updated exactly once -> server value 1.25 everywhere
        table.bypass()
        dest = np.zeros((NROWS, WIDTH), np.float32)
        table.embedding_lookup(np.arange(NROWS, dtype=np.uint64), dest,
                               sync=True)
        np.testing.assert_allclose(dest, 1.25, err_msg=policy)


def _dedup_keys(client, rank, tmpdir):
    table = _mk_table(client, 14, "LRU", bound=0)
    # duplicate keys in one lookup get one line; update accumulates per slot
    keys = np.array([3, 3, 3, 5], np.uint64)
    dest = np.zeros((4, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.0)
    table.embedding_update(keys, np.ones((4, WIDTH), np.float32), sync=True)
    out = table.lookup(3)
    np.testing.assert_allclose(out["data"], 4.0)  # 1.0 + 3 dup grads


def _staleness_propagation(client, rank, tmpdir):
    # bound=0: every lookup syncs rows the server advanced past the local
    # version, so worker 1 observes worker 0's pushed update
    table = _mk_table(client, 15, "LRU", bound=0)
    keys = np.arange(8, dtype=np.uint64)
    dest = np.zeros((8, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.0)
    client.BarrierWorker()
    if rank == 0:
        table.embedding_update(keys, np.full((8, WIDTH), 2.0, np.float32),
                               sync=True)
    client.BarrierWorker()
    table.embedding_lookup(keys, dest, sync=True)
    expected = 3.0  # both workers see 1.0 + 2.0 after the push
    np.testing.assert_allclose(dest, expected)


def _bounded_staleness_skips_fresh_rows(client, rank, tmpdir):
    # large bound: a second lookup transfers NO rows (version gap <= bound)
    table = _mk_table(client, 16, "LRU", bound=1000)
    table.perf_enabled(True)
    keys = np.arange(8, dtype=np.uint64)
    dest = np.zeros((8, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)   # cold: pulls all 8
    table.embedding_lookup(keys, dest, sync=True)   # warm: pulls none
    perf = table.perf
    assert perf[0]["num_transfered"] == 8, perf[0]
    assert perf[1]["num_transfered"] == 0, perf[1]
    assert table.overall_miss_rate(include_cold_start=True) >= 0
    # telemetry_summary reads the native O(1) rollup — it must agree with
    # aggregating the full per-batch log (the path it replaced)
    s = table.telemetry_summary()
    pull = [x for x in perf if x["type"] == "Pull"]
    assert s["batches"] == len(perf)
    assert s["evictions"] == sum(x["num_evict"] for x in perf)
    assert s["miss_rate"] == (sum(x["num_miss"] for x in pull)
                              / sum(x["num_unique"] for x in pull))
    assert s["data_rate"] == (sum(x["num_transfered"] for x in perf)
                              / sum(x["num_all"] for x in perf))


def _push_pull_combined(client, rank, tmpdir):
    table = _mk_table(client, 17, "LFU", bound=0)
    keys = np.arange(8, dtype=np.uint64)
    dest = np.zeros((8, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)
    grads = np.full((8, WIDTH), 0.5, np.float32)
    out = table.embedding_push_pull(keys, dest, keys, grads, sync=True)
    np.testing.assert_allclose(out, 1.5)


def test_cache_lookup_update_roundtrip(tmp_path):
    run_cluster(_lookup_update_roundtrip, tmp_path, n_workers=1)


def test_cache_policies(tmp_path):
    run_cluster(_policies, tmp_path, n_workers=1)


def test_cache_dedup_keys(tmp_path):
    run_cluster(_dedup_keys, tmp_path, n_workers=1)


def test_cache_staleness_propagation(tmp_path):
    run_cluster(_staleness_propagation, tmp_path, n_workers=2)


def test_cache_bounded_staleness(tmp_path):
    run_cluster(_bounded_staleness_skips_fresh_rows, tmp_path, n_workers=1)


def test_cache_push_pull(tmp_path):
    run_cluster(_push_pull_combined, tmp_path, n_workers=1)


# ---------------------------------------------------------------------------
# bounded-staleness invariants across a server restart: the replacement
# restores VALUES AND ROW VERSIONS from the continuous snapshot, so a cache
# whose lines pre-date the death keeps its contract — no value regression,
# sync traffic flows through worker failover, and later updates land once
# ---------------------------------------------------------------------------

def _cache_across_restart(client, rank, tmpdir):
    from hetu_tpu.cstable import CacheSparseTable
    client.InitTensor(18, sparse=2, length=NROWS, width=WIDTH,
                      init_type="constant", init_a=1.0)
    table = CacheSparseTable(16, NROWS, WIDTH, 18, policy="LRU", bound=0)
    table.perf_enabled(True)
    keys = np.arange(28, 36, dtype=np.uint64)  # spans both server shards
    dest = np.zeros((8, WIDTH), np.float32)
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.0)
    table.embedding_update(keys, np.full((8, WIDTH), 0.5, np.float32),
                           sync=True)  # bound=0: pushed immediately
    # wait for a snapshot covering the push on server 1
    deadline = time.time() + 30
    while client.ServerStats(1)["snapshot_updates"] < 1:
        assert time.time() < deadline, "no covering snapshot appeared"
        time.sleep(0.05)
    open(os.path.join(tmpdir, "push_done"), "w").write("ok")
    from test_ps_fault import _wait_file
    _wait_file(os.path.join(tmpdir, "killed"))
    # sync lookup rides the fast channel THROUGH the failover window; the
    # restored rows carry the pre-death update — never a regression to 1.0
    table.embedding_lookup(keys, dest, sync=True)
    np.testing.assert_allclose(dest, 1.5)
    # the server itself (bypass = raw SyncEmbedding of every row) agrees
    table.bypass()
    raw = np.zeros((8, WIDTH), np.float32)
    table.embedding_lookup(keys, raw, sync=True)
    np.testing.assert_allclose(raw, 1.5)
    table.undobypass()
    # post-restart updates land exactly once on the restored shard
    table.embedding_update(keys, np.full((8, WIDTH), 0.5, np.float32),
                           sync=True)
    table.bypass()
    table.embedding_lookup(keys, raw, sync=True)
    np.testing.assert_allclose(raw, 2.0)
    assert client.ServerStats(1)["restored_updates"] >= 1


def test_cache_bounded_staleness_across_server_restart(tmp_path):
    from test_ps_fault import _run_ha_cluster, _wait_file

    def orchestrate(ctx, env):
        _wait_file(os.path.join(env["tmpdir"], "push_done"))
        env["kill"](1)
        open(os.path.join(env["tmpdir"], "killed"), "w").write("ok")

    sup = _run_ha_cluster(_cache_across_restart, orchestrate, tmp_path)
    assert sup.respawns == 1 and sup.fatal is None
