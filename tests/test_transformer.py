"""Flagship transformer: dp/tp/sp/ep GSPMD step + ppermute GPipe pipeline.

Correctness oracle: the sharded run must match the single-device run on the
same data (f32, no dropout), and the pipeline must match the non-pipelined
forward within fp tolerance.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hetu_tpu.models import transformer as tfm
from hetu_tpu.parallel import mesh as meshlib
from hetu_tpu.parallel import pipeline as pplib


def tiny_cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
                max_seq_len=32, dtype=jnp.float32, remat=False)
    base.update(kw)
    return tfm.TransformerConfig(**base)


def make_data(cfg, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, cfg.vocab_size, (batch, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


def test_single_device_step_decreases_loss():
    cfg = tiny_cfg()
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = tfm.init_opt_state(params)
    step = tfm.make_train_step(cfg, mesh=None, lr=1e-2)
    tokens, targets = make_data(cfg)
    losses = []
    for _ in range(10):
        loss, params, opt = step(params, opt, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_dp_tp_sp_matches_single_device():
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=2, pp=1, tp=2, sp=2, ep=1)
    tokens, targets = make_data(cfg)

    params1 = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt1 = tfm.init_opt_state(params1)
    step1 = tfm.make_train_step(cfg, mesh=None, lr=1e-2)

    params8 = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(0), cfg),
                               cfg, mesh)
    opt8 = tfm.init_opt_state(params8)
    step8 = tfm.make_train_step(cfg, mesh=mesh, lr=1e-2)

    for i in range(3):
        l1, params1, opt1 = step1(params1, opt1, tokens, targets)
        l8, params8, opt8 = step8(params8, opt8, tokens, targets)
        np.testing.assert_allclose(float(l1), float(l8), rtol=2e-4,
                                   err_msg=f"step {i}")


def test_moe_ep_step_runs():
    cfg = tiny_cfg(n_experts=4, d_ff=32)
    mesh = meshlib.make_mesh(dp=2, pp=1, tp=1, sp=1, ep=4)
    params = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(1), cfg),
                              cfg, mesh)
    opt = tfm.init_opt_state(params)
    step = tfm.make_train_step(cfg, mesh=mesh, lr=1e-2)
    tokens, targets = make_data(cfg)
    losses = []
    for _ in range(6):
        loss, params, opt = step(params, opt, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_pipeline_matches_dense():
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=2, pp=4, tp=1, sp=1, ep=1)
    M, mb = 4, 4
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, cfg.vocab_size, (M, mb, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2).astype(np.int32)

    # oracle: plain step on the flat batch (same global data, lr, init)
    params1 = tfm.init_params(jax.random.PRNGKey(3), cfg)
    flat_tok = jnp.asarray(tokens.reshape(M * mb, 16))
    flat_tgt = jnp.asarray(targets.reshape(M * mb, 16))
    oracle_loss = float(tfm.loss_fn(params1, flat_tok, flat_tgt, cfg, None))

    pparams = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg, mesh)
    popt = tfm.init_opt_state(pparams)
    pstep = pplib.make_pipeline_train_step(cfg, mesh, num_microbatches=M,
                                           lr=1e-2)
    loss, pparams, popt = pstep(pparams, popt, jnp.asarray(tokens),
                                jnp.asarray(targets))
    np.testing.assert_allclose(float(loss), oracle_loss, rtol=2e-4)

    # and training progresses
    losses = [float(loss)]
    for _ in range(5):
        l, pparams, popt = pstep(pparams, popt, jnp.asarray(tokens),
                                 jnp.asarray(targets))
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_zero1_matches_replicated_and_shards_state():
    """ZeRO-1: AdamW m/v shard over dp; the step is numerically identical
    to the replicated-optimizer step and the slots are ACTUALLY smaller
    per device."""
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=4, pp=1, tp=2, sp=1, ep=1)
    tok, tgt = make_data(cfg, batch=8, seed=9)
    p0 = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(2), cfg), cfg,
                          mesh)

    base = tfm.make_train_step(cfg, mesh=mesh, lr=1e-2)
    lb, pb, ob = base(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                      tok, tgt)

    z1 = tfm.make_train_step(cfg, mesh=mesh, lr=1e-2, zero1=True)
    oz0 = tfm.shard_opt_state(tfm.init_opt_state(p0), cfg, mesh, zero1=True)
    lz, pz, oz = z1(jax.tree.map(jnp.copy, p0), oz0, tok, tgt)

    np.testing.assert_allclose(float(lz), float(lb), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(pz), jax.tree.leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(jax.tree.leaves(oz["m"]), jax.tree.leaves(ob["m"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # the slots really shard over dp: some leaf's addressable shard is
    # smaller than the global array by the dp factor
    emb_m = oz["m"]["embed"]
    assert "dp" in tuple(emb_m.sharding.spec), emb_m.sharding
    shard_rows = emb_m.addressable_shards[0].data.shape[0]
    assert shard_rows * 4 <= emb_m.shape[0] * 2, (
        shard_rows, emb_m.shape)  # dp=4 sharding (tp may co-shard axis 1)
    # second step keeps working (donated sharded state round-trips)
    lz2, _, _ = z1(pz, oz, tok, tgt)
    assert np.isfinite(float(lz2))


def test_fused_lm_ce_matches_materializing_form():
    """The fused linear+CE flagship loss (forced on) must equal the
    logits-materializing form — loss and grads — and make_train_step must
    train with it."""
    import dataclasses
    cfg_on = tiny_cfg(fused_lm_ce=True)
    cfg_off = dataclasses.replace(cfg_on, fused_lm_ce=False)
    params = tfm.init_params(jax.random.PRNGKey(5), cfg_on)
    tok, tgt = make_data(cfg_on, batch=4, seed=6)

    lf = tfm.loss_fn(params, tok, tgt, cfg_on, None)
    lo = tfm.loss_fn(params, tok, tgt, cfg_off, None)
    np.testing.assert_allclose(float(lf), float(lo), rtol=1e-5)

    gf = jax.grad(lambda p: tfm.loss_fn(p, tok, tgt, cfg_on, None))(params)
    go = jax.grad(lambda p: tfm.loss_fn(p, tok, tgt, cfg_off, None))(params)
    for k in ("head", "embed", "lnf_scale"):
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(go[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)

    step = tfm.make_train_step(cfg_on, lr=1e-2)
    opt = tfm.init_opt_state(params)
    l0, params, opt = step(params, opt, tok, tgt)
    l1, params, opt = step(params, opt, tok, tgt)
    assert float(l1) < float(l0)


def test_pipeline_dropout_matches_trunk():
    """pp2 training WITH dropout must match the single-device trunk running
    grad accumulation with the same key: the pipeline folds key(mb, global
    layer) exactly like make_train_step's fold_in(rng, mi) -> encode's
    fold_in(·, li), so losses and updated params agree step for step."""
    cfg = tiny_cfg(dropout_rate=0.25)
    mesh = meshlib.make_mesh(dp=4, pp=2, tp=1, sp=1, ep=1)
    M, mb = 2, 4
    rng = np.random.RandomState(11)
    tokens = rng.randint(0, cfg.vocab_size, (M, mb, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2).astype(np.int32)

    p0 = tfm.init_params(jax.random.PRNGKey(7), cfg)
    trunk = tfm.make_train_step(cfg, lr=1e-2, accum_steps=M)
    tparams, topt = jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0)

    pparams = pplib.init_pipeline_params(jax.random.PRNGKey(7), cfg, mesh)
    popt = tfm.init_opt_state(pparams)
    pstep = pplib.make_pipeline_train_step(cfg, mesh, num_microbatches=M,
                                           lr=1e-2)
    key = jax.random.PRNGKey(42)
    for step in range(3):
        krng = jax.random.fold_in(key, step)
        tl, tparams, topt = trunk(tparams, topt, jnp.asarray(tokens),
                                  jnp.asarray(targets), krng)
        pl, pparams, popt = pstep(pparams, popt, jnp.asarray(tokens),
                                  jnp.asarray(targets), krng)
        np.testing.assert_allclose(float(pl), float(tl), rtol=2e-4,
                                   err_msg=f"step {step}")
    # updated params agree (pipeline blocks are (pp, L/pp, ...) stacked)
    tblocks = {k: v.reshape(pparams["blocks"][k].shape)
               for k, v in tparams["blocks"].items()}
    for k in tblocks:
        np.testing.assert_allclose(np.asarray(pparams["blocks"][k]),
                                   np.asarray(tblocks[k]), atol=2e-4,
                                   err_msg=k)
    # a forgotten key fails loudly (jit arity or the explicit assert)
    with pytest.raises((AssertionError, ValueError)):
        pstep(pparams, popt, jnp.asarray(tokens), jnp.asarray(targets))


def test_1f1b_schedule_is_dependency_valid_and_stash_bounded():
    """Every stage runs M forwards + M backwards; activations/grads move
    one hop per tick (producer strictly earlier); in-flight microbatches
    per stage never exceed pp (the memory law 1F1B exists for); the
    dual-slot table keeps the tick count near M + 2(pp-1) — the masked
    lowering's per-tick fwd+bwd execution is then almost fully used."""
    for pp, M in [(2, 1), (2, 4), (4, 3), (4, 8), (8, 16)]:
        table = pplib.simulate_1f1b_schedule(pp, M)
        fwd_t = [[None] * M for _ in range(pp)]
        bwd_t = [[None] * M for _ in range(pp)]
        for t, row in enumerate(table):
            for s, (fm, bm) in enumerate(row):
                if fm is not None:
                    fwd_t[s][fm] = t
                if bm is not None:
                    bwd_t[s][bm] = t
        # dual slots keep the schedule dense: fill + M + drain, not 2M
        assert len(table) <= M + 2 * pp + 2, (pp, M, len(table))
        for s in range(pp):
            assert all(v is not None for v in fwd_t[s] + bwd_t[s])
            for m in range(M):
                if s > 0:
                    assert fwd_t[s][m] > fwd_t[s - 1][m]
                if s < pp - 1:
                    assert bwd_t[s][m] > bwd_t[s + 1][m]
                else:
                    assert bwd_t[s][m] > fwd_t[s][m]
                # single-slot receive buffers suffice: a stage consumes
                # each activation/grad no later than the tick its producer
                # sends the NEXT one (the runtime's sticky flagged
                # receives depend on this backpressure property)
                if s > 0 and m + 1 < M:
                    assert fwd_t[s][m] <= fwd_t[s - 1][m + 1]
                if s < pp - 1 and m + 1 < M:
                    assert bwd_t[s][m] <= bwd_t[s + 1][m + 1]
        stats = pplib.schedule_stats(pp, M)
        # default window 2*pp keeps both tick slots busy in steady state
        # while the stash stays O(pp) — far under GPipe's O(M)
        assert stats["1f1b"]["peak_act_stash_per_stage"] <= min(2 * pp, M)
        assert stats["gpipe"]["peak_act_stash_per_stage"] == M + pp - 1
        # the classic minimum-memory window still schedules validly
        lo = pplib.schedule_stats(pp, M, max_inflight=pp)
        assert lo["1f1b"]["peak_act_stash_per_stage"] <= min(pp, M)
    # exact tick counts: a greedy-simulator regression that loosens the
    # schedule shows up here before it shows up as lost throughput
    assert {(pp, M): pplib.schedule_stats(pp, M)["1f1b"]["ticks"]
            for pp, M in [(2, 1), (2, 4), (4, 3), (4, 8), (8, 16)]} == {
        (2, 1): 4, (2, 4): 7, (4, 3): 10, (4, 8): 15, (8, 16): 31}
    # the steady state really densifies: at M >> pp the slot bubble
    # approaches 2(pp-1)/M (measured 9.9% at pp4/M64)
    assert pplib.schedule_stats(4, 64)["1f1b"]["bubble_fraction"] < 0.12


def test_1f1b_matches_gpipe_and_dense():
    """The 1F1B step is the GPipe step's drop-in twin: same loss as the
    dense oracle on the flat batch, same losses as GPipe across steps,
    and gradient-for-gradient equality with jax.grad(GPipe loss) —
    grads, not post-AdamW params, are the noise-free place to pin."""
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=2, pp=4, tp=1, sp=1, ep=1)
    M, mb = 4, 4
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, cfg.vocab_size, (M, mb, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2).astype(np.int32)

    params1 = tfm.init_params(jax.random.PRNGKey(3), cfg)
    flat_tok = jnp.asarray(tokens.reshape(M * mb, 16))
    flat_tgt = jnp.asarray(targets.reshape(M * mb, 16))
    oracle_loss = float(tfm.loss_fn(params1, flat_tok, flat_tgt, cfg, None))

    def run(make):
        p = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg, mesh)
        o = tfm.init_opt_state(p)
        step = make(cfg, mesh, num_microbatches=M, lr=1e-2)
        losses = []
        for _ in range(3):
            l, p, o = step(p, o, jnp.asarray(tokens), jnp.asarray(targets))
            losses.append(float(l))
        return losses

    g_losses = run(pplib.make_pipeline_train_step)
    f_losses = run(pplib.make_pipeline_train_step_1f1b)

    np.testing.assert_allclose(f_losses[0], oracle_loss, rtol=2e-4)
    np.testing.assert_allclose(f_losses, g_losses, rtol=2e-5)

    # grad-level parity: the 1F1B hand-rolled backward equals
    # jax.grad(GPipe fwd_loss) exactly (this is the noise-free pin —
    # params-after-AdamW comparisons amplify last-bit grad differences to
    # ~lr near sign flips, so grads are the right place to assert)
    p = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg, mesh)
    gstep = pplib.make_pipeline_train_step(cfg, mesh, num_microbatches=M,
                                           lr=1e-2)
    fstep = pplib.make_pipeline_train_step_1f1b(cfg, mesh,
                                                num_microbatches=M, lr=1e-2)
    g_ref = jax.grad(gstep.fwd_loss)(p, jnp.asarray(tokens),
                                     jnp.asarray(targets))
    _, g_f1b = fstep.fwd_bwd(p, jnp.asarray(tokens), jnp.asarray(targets))
    flat_ref, _ = jax.tree.flatten_with_path(g_ref)
    flat_f1b = dict(jax.tree.flatten_with_path(g_f1b)[0])
    for path, ref in flat_ref:
        got = flat_f1b[path]
        scale = float(np.max(np.abs(np.asarray(ref)))) or 1.0
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=5e-6 * max(scale, 1.0), rtol=2e-4,
                                   err_msg=str(path))


def test_1f1b_cond_predication_matches_and_guards_model_axes():
    """The opt-in cond lowering (idle ticks free) matches the masked
    default on a validated dp x pp config, and refuses model axes
    outright (GSPMD collectives inside divergent branches deadlock)."""
    cfg = tiny_cfg(max_seq_len=16)   # T == max_seq_len: no pos reshard
    mesh = meshlib.make_mesh(dp=2, pp=4)
    M = 4
    rng = np.random.RandomState(2)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (M, 4, 16)).astype(np.int32))
    targets = jnp.roll(tokens, -1, axis=2)
    p = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg, mesh)
    masked = pplib.make_pipeline_train_step_1f1b(cfg, mesh,
                                                 num_microbatches=M)
    cond = pplib.make_pipeline_train_step_1f1b(cfg, mesh,
                                               num_microbatches=M,
                                               predication="cond")
    lm, _ = masked.fwd_bwd(p, tokens, targets)
    lc, _ = cond.fwd_bwd(p, tokens, targets)
    np.testing.assert_allclose(float(lc), float(lm), rtol=1e-6)

    with pytest.raises(AssertionError, match="cond"):
        pplib.make_pipeline_train_step_1f1b(
            cfg, meshlib.make_mesh(dp=2, pp=2, tp=2),
            num_microbatches=M, predication="cond")

    # the pos-table reshard deadlock (max_seq_len > T) is refused at
    # trace time instead of hanging at runtime
    cfg32 = tiny_cfg()   # max_seq_len 32 > T 16
    bad = pplib.make_pipeline_train_step_1f1b(cfg32, mesh,
                                              num_microbatches=M,
                                              predication="cond")
    p32 = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg32, mesh)
    with pytest.raises(AssertionError, match="max_seq_len"):
        bad.fwd_bwd(p32, tokens, targets)


def test_1f1b_grads_match_gpipe_on_tp_mesh():
    """With tp in the mesh the 1F1B step runs its MASKED lowering (cond
    branches would put GSPMD's tp collectives on divergent paths); grads
    must still equal jax.grad of the GPipe loss."""
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=2, pp=2, tp=2, sp=1, ep=1)
    M, mb = 3, 4
    rng = np.random.RandomState(5)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (M, mb, 16)).astype(np.int32))
    targets = jnp.roll(tokens, -1, axis=2)
    p = pplib.init_pipeline_params(jax.random.PRNGKey(3), cfg, mesh)
    gstep = pplib.make_pipeline_train_step(cfg, mesh, num_microbatches=M,
                                           lr=1e-2)
    fstep = pplib.make_pipeline_train_step_1f1b(cfg, mesh,
                                                num_microbatches=M, lr=1e-2)
    g_ref = jax.grad(gstep.fwd_loss)(p, tokens, targets)
    loss, g_f1b = fstep.fwd_bwd(p, tokens, targets)
    assert np.isfinite(float(loss))
    flat_f1b = dict(jax.tree.flatten_with_path(g_f1b)[0])
    for path, ref in jax.tree.flatten_with_path(g_ref)[0]:
        scale = float(np.max(np.abs(np.asarray(ref)))) or 1.0
        np.testing.assert_allclose(np.asarray(flat_f1b[path]),
                                   np.asarray(ref),
                                   atol=5e-6 * max(scale, 1.0), rtol=2e-4,
                                   err_msg=str(path))


@pytest.mark.parametrize("make", [pplib.make_pipeline_train_step,
                                  pplib.make_pipeline_train_step_1f1b],
                         ids=["gpipe", "1f1b"])
def test_pipeline_zero1_matches_replicated_and_shards_state(make):
    """ZeRO-1 on the pipeline steps: same grads -> same update (the
    trunk's zero1 recipe applied to pp-stacked params), slots genuinely
    dp-sharded, donated sharded state round-trips a second step."""
    cfg = tiny_cfg()
    mesh = meshlib.make_mesh(dp=4, pp=2, tp=1, sp=1, ep=1)
    M, mb = 2, 4
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (M, mb, 16)).astype(np.int32))
    targets = jnp.roll(tokens, -1, axis=2)
    p0 = pplib.init_pipeline_params(jax.random.PRNGKey(5), cfg, mesh)

    base = make(cfg, mesh, num_microbatches=M, lr=1e-2)
    lb, pb, ob = base(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                      tokens, targets)

    z1 = make(cfg, mesh, num_microbatches=M, lr=1e-2, zero1=True)
    oz0 = pplib.shard_pipeline_opt_state(tfm.init_opt_state(p0), cfg, mesh,
                                         zero1=True)
    lz, pz, oz = z1(jax.tree.map(jnp.copy, p0), oz0, tokens, targets)

    np.testing.assert_allclose(float(lz), float(lb), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(pz), jax.tree.leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(jax.tree.leaves(oz["m"]), jax.tree.leaves(ob["m"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # the slots really shard over dp (embed m: replicated param, dp slot)
    emb_m = oz["m"]["embed"]
    assert "dp" in tuple(emb_m.sharding.spec), emb_m.sharding
    shard_rows = emb_m.addressable_shards[0].data.shape[0]
    assert shard_rows * 4 == emb_m.shape[0], (shard_rows, emb_m.shape)
    # second step keeps working (donated sharded state round-trips)
    lz2, _, _ = z1(pz, oz, tokens, targets)
    assert np.isfinite(float(lz2))


def test_1f1b_dropout_matches_gpipe():
    """Dropout keys are per (microbatch, global layer) in both schedules,
    so 1F1B with dropout matches GPipe loss- and param-wise step for
    step (the backward recompute re-draws the identical masks)."""
    cfg = tiny_cfg(dropout_rate=0.25)
    mesh = meshlib.make_mesh(dp=4, pp=2, tp=1, sp=1, ep=1)
    M, mb = 2, 4
    rng = np.random.RandomState(11)
    tokens = rng.randint(0, cfg.vocab_size, (M, mb, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2).astype(np.int32)

    def run(make):
        p = pplib.init_pipeline_params(jax.random.PRNGKey(7), cfg, mesh)
        o = tfm.init_opt_state(p)
        step = make(cfg, mesh, num_microbatches=M, lr=1e-2)
        key = jax.random.PRNGKey(42)
        losses = []
        for i in range(3):
            l, p, o = step(p, o, jnp.asarray(tokens), jnp.asarray(targets),
                           jax.random.fold_in(key, i))
            losses.append(float(l))
        return losses, p

    g_losses, g_params = run(pplib.make_pipeline_train_step)
    f_losses, f_params = run(pplib.make_pipeline_train_step_1f1b)
    np.testing.assert_allclose(f_losses, g_losses, rtol=2e-5)
    for k in f_params["blocks"]:
        np.testing.assert_allclose(np.asarray(f_params["blocks"][k]),
                                   np.asarray(g_params["blocks"][k]),
                                   atol=1e-5, err_msg=k)


def test_pipeline_with_moe_and_remat():
    """pp x ep x dp with remat — the combination that exercises pcast on
    every scan carry in the manual region."""
    cfg = tiny_cfg(n_experts=2, d_ff=32, remat=True)
    mesh = meshlib.make_mesh(dp=2, pp=2, tp=1, sp=1, ep=2)
    M, mb = 4, 4
    rng = np.random.RandomState(5)
    tokens = rng.randint(0, cfg.vocab_size, (M, mb, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=2).astype(np.int32)
    pparams = pplib.init_pipeline_params(jax.random.PRNGKey(4), cfg, mesh)
    popt = tfm.init_opt_state(pparams)
    pstep = pplib.make_pipeline_train_step(cfg, mesh, num_microbatches=M, lr=1e-2)
    losses = []
    for _ in range(4):
        l, pparams, popt = pstep(pparams, popt, jnp.asarray(tokens),
                                 jnp.asarray(targets))
        losses.append(float(l))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


def test_grad_accumulation_matches_big_batch():
    """accum_steps=4 over (4, 2, T) microbatches == one batch of 8 — the
    scan-accumulated grads and the big-batch grads drive identical updates
    (mean loss is linear in the batch)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=8,
                                dtype=jnp.float32, remat=False)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 64, (8, 8)), jnp.int32)
    tgt = jnp.roll(tok, -1, 1)

    big = tfm.make_train_step(cfg, lr=1e-2)
    p0 = tfm.init_params(jax.random.PRNGKey(0), cfg)
    loss_a, pa, _ = big(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                        tok, tgt)

    acc = tfm.make_train_step(cfg, lr=1e-2, accum_steps=4)
    loss_b, pb, _ = acc(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                        tok.reshape(4, 2, 8), tgt.reshape(4, 2, 8))

    assert float(loss_a) == __import__("pytest").approx(float(loss_b),
                                                        rel=1e-5)
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_dropout_trains_and_eval_is_deterministic():
    """cfg.dropout_rate > 0: the step takes a dropout_rng; same key -> same
    loss, different keys -> different losses; eval (no rng) is
    deterministic and ignores the rate; rate=0 path keeps the historical
    4-arg signature."""
    cfg = tiny_cfg(n_layers=2, max_seq_len=8, remat=True, dropout_rate=0.3)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 64, (4, 8)), jnp.int32)
    tgt = jnp.roll(tok, -1, 1)
    p0 = tfm.init_params(jax.random.PRNGKey(0), cfg)

    step = tfm.make_train_step(cfg, lr=1e-2)
    la, _, _ = step(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                    tok, tgt, jax.random.PRNGKey(1))
    lb, _, _ = step(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                    tok, tgt, jax.random.PRNGKey(1))
    lc, _, _ = step(jax.tree.map(jnp.copy, p0), tfm.init_opt_state(p0),
                    tok, tgt, jax.random.PRNGKey(2))
    assert float(la) == float(lb)          # same mask
    assert float(la) != float(lc)          # different mask

    # eval: no rng -> deterministic, identical to the rate=0 model
    e1, _ = tfm.forward(p0, tok, cfg)
    e2, _ = tfm.forward(p0, tok, tiny_cfg(n_layers=2, max_seq_len=8,
                                          remat=True))
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2), atol=1e-6)

    # a short dropout-on training run still learns
    params, opt = p0, tfm.init_opt_state(p0)
    key = jax.random.PRNGKey(3)
    first = None
    for i in range(30):
        key, sub = jax.random.split(key)
        loss, params, opt = step(params, opt, tok, tgt, sub)
        if i == 0:
            first = float(loss)
    assert float(loss) < first
