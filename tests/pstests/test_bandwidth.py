"""PS push/pull bandwidth microbench (reference
``tests/pstests/test_bandwidth.py`` — prints MB/s per PSF against a local
cluster). Run standalone:

    python tests/pstests/test_bandwidth.py [--nitem 512] [--item-len 4096]

or via pytest (small sizes, asserts only sanity, prints the numbers).
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def run_bandwidth(client, nitem=512, item_len=4096, sparse_rows=256,
                  iters=10, report=print):
    """Returns {psf_name: MB/s} for dense push/pull/DDPushPull and sparse
    pull/push against the connected cluster."""
    n = nitem * item_len
    mb = n * 4 / 1e6
    out = {}

    client.InitTensor(900, sparse=False, length=n, width=1,
                      init_type="constant", init_a=0.5)
    buf = np.empty(n, np.float32)
    grad = np.random.rand(n).astype(np.float32)

    t0 = time.time()
    for _ in range(iters):
        client.Push(900, grad)
        client.Wait(900)
    out["dense_push"] = mb * iters / (time.time() - t0)

    t0 = time.time()
    for _ in range(iters):
        client.Pull(900, buf)
        client.Wait(900)
    out["dense_pull"] = mb * iters / (time.time() - t0)

    t0 = time.time()
    for _ in range(iters):
        client.DDPushPull(900, grad, buf)
        client.Wait(900)
    out["dd_push_pull"] = 2 * mb * iters / (time.time() - t0)

    client.InitTensor(901, sparse=True, length=nitem, width=item_len,
                      init_type="normal", init_a=0.0, init_b=0.1)
    idx = np.random.randint(0, nitem, sparse_rows).astype(np.int64)
    rows = np.empty((sparse_rows, item_len), np.float32)
    smb = sparse_rows * item_len * 4 / 1e6
    t0 = time.time()
    for _ in range(iters):
        client.SparsePull(901, idx, rows)
        client.Wait(901)
    out["sparse_pull"] = smb * iters / (time.time() - t0)

    t0 = time.time()
    for _ in range(iters):
        client.SparsePush(901, idx, rows)
        client.Wait(901)
    out["sparse_push"] = smb * iters / (time.time() - t0)

    for name, rate in out.items():
        report(f"[bandwidth] {name}: {rate:,.1f} MB/s")
    return out


def _worker(client, rank, tmpdir):
    rates = run_bandwidth(client)
    assert all(r > 1.0 for r in rates.values()), rates  # sanity: >1 MB/s
    client.BarrierWorker()


def test_ps_bandwidth(tmp_path):
    from test_ps import run_cluster
    run_cluster(_worker, tmp_path, n_workers=1, timeout=300)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nitem", type=int, default=2000)
    ap.add_argument("--item-len", type=int, default=10000)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    def body(client, rank, tmpdir):
        run_bandwidth(client, nitem=args.nitem, item_len=args.item_len,
                      iters=args.iters)
        client.BarrierWorker()

    import tempfile
    from test_ps import run_cluster
    run_cluster(body, tempfile.mkdtemp(), n_workers=1, timeout=600)


if __name__ == "__main__":
    main()
