"""Fast-channel latency under bulk load (the repo's answer to the reference's
priority p3 van, ps-lite/src/p3_van.h): small pulls ride a separate TCP
stream, so a continuous stream of multi-megabyte pushes must NOT
head-of-line-block them. On a single shared connection the small-pull
latency would jump to roughly the bulk transfer time (tens of ms per 64MB on
loopback); with the split channels it stays within the normal contention
envelope.
"""
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

BIG_N = 16 * 1024 * 1024     # 64 MB of f32 per push
SMALL_ROWS = 4
WIDTH = 16


def _median_pull_ms(client, idx, rows, n=30):
    lat = []
    for _ in range(n):
        t0 = time.perf_counter()
        client.SparsePull(911, idx, rows)
        client.Wait(911)
        lat.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(lat))


def _worker(client, rank, tmpdir):
    client.InitTensor(910, sparse=False, length=BIG_N, width=1,
                      init_type="constant", init_a=0.0)
    client.InitTensor(911, sparse=True, length=64, width=WIDTH,
                      init_type="normal", init_a=0.0, init_b=0.1)
    big = np.random.rand(BIG_N).astype(np.float32)
    idx = np.arange(SMALL_ROWS, dtype=np.int64)
    rows = np.empty((SMALL_ROWS, WIDTH), np.float32)

    # warm both paths, then measure the unloaded baseline
    client.Push(910, big)
    client.Wait(910)
    baseline = _median_pull_ms(client, idx, rows)

    # continuous bulk pushes on a background thread (one in flight at a
    # time: the bulk socket is saturated, the pool is not)
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            client.Push(910, big)
            client.Wait(910)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    time.sleep(0.3)   # ensure pushes are streaming
    try:
        loaded = _median_pull_ms(client, idx, rows)
    finally:
        stop.set()
        t.join(timeout=30)
    client.BarrierWorker()
    print(f"[priority] small-pull median: baseline {baseline:.3f} ms, "
          f"under 64MB-push load {loaded:.3f} ms")
    # the fast channel keeps the pull out of the bulk stream: allow generous
    # scheduler/CPU contention headroom (loaded CI hosts), but not the
    # ~30-60ms transfer-time stalls a shared single connection exhibits —
    # that failure mode overshoots this bound by an order of magnitude.
    assert loaded < max(5.0 * baseline, baseline + 10.0), (baseline, loaded)


def test_fast_channel_latency_under_bulk_load(tmp_path):
    from test_ps import run_cluster
    run_cluster(_worker, tmp_path, n_workers=1, n_servers=1, timeout=300)
