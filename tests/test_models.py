"""Model-zoo smoke tests: every examples/cnn model builds, trains two steps,
and produces a finite decreasing-capable loss (reference runs these via
examples/cnn/scripts/*.sh)."""
import os
import sys

import numpy as np
import pytest

import hetu_tpu as ht


from conftest import import_example_models as _import_example_models


models = None


def setup_module():
    global models
    models = _import_example_models("cnn")


def _train_two_steps(model_fn, x_shape, num_class=10, lr=0.01, **kwargs):
    rng = np.random.RandomState(0)
    xv = rng.randn(8, *x_shape).astype(np.float32)
    yv = np.eye(num_class, dtype=np.float32)[rng.randint(0, num_class, 8)]
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y_", trainable=False)
    loss, y = model_fn(x, y_, num_class, **kwargs)
    opt = ht.optim.SGDOptimizer(lr)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, y, train_op]}, ctx=ht.cpu(0))
    w_node = ex.param_nodes[0]
    w_before = np.asarray(ex.state["params"][id(w_node)]).copy()
    l0 = float(ex.run("train", feed_dict={x: xv, y_: yv})[0].asnumpy())
    l1 = float(ex.run("train", feed_dict={x: xv, y_: yv})[0].asnumpy())
    assert np.isfinite(l0) and np.isfinite(l1), (l0, l1)
    w_after = np.asarray(ex.state["params"][id(w_node)])
    assert not np.allclose(w_before, w_after), "parameters did not update"
    return l0, l1


def test_mlp():
    _train_two_steps(models.mlp, (3072,), input_dim=3072)


def test_logreg():
    _train_two_steps(models.logreg, (784,), input_dim=784)


def test_cnn_3_layers():
    _train_two_steps(models.cnn_3_layers, (1, 28, 28))


def test_lenet():
    _train_two_steps(models.lenet, (1, 28, 28))


def test_alexnet():
    _train_two_steps(models.alexnet, (3, 32, 32), lr=1e-4)


def test_resnet18():
    _train_two_steps(models.resnet18, (3, 32, 32))


@pytest.mark.slow
def test_resnet34():
    _train_two_steps(models.resnet34, (3, 32, 32))


@pytest.mark.slow
def test_vgg16():
    _train_two_steps(models.vgg16, (3, 32, 32))


def test_rnn():
    _train_two_steps(models.rnn, (784,))


def test_lstm():
    _train_two_steps(models.lstm, (784,))


def test_vit():
    l0, l1 = _train_two_steps(models.vit, (3, 32, 32), lr=1e-3, batch=8)
    assert l1 < l0 * 1.5  # attention model is stable from step one
