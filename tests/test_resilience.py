"""Training supervision layer (hetu_tpu/resilience.py): anomaly detection
with bit-identical NaN-skip and rollback, preemption-safe emergency
checkpointing with exact-step resume, the hang watchdog's stack dump, and
supervise() restart-with-backoff — every path driven by the deterministic
fault-injection hook on the CPU backend.
"""
import io
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import resilience as rs
from hetu_tpu.checkpoint import TrainCheckpointer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared tiny training job (graph API, deterministic)
# ---------------------------------------------------------------------------

def build_job(seed=0, anomaly_guard=True, shuffle=True):
    """2-layer softmax regression over a dataloader; returns (executor,
    feed-free run closure). Deterministic: fixed seeds everywhere."""
    rng = np.random.RandomState(7)
    data_x = rng.randn(64, 6).astype(np.float32)
    data_y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)]
    x = ht.dataloader_op([ht.Dataloader(data_x, 16, "train",
                                        shuffle=shuffle, seed=11)])
    y_ = ht.dataloader_op([ht.Dataloader(data_y, 16, "train",
                                         shuffle=shuffle, seed=11)])
    w = ht.init.random_normal((6, 3), stddev=0.5, name="w")
    b = ht.init.zeros((3,), name="b")
    h = ht.matmul_op(x, w)
    logits = h + ht.broadcastto_op(b, h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=seed,
                     anomaly_guard=anomaly_guard)
    return ex


def params_snapshot(ex):
    return {n.name: np.asarray(ex.state["params"][id(n)]).copy()
            for n in ex.param_nodes}


# ---------------------------------------------------------------------------
# fault injection: spec parsing + gating
# ---------------------------------------------------------------------------

def test_fault_spec_parsing():
    fi = rs.FaultInjector("nan_grads@3, stall@5:2.5, sigterm@7")
    assert fi.fires("nan_grads", 3)
    assert not fi.fires("nan_grads", 3)      # one-shot
    assert not fi.fires("nan_grads", 4)
    e = fi.take("stall", 5)
    assert e["arg"] == 2.5
    with pytest.raises(ValueError):
        rs.FaultInjector("teleport@3")
    with pytest.raises(ValueError):
        rs.FaultInjector("nan_grads3")


def test_fault_env_is_inert_without_test_mode(monkeypatch):
    monkeypatch.setenv("HETU_FAULT_SPEC", "nan_grads@0")
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    assert rs.FaultInjector.from_env() is None      # leaked spec: inert
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    assert rs.FaultInjector.from_env() is not None


def test_ps_kill_hook_gated_and_bounds_checked(monkeypatch):
    from hetu_tpu.ps.local_cluster import resolve_test_kill_index
    monkeypatch.setenv("HETU_PS_TEST_KILL_SERVER", "1")
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    assert resolve_test_kill_index(2) is None        # leaked var: inert
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    assert resolve_test_kill_index(2) == 1
    with pytest.raises(ValueError):
        resolve_test_kill_index(1)                   # out of range


def test_ps_kill_fault_kind_parses_and_is_bounds_checked():
    from hetu_tpu.ps import local_cluster as lc
    fi = rs.FaultInjector("ps_kill@4:1")
    e = fi.take("ps_kill", 4)
    assert e is not None and e["arg"] == 1.0
    # no live local_cluster in this process: firing is a hard error, never
    # a silent no-op (the fault test would be meaningless)
    fi2 = rs.FaultInjector("ps_kill@0")
    with pytest.raises(RuntimeError, match="no live local_cluster"):
        fi2.inject_host(0)
    # bounds check against a (fake) live registry, like
    # resolve_test_kill_index: the scheduler slot must be unreachable
    lc._LIVE.update({"n_servers": 2, "servers": {}, "supervisor": None})
    try:
        fi3 = rs.FaultInjector("ps_kill@0:5")
        with pytest.raises(ValueError, match="out of range"):
            fi3.inject_host(0)
    finally:
        lc._LIVE.clear()


def test_ps_supervisor_respawn_budget_records_fatal():
    """PSSupervisor exhausts its bounded respawn budget and records a fatal
    diagnostic instead of looping (first-failure preservation upstream)."""
    from hetu_tpu.ps.supervisor import PSSupervisor
    spawned = []
    sup = PSSupervisor("127.0.0.1", 1, n_servers=1,
                       respawn=lambda i: spawned.append(i), max_respawns=1)
    sup._seen_alive[0] = True
    sup._respawn(0)                       # consumes the budget
    assert spawned == [0] and sup.respawns == 1 and sup.fatal is None
    sup._seen_alive[0] = True
    sup._respawn(0)                       # budget exhausted -> fatal, no spawn
    assert spawned == [0]
    assert sup.fatal is not None and "budget" in sup.fatal


def test_pipeline_inflight_window_rejects_zero():
    from hetu_tpu.parallel.pipeline import resolve_inflight_window
    assert resolve_inflight_window(4) == 8           # default 2*pp
    assert resolve_inflight_window(4, 3) == 3        # explicit wins
    with pytest.raises(ValueError):
        resolve_inflight_window(4, 0)                # no longer 'or'-swallowed
    with pytest.raises(ValueError):
        resolve_inflight_window(4, -1)


# ---------------------------------------------------------------------------
# anomaly detection
# ---------------------------------------------------------------------------

def test_nan_step_leaves_params_bit_identical():
    ex = build_job()
    sup = ex.attach_supervisor(
        rs.Supervisor(fault_injector=rs.FaultInjector("nan_grads@2")))
    with sup:
        for step in range(5):
            pre = params_snapshot(ex)
            (lv, _) = ex.run("train")
            assert np.isfinite(float(lv.asnumpy()))
            post = params_snapshot(ex)
            if step == 2:
                for k in pre:       # bit-identical, not just close
                    np.testing.assert_array_equal(pre[k], post[k])
            else:
                assert any((pre[k] != post[k]).any() for k in pre)
    assert ex.state["anomaly_total"] == 1
    assert ex.state["anomaly_streak"] == 0          # reset by finite step 3
    assert ex.state["last_step_finite"] is True
    assert sup.anomaly.total == 1


def test_anomaly_rollback_after_k_consecutive(tmp_path):
    ex = build_job()
    with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
        sup = ex.attach_supervisor(rs.Supervisor(
            ckptr=ck, ckpt_every=1,
            anomaly=rs.AnomalyPolicy(max_consecutive=2),
            fault_injector=rs.FaultInjector("nan_grads@2,nan_grads@3")))
        with sup:
            ex.run("train")                      # step 0, ckpt 0
            ex.run("train")                      # step 1, ckpt 1
            post1 = params_snapshot(ex)
            ex.run("train")                      # step 2: anomaly, skip
            assert ex.state["step"] == 3
            ex.run("train")                      # step 3: anomaly -> rollback
            # rolled back to checkpoint 1: next step to run is 2 again
            assert ex.state["step"] == 2
            for k, v in params_snapshot(ex).items():
                np.testing.assert_array_equal(v, post1[k])
            assert sup.anomaly.rollbacks == 1
            assert sup.anomaly.streak == 0
            # training continues from the restored state
            lv, _ = ex.run("train")              # step 2 re-run, finite now
            assert np.isfinite(float(lv.asnumpy()))
            assert ex.state["step"] == 3


def test_rollback_budget_stops_deterministic_nan_livelock(tmp_path):
    """Restore is deterministic (params AND dataloader position), so a NaN
    whose cause survives restore replays forever — the rollback budget
    converts the livelock into an error supervise() can escalate."""
    ex = build_job()
    with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
        # step 1 NaNs on EVERY replay (duplicate one-shot entries): the
        # deterministic-divergence shape, where rollback cannot help
        spec = ",".join(["nan_grads@1"] * 4)
        sup = ex.attach_supervisor(rs.Supervisor(
            ckptr=ck, ckpt_every=1,
            anomaly=rs.AnomalyPolicy(max_consecutive=1, max_rollbacks=2),
            fault_injector=rs.FaultInjector(spec)))
        with sup:
            ex.run("train")                       # step 0: finite, ckpt 0
            for _ in range(2):
                ex.run("train")                   # step 1 NaN -> rollback
                assert ex.state["step"] == 1      # replayed from ckpt 0
            with pytest.raises(RuntimeError, match="max_rollbacks"):
                ex.run("train")
        assert sup.anomaly.rollbacks == 3


def test_rollback_without_checkpoint_raises():
    ex = build_job()
    sup = ex.attach_supervisor(rs.Supervisor(
        anomaly=rs.AnomalyPolicy(max_consecutive=1),
        fault_injector=rs.FaultInjector("nan_grads@0")))
    with sup, pytest.raises(RuntimeError, match="no checkpointer"):
        ex.run("train")


def test_loss_scaler_backoff_and_growth():
    s = rs.LossScaler(init_scale=8.0, backoff=0.5, growth=2.0,
                      growth_interval=3, min_scale=1.0, max_scale=16.0)
    s.update(False)
    assert s.scale == 4.0
    for _ in range(3):
        s.update(True)
    assert s.scale == 8.0
    for _ in range(6):
        s.update(True)
    assert s.scale == 16.0                       # capped at max
    policy = rs.AnomalyPolicy(max_consecutive=3, loss_scaler=s)
    policy.note(False)
    assert s.scale == 8.0                        # policy drives the scaler


# ---------------------------------------------------------------------------
# preemption -> emergency checkpoint -> exact resume
# ---------------------------------------------------------------------------

def run_to_completion(n_steps):
    """Uninterrupted baseline: the exact loss trajectory a supervised run
    (with a preemption in the middle) must reproduce."""
    ex = build_job()
    losses = []
    for _ in range(n_steps):
        lv, _ = ex.run("train")
        losses.append(float(lv.asnumpy()))
    return losses


def test_sigterm_emergency_checkpoint_then_exact_resume(tmp_path):
    N = 8
    baseline = run_to_completion(N)
    losses = []

    def make_loop(faults):
        def loop_fn(state, start_step):
            ex = build_job()
            sup = ex.attach_supervisor(rs.Supervisor(
                ckptr=ck, preemption=rs.PreemptionHandler(),
                fault_injector=faults))
            with sup:
                if state is not None:
                    rs.load_executor_state(ex, state)
                    assert ex.state["step"] == start_step
                for _ in range(start_step, N):
                    lv, _ = ex.run("train")
                    losses.append(float(lv.asnumpy()))
            return losses
        return loop_fn

    with TrainCheckpointer(tmp_path / "ck", keep=2) as ck:
        # a real SIGTERM lands at step 3's boundary: emergency checkpoint,
        # then clean exit with the distinct preemption code
        with pytest.raises(SystemExit) as ei:
            rs.supervise(make_loop(rs.FaultInjector("sigterm@3")), ck)
        assert ei.value.code == rs.EXIT_PREEMPTED
        # step 3 RAN (its state committed + checkpointed) but Preempted
        # aborts run()'s return, so its loss value is consumed by the exit
        assert len(losses) == 3
        assert ck.latest_step() == 3             # emergency ckpt at step 3

        # second supervise invocation (the restarted process): resumes at
        # the exact next step and reproduces the uninterrupted trajectory
        out = rs.supervise(make_loop(None), ck)
    assert out is losses and len(losses) == N - 1
    np.testing.assert_array_equal(np.float64(losses),
                                  np.float64(baseline[:3] + baseline[4:]))


def test_sigterm_preempts_even_when_periodic_ckpt_hits_same_step(tmp_path):
    """Regression (found driving the real script): with ckpt_every aligned
    so the periodic save lands on the preempted step, the emergency save
    used to collide (orbax StepAlreadyExistsError) and the error swallowed
    the Preempted exit."""
    ex = build_job()
    with TrainCheckpointer(tmp_path / "ck", keep=3) as ck:
        sup = ex.attach_supervisor(rs.Supervisor(
            ckptr=ck, ckpt_every=2, preemption=rs.PreemptionHandler(),
            fault_injector=rs.FaultInjector("sigterm@5")))
        with sup, pytest.raises(rs.Preempted):
            for _ in range(8):
                ex.run("train")
        assert ck.latest_step() == 5


def test_save_step_force_overwrites_same_step(tmp_path):
    with TrainCheckpointer(tmp_path / "ck", keep=3) as ck:
        ck.save_step(4, {"x": np.asarray(1.0, np.float32)})
        ck.save_step(4, {"x": np.asarray(9.0, np.float32)}, force=True)
        state, step = ck.restore_latest()
        assert step == 4 and float(state["x"]) == 9.0


def test_preemption_handler_flag_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    h = rs.PreemptionHandler()
    with h:
        assert not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested
        assert h.should_stop()           # single-process: local flag
        assert h.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev


def test_any_process_flag_single_process():
    from hetu_tpu.parallel import multihost
    assert multihost.any_process_flag(True) is True
    assert multihost.any_process_flag(False) is False


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

def test_watchdog_beats_keep_it_quiet_then_timeout_fires():
    fired = threading.Event()
    buf = io.StringIO()
    wd = rs.Watchdog(2.0, on_timeout=fired.set, stream=buf, poll_s=0.05)
    with wd:
        for _ in range(5):
            wd.beat(phase="train", step=4)
            time.sleep(0.2)
        assert not fired.is_set()        # beats inside deadline: quiet
        deadline = time.time() + 30
        while not fired.is_set() and time.time() < deadline:
            time.sleep(0.1)
    assert fired.is_set()
    dump = buf.getvalue()
    assert "phase='train' step=4" in dump
    assert "hetu-watchdog" in dump        # its own thread is in the dump
    assert "MainThread" in dump           # ... and the hung main thread


def test_injected_stall_trips_watchdog_with_stack_dump(tmp_path):
    """Acceptance path: a stalled training step aborts with EXIT_WATCHDOG
    and a stack dump on stderr instead of hanging (child process — the
    watchdog's real abort is os._exit)."""
    script = textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import numpy as np
        import hetu_tpu as ht
        from hetu_tpu import resilience as rs

        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        w = ht.init.random_normal((4, 2), stddev=0.5, name="w")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)
        sup = ex.attach_supervisor(rs.Supervisor(
            watchdog=rs.Watchdog(2.0, poll_s=0.1),
            fault_injector=rs.FaultInjector("stall@2:600")))
        rng = np.random.RandomState(0)
        bx = rng.randn(8, 4).astype(np.float32)
        by = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        with sup:
            for step in range(5):
                ex.run("train", feed_dict={x: bx, y_: by})
                print("STEP_DONE", step, flush=True)
        print("FINISHED", flush=True)   # must never be reached
    """ % REPO)
    p = tmp_path / "stall_job.py"
    p.write_text(script)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    proc = subprocess.run([sys.executable, str(p)], capture_output=True,
                          text=True, timeout=240, env=env, cwd=str(tmp_path))
    assert proc.returncode == rs.EXIT_WATCHDOG, (proc.stdout, proc.stderr)
    assert "STEP_DONE 1" in proc.stdout
    assert "FINISHED" not in proc.stdout
    assert "hetu watchdog: no progress" in proc.stderr
    assert "pre_step" in proc.stderr              # last-known phase
    assert "inject_host" in proc.stderr           # the stalled frame is named
    assert "MainThread" in proc.stderr


# ---------------------------------------------------------------------------
# PS server death inside a supervised training loop (end to end): the
# ps_kill fault SIGKILLs one of two live servers mid-run; continuous
# snapshots + PSSupervisor respawn + worker failover absorb it WITHOUT a
# training-loop restart (child process — local_cluster claims the worker
# role via os.environ, which must not leak into this test process)
# ---------------------------------------------------------------------------

def test_supervised_training_survives_ps_server_kill(tmp_path):
    script = textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        os.environ.update({
            "HETU_TEST_MODE": "1",
            "HETU_FAULT_SPEC": "ps_kill@6:1",
            # tight knobs: death detected + recovered in seconds
            "DMLC_PS_RECV_TIMEOUT_MS": "2000",
            "DMLC_PS_MAX_RETRY": "2",
            "DMLC_PS_HEARTBEAT_MS": "300",
            "DMLC_PS_HEARTBEAT_TIMEOUT_MS": "1500",
            "DMLC_PS_FAILOVER_DEADLINE_MS": "60000",
            "DMLC_PS_FAILOVER_POLL_MS": "200",
        })
        import numpy as np
        from hetu_tpu.ps.local_cluster import local_cluster, get_live_cluster

        with local_cluster(n_servers=2, n_workers=1, ha=True,
                           snapshot_ms=200, max_respawns=2):
            import hetu_tpu as ht
            from hetu_tpu import resilience as rs
            x = ht.Variable(name="x", trainable=False)
            y_ = ht.Variable(name="y_", trainable=False)
            w = ht.init.random_normal((4, 2), stddev=0.5, name="w")
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(x, w), y_), [0])
            train_op = ht.optim.SGDOptimizer(0.2).minimize(loss)
            ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                             comm_mode="PS", seed=0)
            sup = ex.attach_supervisor(rs.Supervisor())  # env fault spec
            rng = np.random.RandomState(0)
            bx = rng.randn(16, 4).astype(np.float32)
            by = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
            losses = []
            with sup:
                for step in range(12):   # server 1 dies at step 6's boundary
                    lv, _ = ex.run("train", feed_dict={x: bx, y_: by})
                    losses.append(float(lv.asnumpy()))
            assert all(np.isfinite(losses)), losses
            assert losses[-1] < losses[0], losses   # still learning after
            live = get_live_cluster()
            assert live["supervisor"].respawns == 1, \\
                live["supervisor"].events
            assert live["supervisor"].fatal is None
            print("SURVIVED", len(losses), flush=True)
    """ % REPO)
    p = tmp_path / "ps_kill_job.py"
    p.write_text(script)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="")
    proc = subprocess.run([sys.executable, str(p)], capture_output=True,
                          text=True, timeout=240, env=env, cwd=str(tmp_path))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "SURVIVED 12" in proc.stdout
    assert "respawning replacement" in proc.stderr  # the supervisor acted
    # the replacement rebuilt its store from the continuous snapshot (the
    # worker reconnects via fast retry or the failover wait — both re-issue
    # the same req_id; which one wins the race is timing, and the dedup
    # VALUE proof lives in test_ps_fault)
    assert "restored 1 param shard(s) from snapshot" in proc.stderr


# ---------------------------------------------------------------------------
# supervise(): restart with backoff
# ---------------------------------------------------------------------------

def test_supervise_restarts_with_backoff_and_resumes_state(tmp_path):
    delays = []
    attempts = []

    with TrainCheckpointer(tmp_path / "ck", keep=3) as ck:
        def loop_fn(state, start_step):
            attempts.append(start_step)
            if len(attempts) == 1:
                assert state is None and start_step == 0
                ck.save_step(0, {"x": np.asarray(1.0, np.float32)})
                raise RuntimeError("boom 1")
            if len(attempts) == 2:
                assert float(state["x"]) == 1.0 and start_step == 1
                ck.save_step(1, {"x": np.asarray(2.0, np.float32)})
                raise RuntimeError("boom 2")
            assert float(state["x"]) == 2.0 and start_step == 2
            return "done"

        out = rs.supervise(loop_fn, ck, max_restarts=3, backoff_s=0.5,
                           sleep=delays.append)
    assert out == "done"
    assert attempts == [0, 1, 2]
    assert delays == [0.5, 1.0]                   # exponential backoff


def test_supervise_exhausts_restarts_and_reraises():
    calls = []

    def loop_fn(state, start_step):
        calls.append(1)
        raise RuntimeError("always")

    with pytest.raises(RuntimeError, match="always"):
        rs.supervise(loop_fn, None, max_restarts=2, sleep=lambda s: None)
    assert len(calls) == 3                        # 1 attempt + 2 restarts


def test_supervise_never_retries_preemption():
    def loop_fn(state, start_step):
        raise rs.Preempted(5)

    with pytest.raises(SystemExit) as ei:
        rs.supervise(loop_fn, None, max_restarts=5, sleep=lambda s: None)
    assert ei.value.code == rs.EXIT_PREEMPTED
    with pytest.raises(rs.Preempted):
        rs.supervise(loop_fn, None, on_preempt="raise", sleep=lambda s: None)


# ---------------------------------------------------------------------------
# dataloader state round trip
# ---------------------------------------------------------------------------

def test_dataloader_state_dict_round_trip():
    data = np.arange(40, dtype=np.float32).reshape(20, 2)

    def fresh():
        return ht.Dataloader(data, 4, "train", shuffle=True, seed=3)

    a = fresh()
    for _ in range(7):                 # crosses the epoch reshuffle at 5
        a.get_arr()
    a.peek_arr()                       # peeked-but-unconsumed batch in state
    sd = a.state_dict()

    b = fresh()
    b.load_state_dict(sd)
    for _ in range(12):
        np.testing.assert_array_equal(a.get_arr(), b.get_arr())

    # mismatched dataset size is rejected, not silently skewed
    c = ht.Dataloader(np.zeros((8, 2), np.float32), 4, "train")
    with pytest.raises(ValueError):
        c.load_state_dict(sd)


def test_dataloader_op_state_round_trip():
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    op = ht.dataloader_op([ht.Dataloader(data, 3, "train", shuffle=True,
                                         seed=5)])
    for _ in range(4):
        op.get_batch("train")
    sd = op.state_dict("train")
    assert op.state_dict("nosuch") is None
    op2 = ht.dataloader_op([ht.Dataloader(data, 3, "train", shuffle=True,
                                          seed=5)])
    op2.load_state_dict("train", sd)
    for _ in range(6):
        np.testing.assert_array_equal(op.get_batch("train"),
                                      op2.get_batch("train"))


def test_anomaly_guard_refuses_ps_mode():
    x = ht.Variable(name="x", trainable=False)
    w = ht.init.random_normal((4, 2), stddev=0.5, name="w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    with pytest.raises(ValueError, match="anomaly_guard"):
        ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                    comm_mode="PS", anomaly_guard=True)
