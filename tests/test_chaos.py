"""hetuchaos — deterministic network-fault chaos engine + PS transport
hardening (docs/FAULT_TOLERANCE.md "Chaos testing & transport hardening").

The cluster tests are the acceptance proofs: CRC reject → retry →
exact-apply (bit-identical to an undisturbed twin tensor), duplicate/
reorder delivery under exact update accounting, deterministic replay
(same seed ⇒ identical canonical chaos event log across two live cluster
runs), directed-partition escalation with the typed diagnosis, and
off-mode zero-work. The unit tests pin the backoff/jitter schedule
mirror against a fake clock, the spec grammar (incl. unknown-kind
rejection on both the Python and native parsers), and the fault-kind
catalogue rejection in HETU_FAULT_SPEC.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from test_ps import run_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# backoff/jitter schedule against a fake clock (the Python mirror IS the
# C++ schedule — both sides are pure integer math on splitmix64)
# ---------------------------------------------------------------------------

def test_backoff_schedule_fake_clock():
    from hetu_tpu import chaos
    # what a clock would observe between attempts: exponential envelope,
    # deterministic jitter in [0.5, 1.0) of it, capped
    sched = chaos.backoff_schedule(8, base_ms=10, cap_ms=2000, key=1234)
    assert len(sched) == 8
    for attempt, slept in enumerate(sched, 1):
        envelope = min(10 << (attempt - 1), 2000)
        assert envelope // 2 <= slept < envelope, (attempt, slept)
    # the cap holds forever after (attempt 20+ must not overflow the shift)
    assert chaos.backoff_ms(40, base_ms=10, cap_ms=2000, key=5) < 2000
    # deterministic per (key, attempt): replays bit-identically
    assert sched == chaos.backoff_schedule(8, base_ms=10, cap_ms=2000,
                                           key=1234)
    # ...and keys decorrelate (different req_ids don't sleep in lockstep)
    other = chaos.backoff_schedule(8, base_ms=10, cap_ms=2000, key=1235)
    assert sched != other
    # splitmix64 mirror pinned to reference values (csrc/ps/chaos.h)
    assert chaos.splitmix64(0) == 0xE220A8397B1DCDAF


def test_spec_grammar_roundtrip_and_unknown_kind():
    from hetu_tpu import chaos
    cs = chaos.parse_spec(
        "seed=9,drop=0.05,droprsp=0.02,dup=0.1,corrupt=0.01,"
        "delay=0.2:7,reorder=0.1:3,partition=1:5:10")
    assert cs.seed == 9 and cs.delay_ms == 7 and cs.reorder_ms == 3
    assert cs.partitions == [(1, 5, 10)]
    assert chaos.parse_spec(chaos.render_spec(cs)) == cs
    with pytest.raises(ValueError, match="unknown kind 'flood'"):
        chaos.parse_spec("flood=0.5")
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        chaos.parse_spec("drop=1.01")
    # random_spec is deterministic and always parses
    assert chaos.random_spec(7) == chaos.random_spec(7)
    chaos.parse_spec(chaos.random_spec(7))


def test_fault_spec_unknown_kind_lists_catalogue():
    """HETU_FAULT_SPEC rejects unknown kinds with the known list and a
    pointer at the catalogue, instead of silently ignoring them."""
    from hetu_tpu.resilience import FaultInjector
    with pytest.raises(ValueError) as ei:
        FaultInjector("explode@3")
    msg = str(ei.value)
    assert "ps_kill" in msg and "ps_partition" in msg
    assert "FAULT_TOLERANCE.md" in msg
    # the chaos-era kind parses like the rest
    fi = FaultInjector("ps_partition@4:2")
    assert fi.entries[0]["kind"] == "ps_partition"
    assert fi.entries[0]["arg"] == 2.0


# ---------------------------------------------------------------------------
# CRC reject -> retry -> exact-apply under a live cluster
# ---------------------------------------------------------------------------

def _crc_reject_worker(client, rank, tmpdir):
    from hetu_tpu import chaos
    client.InitTensor(1, 0, 64, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    client.InitTensor(2, 0, 64, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    # twin tensor 2: the same pushes with no chaos — ground truth
    for _ in range(6):
        client.Push(2, np.ones(64, np.float32))
        client.Wait(2)
    # corrupt=1: EVERY first attempt has one payload byte flipped on the
    # wire (after checksumming — where a real bit-flip lands); retries are
    # clean, so the run converges while exercising reject->retry each time
    client.SetChaos("seed=11,corrupt=1.0")
    for _ in range(6):
        client.Push(1, np.ones(64, np.float32))
        client.Wait(1)
    client.SetChaos(None)
    cs = client.ClientStats()
    assert cs["crc_rejects"] > 0, cs
    assert cs["retries"] >= cs["crc_rejects"], cs
    # the servers refused BEFORE any apply: both tensors saw exactly 6
    # applies, so their final values are bit-identical
    srv_rejects = sum(client.ServerStats(s)["crc_rejects"]
                      for s in range(2))
    assert srv_rejects > 0
    a = np.zeros(64, np.float32)
    client.Pull(1, a)
    client.Wait(1)
    b = np.zeros(64, np.float32)
    client.Pull(2, b)
    client.Wait(2)
    assert np.array_equal(a, b), (a[:4], b[:4])
    counts = chaos.fault_counts(client.DrainChaosEvents())
    assert counts.get("corrupt", 0) > 0, counts


def test_crc_reject_retry_exact_apply(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    run_cluster(_crc_reject_worker, tmp_path, n_workers=1, n_servers=2)


# ---------------------------------------------------------------------------
# duplicate + reorder delivery: exact update accounting
# ---------------------------------------------------------------------------

def _dup_reorder_worker(client, rank, tmpdir):
    from hetu_tpu import chaos
    client.InitTensor(1, 0, 48, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    base_cs = client.ClientStats()
    base_updates = sum(client.ServerStats(s)["updates"] for s in range(2))
    client.SetChaos("seed=21,dup=0.5,reorder=0.5:3,droprsp=0.2")
    for _ in range(12):
        client.Push(1, np.ones(48, np.float32))
        client.Wait(1)
    client.SetChaos(None)
    cs = client.ClientStats()
    # every duplicate was answered from the dedup slot and every dropped
    # response was replayed, never re-applied: logical write RPCs == the
    # servers' summed optimizer update counters, exactly
    pushes = cs["pushes_ok"] - base_cs["pushes_ok"]
    updates = sum(client.ServerStats(s)["updates"]
                  for s in range(2)) - base_updates
    assert pushes == updates, (pushes, updates)
    counts = chaos.fault_counts(client.DrainChaosEvents())
    assert counts.get("dup", 0) > 0, counts
    assert counts.get("reorder", 0) > 0, counts
    assert counts.get("droprsp", 0) > 0, counts
    assert cs["chaos_faults"] > 0


def test_duplicate_reorder_exact_accounting(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    run_cluster(_dup_reorder_worker, tmp_path, n_workers=1, n_servers=2)


# ---------------------------------------------------------------------------
# deterministic replay: same seed => identical canonical chaos event log
# across two independent live cluster runs
# ---------------------------------------------------------------------------

# partition included ON PURPOSE: its events record the deterministic
# window hit (attempt index + channel, psf/tensor zeroed), so the
# canonical log stays replayable even when pool threads race for the
# channel — this spec pins that contract
_REPLAY_SPEC = ("seed=33,drop=0.2,dup=0.3,corrupt=0.2,delay=0.2:2,"
                "partition=0:4:2")


def _replay_worker(client, rank, tmpdir):
    from hetu_tpu import chaos
    client.InitTensor(1, 0, 32, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    client.SetChaos(_REPLAY_SPEC)
    for _ in range(10):
        client.Push(1, np.ones(32, np.float32))
        client.Wait(1)
        out = np.zeros(32, np.float32)
        client.Pull(1, out)
        client.Wait(1)
    client.SetChaos(None)
    rows = client.DrainChaosEvents()
    np.save(os.path.join(str(tmpdir),
                         f"events-{os.environ['HETU_CHAOS_RUN']}.npy"),
            np.asarray(chaos.canonical_log(rows), np.int64))


def test_deterministic_replay(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    for run in ("a", "b"):
        monkeypatch.setenv("HETU_CHAOS_RUN", run)
        run_cluster(_replay_worker, tmp_path, n_workers=1, n_servers=2)
    a = np.load(tmp_path / "events-a.npy")
    b = np.load(tmp_path / "events-b.npy")
    # ring order may race across the send pool; the canonical (sorted)
    # log is the determinism contract — and it must not be empty
    assert a.size > 0
    assert a.shape == b.shape and np.array_equal(a, b)


# ---------------------------------------------------------------------------
# directed partition: escalates with the typed diagnosis instead of
# blocking forever
# ---------------------------------------------------------------------------

def _partition_worker(client, rank, tmpdir):
    client.InitTensor(1, 0, 8, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    # a partition window covering every attempt incl. retries: the rpc
    # must exhaust its budget and raise the directed-partition diagnosis
    # (scheduler reachable + heartbeat fresh + RPCs failing), pointing at
    # the failover/departure path
    client.SetChaos("seed=1,partition=0:0:1000")
    with pytest.raises(RuntimeError) as ei:
        client.Push(1, np.ones(8, np.float32))
        client.Wait(1)
    assert "directed partition suspected" in str(ei.value), str(ei.value)
    assert "unreachable" in str(ei.value)
    client.SetChaos(None)


def test_partition_escalates_with_diagnosis(tmp_path, monkeypatch):
    monkeypatch.setenv("HETU_TEST_MODE", "1")
    # small budget so the escalation is fast; backoff stays in the ms range
    monkeypatch.setenv("DMLC_PS_MAX_RETRY", "2")
    monkeypatch.setenv("DMLC_PS_BACKOFF_BASE_MS", "5")
    run_cluster(_partition_worker, tmp_path, n_workers=1, n_servers=2)


# ---------------------------------------------------------------------------
# gating + off-mode
# ---------------------------------------------------------------------------

def _gating_worker(client, rank, tmpdir):
    # without HETU_TEST_MODE the chaos surface refuses to arm, like every
    # destructive hook
    with pytest.raises(RuntimeError, match="HETU_TEST_MODE"):
        client.SetChaos("seed=1,drop=0.5")


def test_chaos_requires_test_mode(tmp_path, monkeypatch):
    monkeypatch.delenv("HETU_TEST_MODE", raising=False)
    monkeypatch.delenv("HETU_CHAOS_SPEC", raising=False)
    run_cluster(_gating_worker, tmp_path, n_workers=1, n_servers=1)


def _off_mode_worker(client, rank, tmpdir):
    client.InitTensor(1, 0, 32, 1, "constant", 0.0, opt_type="sgd",
                      lrs=(0.1,))
    for _ in range(4):
        client.Push(1, np.ones(32, np.float32))
        client.Wait(1)
    cs = client.ClientStats()
    # a clean wire with no spec armed: no injected faults, no retries, no
    # backoff slept, no rejects — the chaos engine never ran
    assert cs["chaos_faults"] == 0, cs
    assert cs["retries"] == 0 and cs["backoff_ms"] == 0, cs
    assert cs["crc_rejects"] == 0, cs
    assert len(client.DrainChaosEvents()) == 0


def test_chaos_off_mode_zero_work(tmp_path, monkeypatch):
    monkeypatch.delenv("HETU_CHAOS_SPEC", raising=False)
    run_cluster(_off_mode_worker, tmp_path, n_workers=1, n_servers=1)


# ---------------------------------------------------------------------------
# CLI smoke
# ---------------------------------------------------------------------------

def test_hetuchaos_check_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuchaos"),
         "--check"], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "invariant checkers OK" in out.stdout, out.stdout


def test_hetuchaos_short_soak_cli():
    """The CI soak: one seeded schedule over a live local_cluster
    training run, fault-free twin + every invariant checker, end to end
    through the real CLI (~2 s on a quiet host; the 120 s timeout is a
    hang bound, not a verdict)."""
    env = dict(os.environ, HETU_TEST_MODE="1", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetuchaos"),
         "--seed", "1", "--steps", "12"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=REPO)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "bit-identical to fault-free twin" in out.stdout, out.stdout
