"""Distribution-level initializer checks + scheduler trajectory parity
(reference ``tests/test_gpu_initializers.py`` and ``test_lr_scheduler.py``:
the reference validates initializer statistics and per-step lr values; here
additionally ``get()`` (host, step_count-driven) must agree with
``get_traced(step)`` (in-jit) at every step)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hetu_tpu import initializers as init
from hetu_tpu import lr_scheduler as lr


SHAPE = (400, 300)


def _sample(cls_or_obj):
    return np.asarray(cls_or_obj.init(jax.random.PRNGKey(0)))


def test_constant_zeros_ones():
    assert np.all(_sample(init.ConstantInit(2.5, SHAPE)) == 2.5)
    assert np.all(_sample(init.ZerosInit(SHAPE)) == 0.0)
    assert np.all(_sample(init.OnesInit(SHAPE)) == 1.0)


def test_uniform_bounds_and_moments():
    v = _sample(init.UniformInit(-0.3, 0.7, SHAPE))
    assert v.min() >= -0.3 and v.max() <= 0.7
    assert v.mean() == pytest.approx(0.2, abs=0.01)
    assert v.std() == pytest.approx(1.0 / np.sqrt(12), abs=0.01)


def test_normal_moments():
    v = _sample(init.NormalInit(0.5, 0.2, SHAPE))
    assert v.mean() == pytest.approx(0.5, abs=0.01)
    assert v.std() == pytest.approx(0.2, abs=0.01)


def test_truncated_normal_bounds_and_std():
    v = _sample(init.TruncatedNormalInit(0.0, 0.1, SHAPE))
    assert np.abs(v).max() <= 0.2 + 1e-6      # +/- 2 stddev, like the ref
    assert v.std() == pytest.approx(0.1, rel=0.2)  # truncation shrinks it


@pytest.mark.parametrize("cls,gain,mode", [
    (init.XavierUniformInit, 3.0, "avg"),
    (init.HeUniformInit, 6.0, "fan_in"),
    (init.LecunUniformInit, 3.0, "fan_in"),
])
def test_fanaware_uniform_limits(cls, gain, mode):
    fan_in, fan_out = SHAPE
    fan = {"fan_in": fan_in, "avg": (fan_in + fan_out) / 2.0}[mode]
    limit = np.sqrt(gain / fan)
    v = _sample(cls(SHAPE))
    assert np.abs(v).max() <= limit + 1e-6
    assert v.std() == pytest.approx(2 * limit / np.sqrt(12), rel=0.05)


@pytest.mark.parametrize("cls,gain,mode", [
    (init.XavierNormalInit, 1.0, "avg"),
    (init.HeNormalInit, 2.0, "fan_in"),
    (init.LecunNormalInit, 1.0, "fan_in"),
])
def test_fanaware_normal_std(cls, gain, mode):
    fan_in, fan_out = SHAPE
    fan = {"fan_in": fan_in, "avg": (fan_in + fan_out) / 2.0}[mode]
    v = _sample(cls(SHAPE))
    assert v.std() == pytest.approx(np.sqrt(gain / fan), rel=0.05)
    assert v.mean() == pytest.approx(0.0, abs=0.005)


@pytest.mark.parametrize("make,expected", [
    (lambda: lr.FixedScheduler(0.5), [0.5] * 8),
    (lambda: lr.StepScheduler(0.8, step_size=3, gamma=0.5),
     [0.8, 0.8, 0.8, 0.4, 0.4, 0.4, 0.2, 0.2]),
    (lambda: lr.MultiStepScheduler(1.0, milestones=[2, 5], gamma=0.1),
     [1.0, 1.0, 0.1, 0.1, 0.1, 0.01, 0.01, 0.01]),
])
def test_scheduler_trajectories(make, expected):
    """get() after k step()s and get_traced(k) must both equal the closed
    form — the device path (traced) and PS path (host) share one schedule."""
    sched = make()
    host = []
    for _ in range(len(expected)):
        host.append(float(sched.get()))
        sched.step()
    traced = [float(make().get_traced(jnp.int32(t)))
              for t in range(len(expected))]
    np.testing.assert_allclose(host, expected, rtol=1e-6)
    np.testing.assert_allclose(traced, expected, rtol=1e-6)


def test_exponential_host_traced_parity():
    sched = lr.ExponentialScheduler(0.5, gamma=0.7)
    for t in range(12):
        host = float(sched.get())
        traced = float(lr.ExponentialScheduler(0.5, gamma=0.7)
                       .get_traced(jnp.int32(t)))
        assert host == pytest.approx(0.5 * 0.7 ** t, rel=1e-5), (t, host)
        assert traced == pytest.approx(host, rel=1e-5), (t, host, traced)
        sched.step()


def test_cosine_trajectory_closed_form():
    """Against the closed form directly (get() delegates to get_traced, so
    host/traced parity alone would be tautological here)."""
    base, steps, ending = 0.5, 10, 0.05
    sched = lr.CosineScheduler(base, steps, ending)
    for t in range(14):
        frac = min(t / steps, 1.0)
        expected = ending + (base - ending) * 0.5 * (1 + np.cos(np.pi * frac))
        assert float(sched.get()) == pytest.approx(expected, rel=1e-5), t
        sched.step()
    # warmup ramps linearly on top of the cosine value
    warm = lr.CosineScheduler(base, steps, ending, warmup_steps=4)
    for t in (0, 1, 2, 3):
        frac = t / steps
        cos_lr = ending + (base - ending) * 0.5 * (1 + np.cos(np.pi * frac))
        assert float(warm.get_traced(jnp.int32(t))) == pytest.approx(
            cos_lr * t / 4, rel=1e-5), t
