"""NLP surface tests: BERT WordPiece tokenizer fixtures (reference
``tokenizers/bert_tokenizer.py``) and the graph-API transformer trainer
(reference ``examples/nlp/hetu_transformer.py``)."""
import os
import sys

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.tokenizers import (BasicTokenizer, WordpieceTokenizer,
                                 BertTokenizer, load_vocab)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples", "nlp"))


# ---------------------------------------------------------------------------
# tokenizer: fixture strings with the canonical BERT expected outputs
# ---------------------------------------------------------------------------

def test_wordpiece_canonical_fixture():
    """The canonical example from the BERT paper/code: 'unwanted running'
    -> un ##want ##ed runn ##ing."""
    vocab = {t: i for i, t in enumerate(
        ["[UNK]", "[CLS]", "[SEP]", "want", "##want", "##ed", "wa", "un",
         "runn", "##ing"])}
    wp = WordpieceTokenizer(vocab)
    assert wp.tokenize("unwanted running") == \
        ["un", "##want", "##ed", "runn", "##ing"]
    # unknown word -> [UNK]; known following it still tokenizes
    assert wp.tokenize("unwantedX running") == ["[UNK]", "runn", "##ing"]
    assert wp.tokenize("") == []


def test_basic_tokenizer_lower_and_punct():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize(" \tHeLLo!how  \n Are yoU?  ") == \
        ["hello", "!", "how", "are", "you", "?"]
    # accents stripped under lowercasing
    assert bt.tokenize("Héllo") == ["hello"]
    # control chars removed, CJK chars isolated
    assert bt.tokenize("ah博推zz") == ["ah", "博", "推", "zz"]


def test_basic_tokenizer_cased():
    bt = BasicTokenizer(do_lower_case=False)
    assert bt.tokenize("HeLLo!how Are yoU?") == \
        ["HeLLo", "!", "how", "Are", "yoU", "?"]


def test_bert_tokenizer_end_to_end(tmp_path):
    tokens = ["[UNK]", "[CLS]", "[SEP]", "want", "##want", "##ed", "wa",
              "un", "runn", "##ing", ","]
    vocab_file = tmp_path / "vocab.txt"
    vocab_file.write_text("\n".join(tokens) + "\n")
    tok = BertTokenizer(str(vocab_file))
    out = tok.tokenize("UNwantéd,running")
    assert out == ["un", "##want", "##ed", ",", "runn", "##ing"]
    ids = tok.convert_tokens_to_ids(out)
    assert ids == [7, 4, 5, 10, 8, 9]
    assert tok.convert_ids_to_tokens(ids) == out
    # load_vocab preserves file order
    assert list(load_vocab(str(vocab_file)).items())[:2] == \
        [("[UNK]", 0), ("[CLS]", 1)]


def test_never_split_tokens_pass_through():
    vocab = {t: i for i, t in enumerate(
        ["[UNK]", "[CLS]", "[SEP]", "hello"])}
    tok = BertTokenizer(vocab)
    assert tok.tokenize("[CLS] hello [SEP]") == ["[CLS]", "hello", "[SEP]"]


def test_bert_data_pipeline():
    """processBertData: instances have [CLS]/[SEP] structure, valid masking
    positions, and padded fixed-length rows."""
    from processBertData import create_instances_from_document

    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "fox", "dog",
         "jumps", "runs", "barks", "quick", "lazy", "brown", "over"])}
    tok = BertTokenizer(vocab)
    sentences = ["the quick brown fox jumps over the lazy dog",
                 "the dog barks", "the fox runs", "the lazy dog runs"]
    insts = create_instances_from_document(
        sentences, tok, max_seq_length=24, max_predictions_per_seq=5, seed=0)
    assert len(insts) == len(sentences) - 1
    for ids, mask, seg, mlm_pos, mlm_ids, nsp in insts:
        assert ids.shape == (24,) and mask.shape == (24,)
        assert seg.shape == (24,) and mlm_pos.shape == (5,)
        n = int(mask.sum())
        assert ids[0] == vocab["[CLS]"]
        assert (ids[:n] == vocab["[SEP]"]).sum() == 2
        assert np.all(ids[n:] == vocab["[PAD]"])
        assert nsp in (0, 1)
        # masked positions point inside the live region and the labels are
        # real vocab ids
        live = mlm_ids > 0
        assert np.all(mlm_pos[live] < n)


# ---------------------------------------------------------------------------
# graph-API transformer
# ---------------------------------------------------------------------------

def test_graph_api_transformer_learns():
    """Tiny causal LM on a fixed repeating sequence: loss must fall
    substantially (the model memorizes the pattern)."""
    from hetu_transformer import transformer_lm

    B, T, V = 4, 16, 11
    rng = np.random.RandomState(0)
    pattern = rng.randint(1, V, 64)
    data = np.tile(pattern, 4).astype(np.float32)

    tokens = ht.Variable(name="tokens", trainable=False)
    labels = ht.Variable(name="labels", trainable=False)
    loss, logits, _ = transformer_lm(tokens, labels, V, B, T, d_model=32,
                                     n_heads=2, n_layers=1, d_ff=64,
                                     dropout_prob=0.0)
    train_op = ht.optim.AdamOptimizer(2e-3).minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=0)

    losses = []
    for step in range(150):
        starts = rng.randint(0, data.size - T - 1, B)
        bx = np.stack([data[s:s + T] for s in starts])
        by = np.stack([data[s + 1:s + T + 1] for s in starts])
        lv = ex.run("train", feed_dict={tokens: bx, labels: by},
                    convert_to_numpy_ret_vals=True)[0]
        losses.append(float(np.mean(lv)))
    assert np.mean(losses[-10:]) < 0.5 * np.mean(losses[:10]), (
        np.mean(losses[:10]), np.mean(losses[-10:]))


def test_graph_api_transformer_causality():
    """Changing a future token must not change earlier logits (the causal
    mask is real)."""
    from hetu_transformer import transformer_lm

    B, T, V = 2, 8, 7
    tokens = ht.Variable(name="tokens", trainable=False)
    labels = ht.Variable(name="labels", trainable=False)
    loss, logits, _ = transformer_lm(tokens, labels, V, B, T, d_model=16,
                                     n_heads=2, n_layers=1, d_ff=32,
                                     dropout_prob=0.0)
    ex = ht.Executor({"eval": [logits]}, ctx=ht.cpu(0), seed=0)
    rng = np.random.RandomState(1)
    bx = rng.randint(0, V, (B, T)).astype(np.float32)
    by = np.zeros((B, T), np.float32)
    (l1,) = ex.run("eval", feed_dict={tokens: bx, labels: by},
                   convert_to_numpy_ret_vals=True)
    bx2 = bx.copy()
    bx2[:, -1] = (bx2[:, -1] + 1) % V          # perturb the LAST token only
    (l2,) = ex.run("eval", feed_dict={tokens: bx2, labels: by},
                   convert_to_numpy_ret_vals=True)
    l1 = l1.reshape(B, T, V)
    l2 = l2.reshape(B, T, V)
    np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], rtol=1e-5, atol=1e-6)
    assert np.abs(l1[:, -1] - l2[:, -1]).max() > 1e-4


def test_generate_demo_example_runs():
    """examples/nlp/generate_hetu.py: train-then-decode demo exercising
    every decode strategy (greedy/sample/beam/eos/ragged) end to end."""
    import generate_hetu   # module-level sys.path already covers examples/nlp
    loss = generate_hetu.main(["--steps", "60", "--beam", "2",
                               "--max-len", "12"])
    assert np.isfinite(loss) and loss < 3.0  # learned something


def test_gpt2_pipeline_example_runs():
    """examples/nlp/gpt2_pipeline.py: tokenizer -> HF import -> fine-tune
    -> greedy/sampled/speculative decode -> export -> HF generates the
    same tokens (the asserts live inside the script)."""
    pytest.importorskip("torch")
    pytest.importorskip("transformers")
    import gpt2_pipeline
    loss = gpt2_pipeline.main(["--steps", "6", "--max-len", "20",
                               "--spec-k", "2"])
    assert np.isfinite(loss)


def test_finetune_hf_bert_example_runs():
    """examples/nlp/finetune_hf_bert.py: HF checkpoint -> import -> fresh
    classification head -> flagship fine-tune step, accuracy above chance
    (0.84 batch acc at the default 100 steps when run standalone)."""
    pytest.importorskip("torch")
    pytest.importorskip("transformers")
    import finetune_hf_bert
    acc = finetune_hf_bert.main(["--steps", "100"])
    assert acc > 0.7
