"""CTR/rec model-zoo tests (reference examples/ctr convergence scripts,
SURVEY §4.7): every model builds, trains a few steps locally, loss is finite
and decreasing on the synthetic task; WDL-Criteo also trains under
comm_mode='Hybrid' against a live PS cluster."""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "rec"))

from test_ps import run_cluster


from conftest import import_example_models as _import_example_models


DIM = 500  # small feature dimension for synthetic runs


def _train_criteo_model(model_name, steps=20, **kwargs):
    import hetu_tpu as ht
    models = _import_example_models("ctr")
    load_criteo_data = models.load_data.load_criteo_data

    (tr_dense, tr_sparse, tr_y), _ = load_criteo_data(
        feature_dimension=DIM, n_train=steps * 32, n_test=64)
    dense = ht.dataloader_op([ht.Dataloader(tr_dense, 32, "train")])
    sparse = ht.dataloader_op([ht.Dataloader(tr_sparse, 32, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(tr_y, 32, "train")])
    model_fn = getattr(models, model_name)
    loss, y, labels, train_op = model_fn(dense, sparse, y_,
                                         feature_dimension=DIM,
                                         embedding_size=16, **kwargs)
    # explicit seed: the default comes from numpy's global RNG, making
    # convergence assertions depend on which tests ran earlier
    ex = ht.Executor({"train": [loss, y, labels, train_op]}, ctx=ht.cpu(0),
                     seed=42)
    losses = []
    for _ in range(steps):
        out = ex.run("train", convert_to_numpy_ret_vals=True)
        losses.append(float(np.mean(out[0])))
    assert np.all(np.isfinite(losses)), losses
    return losses


# wdl_criteo's reference-scale 0.01 inits vanish through its 3-layer MLP
# (activations shrink ~100x by the output); near-Xavier stddev + a larger lr
# make 30-step convergence observable without changing the model defaults
_TRAIN_KWARGS = {"wdl_criteo": dict(stddev=0.06, learning_rate=0.05)}


@pytest.mark.parametrize("model_name", ["wdl_criteo", "dfm_criteo",
                                        "dcn_criteo", "dc_criteo"])
def test_criteo_model_trains(model_name):
    losses = _train_criteo_model(model_name, steps=30,
                                 **_TRAIN_KWARGS.get(model_name, {}))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        model_name, losses[:5], losses[-5:])


def test_wdl_adult_trains():
    import hetu_tpu as ht
    models = _import_example_models("ctr")
    load_adult_data = models.load_data.load_adult_data

    (tr_deep, tr_wide, tr_y), _ = load_adult_data(n_train=640, n_test=64)
    X_deep = [ht.dataloader_op([ht.Dataloader(tr_deep[i], 32, "train")])
              for i in range(12)]
    X_wide = ht.dataloader_op([ht.Dataloader(tr_wide, 32, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(tr_y, 32, "train")])
    loss, y, labels, train_op = models.wdl_adult(X_deep, X_wide, y_)
    ex = ht.Executor({"train": [loss, y, labels, train_op]}, ctx=ht.cpu(0),
                     seed=42)
    losses = [float(np.mean(ex.run("train", convert_to_numpy_ret_vals=True)[0]))
              for _ in range(20)]
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_ncf_trains():
    import hetu_tpu as ht
    from hetu_ncf import neural_mf
    from movielens import getdata

    users, items, labels, nu, ni = getdata(num_users=100, num_items=200,
                                           n_pos=2000)
    user_in = ht.dataloader_op([ht.Dataloader(users, 256, "train")])
    item_in = ht.dataloader_op([ht.Dataloader(items, 256, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(labels, 256, "train")])
    # stddev raised for test speed: reference-scale 0.01 inits keep early
    # logits ~1e-4, needing thousands of batches before loss visibly moves
    loss, y, train_op = neural_mf(user_in, item_in, y_, nu, ni,
                                  learning_rate=0.3, embed_stddev=0.3)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=42)
    n = ex.get_batch_num("train")
    losses = []
    for _ in range(4):  # NCF needs a few epochs before the factors separate
        for _ in range(n):
            losses.append(float(np.mean(
                ex.run("train", convert_to_numpy_ret_vals=True)[0])))
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.01


def _wdl_hybrid_worker(client, rank, tmpdir):
    import hetu_tpu as ht
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "ctr"))
    import models
    from models.load_data import load_criteo_data

    (tr_dense, tr_sparse, tr_y), _ = load_criteo_data(
        feature_dimension=DIM, n_train=640, n_test=64, seed=rank)
    dense = ht.dataloader_op([ht.Dataloader(tr_dense, 32, "train")])
    sparse = ht.dataloader_op([ht.Dataloader(tr_sparse, 32, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(tr_y, 32, "train")])
    loss, y, labels, train_op = models.wdl_criteo(
        dense, sparse, y_, feature_dimension=DIM, embedding_size=16)
    ex = ht.Executor({"train": [loss, y, labels, train_op]}, ctx=ht.cpu(0),
                     comm_mode="Hybrid", seed=42)
    losses = [float(np.mean(ex.run("train", convert_to_numpy_ret_vals=True)[0]))
              for _ in range(20)]
    assert np.all(np.isfinite(losses)), losses


def test_wdl_criteo_hybrid_ps(tmp_path):
    run_cluster(_wdl_hybrid_worker, tmp_path, n_workers=2, timeout=300)
