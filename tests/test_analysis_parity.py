"""Abstract-eval <-> executor parity: for every op family under
``graph/ops/``, the shapes and dtypes the analysis subsystem infers
statically must match what the real executor produces at run time.

One executor per family (all of the family's case nodes evaluated in a
single jitted program) keeps the suite tier-1 fast."""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import analysis


def _feed(name, arr):
    node = ht.Variable(name=name, trainable=False,
                       dtype=arr.dtype, batch=False)
    return node, arr


def _rand(shape, seed, dtype=np.float32):
    return np.random.RandomState(seed).randn(*shape).astype(dtype)


def _cases_arith():
    x, xv = _feed("a_x", _rand((4, 3), 0))
    y, yv = _feed("a_y", _rand((4, 3), 1))
    c, cv = _feed("a_c", np.asarray(
        np.random.RandomState(2).rand(4, 3) > 0.5, np.float32))
    feeds = {x: xv, y: yv, c: cv}
    nodes = [
        ht.add_op(x, y), ht.addbyconst_op(x, 1.5), ht.mul_op(x, y),
        ht.mul_byconst_op(x, 2.0), ht.div_op(x, y), ht.div_const_op(3.0, y),
        ht.opposite_op(x), ht.sqrt_op(ht.mul_op(x, x)),
        ht.rsqrt_op(ht.addbyconst_op(ht.mul_op(x, x), 1.0)),
        ht.oneslike_op(x), ht.zeroslike_op(x), ht.where_op(c, x, y),
        ht.relu_op(x), ht.relu_gradient_op(x, y),
        ht.leaky_relu_op(x, 0.1), ht.leaky_relu_gradient_op(x, y, 0.1),
        ht.sigmoid_op(x), ht.tanh_op(x), ht.gelu_op(x), ht.exp_op(x),
        ht.log_op(ht.exp_op(x)), ht.softmax_op(x),
        ht.softmax_gradient_op(ht.softmax_op(x), y),
    ]
    return nodes, feeds


def _cases_shape():
    x, xv = _feed("s_x", _rand((4, 6), 3))
    b, bv = _feed("s_b", _rand((6,), 4))
    feeds = {x: xv, b: bv}
    nodes = [
        ht.array_reshape_op(x, (2, 12)), ht.array_reshape_gradient_op(x, x),
        ht.transpose_op(x, (1, 0)), ht.slice_op(x, (1, 2), (2, 3)),
        ht.slice_gradient_op(ht.slice_op(x, (0, 0), (2, 3)), (0, 0), (4, 6)),
        ht.split_op(x, 1, 0, 2), ht.split_gradient_op(
            ht.split_op(x, 1, 0, 2), 1, 0, 2),
        ht.concat_op(x, x, 1), ht.concat_gradient_op(
            ht.concat_op(x, x, 1), x, 1, 0),
        ht.pad_op(x, [(1, 1), (2, 2)]),
        ht.pad_gradient_op(ht.pad_op(x, [(1, 1), (2, 2)]), [(1, 1), (2, 2)]),
        ht.broadcastto_op(b, x), ht.broadcast_shape_op(b, (4, 6)),
        ht.reduce_sum_op(x, [0]), ht.reduce_mean_op(x, [1], keepdims=True),
        ht.reducesumaxiszero_op(x),
    ]
    return nodes, feeds


def _cases_matmul():
    x, xv = _feed("m_x", _rand((4, 3), 5))
    w, wv = _feed("m_w", _rand((3, 5), 6))
    bx, bxv = _feed("m_bx", _rand((2, 4, 3), 7))
    bw, bwv = _feed("m_bw", _rand((2, 3, 5), 8))
    feeds = {x: xv, w: wv, bx: bxv, bw: bwv}
    nodes = [
        ht.matmul_op(x, w), ht.matmul_op(x, x, trans_B=True),
        ht.batch_matmul_op(bx, bw),
        ht.batch_matmul_op(bx, bx, trans_B=True),
        ht.matrix_dot_op(x, x),
    ]
    return nodes, feeds


def _cases_conv():
    x, xv = _feed("c_x", _rand((2, 3, 8, 8), 9))
    f, fv = _feed("c_f", _rand((4, 3, 3, 3), 10))
    feeds = {x: xv, f: fv}
    nodes = [
        ht.conv2d_op(x, f, padding=1, stride=1),
        ht.max_pool2d_op(x, 2, 2, padding=0, stride=2),
        ht.avg_pool2d_op(x, 2, 2, padding=0, stride=2),
    ]
    return nodes, feeds


def _cases_norm():
    x, xv = _feed("n_x", _rand((4, 3, 6, 6), 11))
    h, hv = _feed("n_h", _rand((4, 10), 12))
    feeds = {x: xv, h: hv}
    bn_s = ht.init.ones((3,), name="pn_bn_s")
    bn_b = ht.init.zeros((3,), name="pn_bn_b")
    ln_s = ht.init.ones((10,), name="pn_ln_s")
    ln_b = ht.init.zeros((10,), name="pn_ln_b")
    nodes = [
        ht.batch_normalization_op(x, bn_s, bn_b),
        ht.layer_normalization_op(h, ln_s, ln_b),
        ht.instance_normalization2d_op(x),
    ]
    return nodes, feeds


def _cases_dropout():
    x, xv = _feed("d_x", _rand((4, 6), 13))
    feeds = {x: xv}
    nodes = [ht.dropout_op(x, 0.5),
             ht.dropout_gradient_op(x, 0.5, ht.dropout_op(x, 0.5))]
    return nodes, feeds


def _cases_losses():
    logits, lv = _feed("l_logits", _rand((8, 5), 14))
    labels_np = np.zeros((8, 5), np.float32)
    labels_np[np.arange(8), np.arange(8) % 5] = 1.0
    labels, labv = _feed("l_labels", labels_np)
    pred, pv = _feed("l_pred", np.random.RandomState(15)
                     .rand(8, 5).astype(np.float32))
    dl, dlv = _feed("l_dl", _rand((8,), 16))
    feeds = {logits: lv, labels: labv, pred: pv, dl: dlv}
    nodes = [
        ht.softmaxcrossentropy_op(logits, labels),
        ht.softmaxcrossentropy_gradient_op(logits, labels, dl),
        ht.binarycrossentropy_op(pred, labels),
        ht.binarycrossentropy_gradient_op(pred, labels, pred),
    ]
    return nodes, feeds


def _cases_embedding():
    idx, idxv = _feed("e_idx", np.random.RandomState(17)
                      .randint(0, 10, size=(4, 6)).astype(np.int32))
    vec, vecv = _feed("e_vec", _rand((4, 6, 8), 18))
    feeds = {idx: idxv, vec: vecv}
    table = ht.init.random_normal((10, 8), stddev=0.1, name="pn_table")
    nodes = [
        ht.embedding_lookup_op(table, idx),
        ht.one_hot_op(idx, 12),
        ht.embedding_lookup_gradient_op(vec, idx, (10, 8)),
    ]
    return nodes, feeds


def _cases_comm():
    x, xv = _feed("cm_x", _rand((4, 3), 19))
    feeds = {x: xv}
    send = ht.pipeline_send_op(ht.relu_op(x))
    nodes = [
        ht.allreduceCommunicate_op(x),
        ht.datah2d_op(x), ht.datad2h_op(x),
        send, ht.pipeline_receive_op(send),
    ]
    return nodes, feeds


def _cases_gradients():
    x, xv = _feed("gr_x", _rand((4, 3), 20))
    feeds = {x: xv}
    w = ht.init.random_normal((3, 5), stddev=0.1, name="pn_gw")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    (grad,) = ht.gradients(loss, [w])
    return [loss, grad], feeds


FAMILIES = {
    "arith": _cases_arith,
    "shape": _cases_shape,
    "matmul": _cases_matmul,
    "conv": _cases_conv,
    "norm": _cases_norm,
    "dropout": _cases_dropout,
    "losses": _cases_losses,
    "embedding": _cases_embedding,
    "comm": _cases_comm,
    "gradients": _cases_gradients,
}


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_abstract_eval_matches_executor(family):
    nodes, feeds = FAMILIES[family]()

    ex = ht.Executor(list(nodes), ctx=ht.cpu(0))
    results = ex.run("default", feed_dict=feeds,
                     convert_to_numpy_ret_vals=True)

    topo = ht.find_topo_sort(nodes)
    ag = analysis.AbstractGraph(topo, feed_meta=feeds).evaluate()
    assert not ag.failures, ag.failures
    assert not ag.unknown_roots, ag.unknown_roots

    for node, real in zip(nodes, results):
        meta = ag.meta.get(id(node))
        assert meta is not None, f"{family}: no abstract meta for {node.name}"
        assert tuple(meta.shape) == tuple(real.shape), \
            f"{family}/{node.name}: abstract {tuple(meta.shape)} " \
            f"!= executor {tuple(real.shape)}"
        assert np.dtype(meta.dtype) == real.dtype, \
            f"{family}/{node.name}: abstract dtype {meta.dtype} " \
            f"!= executor {real.dtype}"


def test_infer_shape_shape_only_signature_parity():
    """The historical shape-only ``infer_shape`` contract keeps working."""
    x = ht.Variable(name="iso_x", trainable=False)
    w = ht.Variable(name="iso_w", trainable=False)
    assert ht.matmul_op(x, w).infer_shape([(7, 3), (3, 2)]) == (7, 2)
    assert ht.relu_op(x).infer_shape([(5, 5)]) == (5, 5)
    assert ht.reduce_sum_op(x, [0]).infer_shape([(4, 6)]) == (6,)
