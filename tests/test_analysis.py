"""hetulint: seeded-defect tests (one per lint, asserting severity and
op-level provenance), the `bin/hetulint --json` CI smoke over the bundled
example graphs, Tier B lowered-program checks, and the executor/graphboard
integration. ISSUE 3 acceptance: every shipped lint fires on its seeded
defect; the recompilation detector flags a signature-churning loop that a
fixed-shape loop does not trigger."""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import analysis
from hetu_tpu.graph.node import FunctionalOp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lints_of(findings, lint):
    return [f for f in findings if f.lint == lint]


def feed(name, shape, dtype=np.float32):
    return ht.Variable(name=name, value=np.ones(shape, dtype),
                       dtype=dtype, trainable=False)


# ---------------------------------------------------------------------------
# Tier A seeded defects — one per lint
# ---------------------------------------------------------------------------

def test_shape_mismatch_localized():
    x = feed("x", (4, 3))
    w = feed("w", (4, 5))
    bad = ht.matmul_op(x, w)
    good_in = ht.relu_op(bad)  # downstream cone must NOT double-report
    fs = analysis.analyze_graph([good_in])
    errs = lints_of(fs, "shape-mismatch")
    assert len(errs) == 1
    assert errs[0].severity == "error"
    assert errs[0].op_name == bad.name          # op-level provenance
    assert "(4, 3)" in errs[0].message and "(4, 5)" in errs[0].message


def test_graph_cycle():
    x = feed("x", (4,))
    a = ht.relu_op(x)
    b = ht.relu_op(a)
    a.inputs.append(b)  # seed the cycle
    fs = analysis.analyze_graph([b])
    errs = lints_of(fs, "graph-cycle")
    assert errs and errs[0].severity == "error"


def test_bad_input():
    x = feed("x", (4,))
    a = ht.relu_op(x)
    a.inputs.append("not-an-op")
    fs = analysis.analyze_graph([a], options=None)
    errs = lints_of(fs, "bad-input")
    assert errs and errs[0].severity == "error" and errs[0].op_name == a.name


def test_duplicate_name():
    w1 = ht.Variable(name="dup_w", value=np.ones((2, 2), np.float32))
    w2 = ht.Variable(name="dup_w", value=np.ones((2, 2), np.float32))
    out = ht.matmul_op(w1, w2)
    fs = analysis.analyze_graph([out])
    dups = lints_of(fs, "duplicate-name")
    assert dups and dups[0].severity == "warn"
    assert "dup_w" in dups[0].message


def test_shape_unknown_note_and_skipped_cone():
    x = ht.Variable(name="x", trainable=False)  # fed at run time, no shape
    y = ht.relu_op(ht.matmul_op(x, x))
    fs = analysis.analyze_graph([y])
    notes = lints_of(fs, "shape-unknown")
    assert len(notes) == 1 and notes[0].op_name == "x"
    assert not lints_of(fs, "shape-mismatch")  # cone skipped, not misreported


def test_f64_value():
    w = ht.Variable(name="w64", value=np.ones((2, 2)), dtype=np.float64)
    fs = analysis.analyze_graph([ht.relu_op(w)])
    warns = lints_of(fs, "f64-value")
    assert warns and warns[0].severity == "warn" and warns[0].op_name == "w64"


def test_int_float_mix():
    i = feed("idx", (4,), np.int32)
    f = feed("valf", (4,), np.float32)
    mixed = ht.add_op(i, f)
    fs = analysis.analyze_graph([mixed])
    notes = lints_of(fs, "int-float-mix")
    assert notes and notes[0].op_name == mixed.name


def test_ps_op_without_ps_mode():
    g = feed("g", (4, 2))
    push = ht.parameterServerCommunicate_op(g)
    cfg = analysis.AnalysisConfig(comm_mode=None)
    fs = analysis.analyze_graph([push], config=cfg)
    errs = lints_of(fs, "ps-op-without-ps-mode")
    assert errs and errs[0].severity == "error" and errs[0].op_name == push.name
    # and the push input not being a gradient is its own warn
    assert lints_of(fs, "ps-push-ignored")


def test_ps_lookup_index_not_fed():
    table = ht.init.random_normal((10, 4), stddev=0.1, name="tbl",
                                  is_embed=True)
    raw = feed("rawidx", (6,), np.float32)
    derived = ht.relu_op(raw)  # NOT a feed/dataloader node
    lk = ht.embedding_lookup_op(table, derived)
    cfg = analysis.AnalysisConfig(comm_mode="PS")
    fs = analysis.analyze_graph([lk], config=cfg)
    errs = lints_of(fs, "ps-lookup-index-not-fed")
    assert errs and errs[0].severity == "error" and errs[0].op_name == lk.name


def test_allreduce_without_comm_mode():
    g = feed("g2", (4, 2))
    ar = ht.allreduceCommunicate_op(g)
    fs = analysis.analyze_graph([ar], config=analysis.AnalysisConfig())
    warns = lints_of(fs, "allreduce-without-comm-mode")
    assert warns and warns[0].severity == "warn" and warns[0].op_name == ar.name


def test_allreduce_degenerate():
    g = feed("g3", (4, 2))
    ar = ht.allreduceCommunicate_op(g)
    cfg = analysis.AnalysisConfig(comm_mode="AllReduce", dp_size=1)
    fs = analysis.analyze_graph([ar], config=cfg)
    assert lints_of(fs, "allreduce-degenerate")


def test_comm_quant_forced_small():
    """Seeded defect: a force-listed param below the exemption threshold is
    quantized anyway — the comm_quant lint must warn, with provenance on
    the AllReduce marker (docs/COMM_QUANT.md exemption policy)."""
    from hetu_tpu.comm_quant import QuantPolicy
    w = ht.Variable(name="w_small_q", value=np.ones((4, 2), np.float32))
    g = feed("gq", (4, 2))
    ar = ht.allreduceCommunicate_op(g, param_node=w)
    cfg = analysis.AnalysisConfig(
        comm_mode="AllReduce", dp_size=8,
        comm_quant_policy=QuantPolicy("int8", force=("w_small_q",)))
    fs = analysis.analyze_graph([ar], config=cfg)
    warns = lints_of(fs, "comm-quant-forced-small")
    assert warns and warns[0].severity == "warn"
    assert warns[0].op_name == ar.name
    assert "w_small_q" in warns[0].message
    # without the override the small param is exempt: no finding
    cfg2 = analysis.AnalysisConfig(
        comm_mode="AllReduce", dp_size=8,
        comm_quant_policy=QuantPolicy("int8"))
    assert not lints_of(analysis.analyze_graph([ar], config=cfg2),
                        "comm-quant-forced-small")


def test_comm_quant_no_error_feedback():
    """Seeded defect: int8 AllReduce with error feedback disabled notes the
    accumulating-compression-error hazard (once per graph)."""
    from hetu_tpu.comm_quant import QuantPolicy
    w = ht.Variable(name="w_big_q",
                    value=np.ones((64, 64), np.float32))
    g = feed("gq2", (64, 64))
    ar = ht.allreduceCommunicate_op(g, param_node=w)
    cfg = analysis.AnalysisConfig(
        comm_mode="AllReduce", dp_size=8,
        comm_quant_policy=QuantPolicy("int8", min_size=1024,
                                      error_feedback=False))
    fs = analysis.analyze_graph([ar], config=cfg)
    notes = lints_of(fs, "comm-quant-no-error-feedback")
    assert len(notes) == 1 and notes[0].severity == "note"
    # with EF on (the default) the note disappears
    cfg2 = analysis.AnalysisConfig(
        comm_mode="AllReduce", dp_size=8,
        comm_quant_policy=QuantPolicy("int8", min_size=1024))
    assert not lints_of(analysis.analyze_graph([ar], config=cfg2),
                        "comm-quant-no-error-feedback")


def test_dispatch_rank_mismatch():
    w = ht.Variable(name="wd", value=np.ones((4, 4), np.float32))
    d = ht.dispatch(w, (1, 2, 1))  # rank 3 parts on a rank 2 input
    fs = analysis.analyze_graph([d])
    errs = lints_of(fs, "dispatch-rank-mismatch")
    assert errs and errs[0].severity == "error" and errs[0].op_name == d.name


def test_dispatch_no_mp_axis():
    w = ht.Variable(name="wd2", value=np.ones((4, 4), np.float32))
    d = ht.dispatch(w, (1, 2))
    cfg = analysis.AnalysisConfig(comm_mode="AllReduce", mesh=None)
    fs = analysis.analyze_graph([d], config=cfg)
    assert lints_of(fs, "dispatch-no-mp-axis")


def test_dispatch_grad_unpaired():
    g = feed("g4", (4, 2))
    dg = ht.dispatch_gradient(g, g)
    fs = analysis.analyze_graph([dg])
    warns = lints_of(fs, "dispatch-grad-unpaired")
    assert warns and warns[0].op_name == dg.name


def test_pipeline_send_unconsumed_and_stage_loop():
    x = feed("px", (4, 2))
    send = ht.pipeline_send_op(x, ctx=ht.cpu(0))
    fs = analysis.analyze_graph([send])
    assert lints_of(fs, "pipeline-send-unconsumed")

    # equal-but-distinct ctx literals (DeviceGroup value equality) — the
    # natural API usage for the seeded same-stage loop
    send2 = ht.pipeline_send_op(x, ctx=ht.cpu(0))
    recv2 = ht.pipeline_receive_op(send2, ctx=ht.cpu(0))
    assert recv2.raw_ctx is not send2.raw_ctx
    fs2 = analysis.analyze_graph([recv2])
    assert lints_of(fs2, "pipeline-stage-loop")
    assert not lints_of(fs2, "pipeline-send-unconsumed")
    # the receiver back-link registered on construction
    assert recv2 in send2.receivers


def test_pipeline_send_paired_outside_topo_not_flagged():
    """A receiver on another eval target (outside the analyzed topo) still
    consumes the send — the registered-receiver backlink prevents a false
    unconsumed warning."""
    x = feed("px3", (4, 2))
    send = ht.pipeline_send_op(x, ctx=ht.cpu(0))
    ht.pipeline_receive_op(send, ctx=ht.cpu(1))  # lives on another target
    fs = analysis.analyze_graph([send])          # recv NOT in this topo
    assert not lints_of(fs, "pipeline-send-unconsumed")


def test_pipeline_recv_source_note():
    x = feed("px2", (4, 2))
    plain = ht.relu_op(x)
    recv = ht.pipeline_receive_op(plain)
    fs = analysis.analyze_graph([recv])
    assert lints_of(fs, "pipeline-recv-source")


def test_dead_subgraph_needs_universe():
    with analysis.record_graph() as universe:
        x = feed("live_x", (4, 2))
        live = ht.relu_op(x)
        dead_tower = ht.sigmoid_op(ht.relu_op(x))  # built, never returned
    fs = analysis.GraphAnalyzer([live], universe=universe).run()
    dead = lints_of(fs, "dead-subgraph")
    assert len(dead) == 1                       # frontier only, not the cone
    assert dead[0].op_name == dead_tower.name
    # without a universe the check cannot run
    assert not lints_of(analysis.analyze_graph([live]), "dead-subgraph")


def test_common_subexpression():
    x = feed("cse_x", (4, 3))
    w = feed("cse_w", (3, 5))
    a = ht.matmul_op(x, w)
    b = ht.matmul_op(x, w)
    out = ht.add_op(a, b)
    fs = analysis.analyze_graph([out])
    notes = lints_of(fs, "common-subexpression")
    assert notes and a.name in notes[0].message


def test_insert_comm_leaves_graph_untouched():
    """Linting with insert_comm (hetulint's PS replay) must not mutate the
    builder's graph: a real Executor built afterwards with its OWN config
    has to insert its own comm ops and actually train."""
    x = ht.Variable(name="ic_x", trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.1, name="ic_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    opt_node = ht.optim.SGDOptimizer(0.1).minimize(loss)
    inputs_before = list(opt_node.inputs)

    fs = analysis.GraphAnalyzer(
        [loss, opt_node], config=analysis.AnalysisConfig(comm_mode="PS"),
        insert_comm=True).run()
    assert not any(f.severity == "error" for f in fs), fs
    assert opt_node.inputs == inputs_before          # graph restored
    assert opt_node._comm_inserted is False

    ex = ht.Executor([loss, opt_node], ctx=ht.cpu(0))  # no comm_mode
    before = np.asarray(ex.state["params"][id(w)]).copy()
    ex.run("default", feed_dict={x: np.ones((4, 8), np.float32)})
    after = np.asarray(ex.state["params"][id(w)])
    assert not np.array_equal(before, after), \
        "parameter did not train after linting — lint mutated the graph"


def test_insert_comm_infers_ps_tables_without_mutation():
    """The comm-insertion replay infers lookup-read tables as PS-resident:
    the staging-contract lint must fire even though the table never declared
    is_embed — and the inference must not leak onto the graph."""
    table = ht.init.random_normal((10, 4), stddev=0.1, name="inf_tbl")
    raw = feed("inf_raw", (6,), np.float32)
    lk = ht.embedding_lookup_op(table, ht.relu_op(raw))  # computed index
    loss = ht.reduce_mean_op(lk, [0, 1])
    opt_node = ht.optim.SGDOptimizer(0.1).minimize(loss)
    fs = analysis.GraphAnalyzer(
        [loss, opt_node], config=analysis.AnalysisConfig(comm_mode="PS"),
        insert_comm=True).run()
    errs = lints_of(fs, "ps-lookup-index-not-fed")
    assert errs and errs[0].op_name == lk.name
    assert getattr(table, "is_embed", False) is False  # graph pristine


def test_recompile_budget_zero_single_signature():
    ex, x = _train_executor("b0")
    ex.run("default", feed_dict={x: np.ones((4, 8), np.float32)})
    fs = analysis.recompile_findings(ex.subexecutors["default"], budget=0)
    assert len(fs) == 1  # one signature over a zero budget — no crash
    assert "1 distinct step programs" in fs[0].message


def test_suppression_node_and_analyzer_level():
    x = feed("sx", (4, 3))
    w = feed("sw", (4, 5))
    bad = ht.matmul_op(x, w)
    # node-level
    analysis.suppress(bad, "shape-mismatch")
    assert not lints_of(analysis.analyze_graph([bad]), "shape-mismatch")
    # analyzer-level
    bad2 = ht.matmul_op(x, w)
    fs = analysis.GraphAnalyzer([bad2], suppress=["shape-mismatch"]).run()
    assert not lints_of(fs, "shape-mismatch")
    assert lints_of(analysis.analyze_graph([bad2]), "shape-mismatch")


# ---------------------------------------------------------------------------
# Executor integration
# ---------------------------------------------------------------------------

def test_executor_lint_error_raises():
    bad = ht.matmul_op(feed("ex", (4, 3)), feed("ew", (4, 5)))
    with pytest.raises(analysis.GraphValidationError) as ei:
        ht.Executor([bad], ctx=ht.cpu(0), lint="error")
    assert any(f.lint == "shape-mismatch" for f in ei.value.findings)
    assert bad.name in str(ei.value)


def test_executor_lint_warn_builds():
    bad = ht.matmul_op(feed("ex2", (4, 3)), feed("ew2", (4, 5)))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        ex = ht.Executor([bad], ctx=ht.cpu(0), lint="warn")
    assert ex is not None
    assert any("shape-mismatch" in str(w.message) for w in rec)


def test_executor_lint_error_clean_graph_runs():
    a = feed("ca", (4, 3))
    b = feed("cb", (3, 5))
    out = ht.matmul_op(a, b)
    ex = ht.Executor([out], ctx=ht.cpu(0), lint="error")
    assert ex.run("default")[0].asnumpy().shape == (4, 5)


def test_executor_lint_env_var(monkeypatch):
    monkeypatch.setenv("HETU_LINT", "error")
    bad = ht.matmul_op(feed("vx", (4, 3)), feed("vw", (4, 5)))
    with pytest.raises(analysis.GraphValidationError):
        ht.Executor([bad], ctx=ht.cpu(0))


# ---------------------------------------------------------------------------
# Tier B: lowered-program checks
# ---------------------------------------------------------------------------

def _train_executor(name, ctx=None, **kwargs):
    x = ht.Variable(name=f"{name}_x", trainable=False)
    w = ht.init.random_normal((8, 4), stddev=0.1, name=f"{name}_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor([loss, train_op], ctx=ctx or ht.cpu(0), **kwargs)
    return ex, x


def test_recompile_detector_churn_vs_fixed():
    # signature-churning loop: a new batch size every step
    ex, x = _train_executor("churn")
    sub = ex.subexecutors["default"]
    for n in (2, 3, 4, 5, 6):
        ex.run("default", feed_dict={x: np.ones((n, 8), np.float32)})
    fs = analysis.recompile_findings(sub, budget=3)
    assert len(fs) == 1 and fs[0].severity == "warn"
    assert "5 distinct step programs" in fs[0].message
    assert "feed signature" in fs[0].message  # churn component identified

    # fixed-shape loop: same budget, no finding
    ex2, x2 = _train_executor("fixed")
    for _ in range(5):
        ex2.run("default", feed_dict={x2: np.ones((4, 8), np.float32)})
    assert not analysis.recompile_findings(ex2.subexecutors["default"],
                                           budget=3)


def test_recompile_monitor_reports_growth_once():
    ex, x = _train_executor("mon")
    mon = analysis.RecompileMonitor(ex, budget=2)
    for n in (2, 3, 4, 5):
        ex.run("default", feed_dict={x: np.ones((n, 8), np.float32)})
    assert len(mon.check()) == 1
    assert len(mon.check()) == 0        # no growth since last check
    ex.run("default", feed_dict={x: np.ones((9, 8), np.float32)})
    assert len(mon.check()) == 1        # re-reported on growth


def test_donation_present_and_missing(monkeypatch):
    ex, x = _train_executor("don")
    ex.run("default", feed_dict={x: np.ones((4, 8), np.float32)})
    assert not analysis.donation_findings(ex.subexecutors["default"])

    monkeypatch.setenv("HETU_NO_DONATE", "1")
    ex2, x2 = _train_executor("nodon")
    ex2.run("default", feed_dict={x2: np.ones((4, 8), np.float32)})
    fs = analysis.donation_findings(ex2.subexecutors["default"])
    assert len(fs) == 1 and fs[0].lint == "donation-missing"


def test_host_transfer_detected():
    import jax

    def noisy(v):
        jax.debug.print("v {}", v[0, 0])
        return v

    x = feed("ht_x", (2, 2))
    op = FunctionalOp("Noisy", noisy, [x])
    ex = ht.Executor([op], ctx=ht.cpu(0))
    ex.run("default", feed_dict={x: np.ones((2, 2), np.float32)})
    fs = analysis.host_transfer_findings(ex.subexecutors["default"])
    assert fs and fs[0].lint == "host-transfer"

    # clean program: no finding
    y = feed("ht_y", (2, 2))
    ex2 = ht.Executor([ht.relu_op(y)], ctx=ht.cpu(0))
    ex2.run("default", feed_dict={y: np.ones((2, 2), np.float32)})
    assert not analysis.host_transfer_findings(ex2.subexecutors["default"])


def test_replicated_large_tensor():
    x = ht.Variable(name="rep_x", trainable=False)
    w = ht.init.random_normal((64, 32), stddev=0.1, name="rep_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    ex = ht.Executor([loss, train_op], ctx=[ht.cpu(0), ht.cpu(1)],
                     comm_mode="AllReduce")
    ex.run("default", feed_dict={x: np.ones((4, 64), np.float32)})
    sub = ex.subexecutors["default"]
    fs = analysis.replicated_tensor_findings(sub, threshold_bytes=1024)
    assert len(fs) == 1 and fs[0].op_name == "rep_w"
    assert "2-way dp axis" in fs[0].message
    # above the real size: silent
    assert not analysis.replicated_tensor_findings(sub,
                                                   threshold_bytes=1 << 30)
    # cost analysis is normalized to a dict on this jax
    assert isinstance(analysis.cost_analysis_of(sub), dict)


def test_analyze_executor_aggregates():
    ex, x = _train_executor("agg")
    for n in (2, 3, 4, 5, 6):
        ex.run("default", feed_dict={x: np.ones((n, 8), np.float32)})
    fs = analysis.analyze_executor(ex, budget=3)
    assert any(f.lint == "recompile-budget" for f in fs)


# ---------------------------------------------------------------------------
# CLI smoke (tier-1 fast): bundled example graphs lint clean
# ---------------------------------------------------------------------------

def test_hetulint_cli_json_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--json",
         "hetu_tpu.analysis.examples:build_mlp",
         "hetu_tpu.analysis.examples:build_transformer",
         "hetu_tpu.analysis.examples:build_ctr_ps"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"]
    assert len(report["results"]) == 3
    for res in report["results"]:
        assert res["ok"], res
        assert res["counts"]["error"] == 0
        for f in res["findings"]:  # any finding still carries provenance
            assert f["lint"] and f["severity"] and f["op"]


def test_hetulint_cli_catches_seeded_defect(tmp_path):
    bad = tmp_path / "badgraph.py"
    bad.write_text(
        "import numpy as np\nimport hetu_tpu as ht\n"
        "def build():\n"
        "    a = ht.Variable(name='a', value=np.ones((4, 3), np.float32))\n"
        "    b = ht.Variable(name='b', value=np.ones((4, 5), np.float32))\n"
        "    return [ht.matmul_op(a, b)]\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--json",
         f"{bad}:build"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert not report["ok"]
    finding = report["results"][0]["findings"][0]
    assert finding["lint"] == "shape-mismatch"
    assert finding["op"].startswith("MatMul")


def test_hetulint_cli_per_target_ok_respects_fail_on(tmp_path):
    """--fail-on warn: a warn-only target must report ok=false in the JSON,
    matching the exit status."""
    warn_only = tmp_path / "warn_only.py"
    warn_only.write_text(
        "import numpy as np\nimport hetu_tpu as ht\n"
        "def build():\n"
        "    w = ht.Variable(name='w64', value=np.ones((2, 2)),\n"
        "                    dtype=np.float64)\n"           # f64-value warn
        "    return [ht.relu_op(w)]\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    base = [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--json",
            f"{warn_only}:build"]
    strict = subprocess.run(base + ["--fail-on", "warn"],
                            capture_output=True, text=True, env=env,
                            cwd=REPO, timeout=300)
    assert strict.returncode == 1
    rep = json.loads(strict.stdout)
    assert not rep["ok"] and not rep["results"][0]["ok"]
    lax = subprocess.run(base, capture_output=True, text=True, env=env,
                         cwd=REPO, timeout=300)  # default --fail-on error
    assert lax.returncode == 0
    rep = json.loads(lax.stdout)
    assert rep["ok"] and rep["results"][0]["ok"]


def test_hetulint_cli_json_survives_broken_builder(tmp_path):
    """A failing builder must still emit a well-formed --json report (with
    the partial results) on stdout, exit 2."""
    broken = tmp_path / "broken.py"
    broken.write_text("def build():\n    raise RuntimeError('boom')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hetulint"), "--json",
         "hetu_tpu.analysis.examples:build_mlp", f"{broken}:build"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert proc.returncode == 2
    report = json.loads(proc.stdout)     # stdout stays machine-readable
    assert not report["ok"]
    assert report["results"][0]["ok"]    # the good target's result kept
    assert "boom" in report["results"][1]["error"]
    assert "boom" in proc.stderr


# ---------------------------------------------------------------------------
# graphboard annotation
# ---------------------------------------------------------------------------

def test_graphboard_lint_annotation(tmp_path):
    bad = ht.matmul_op(feed("gx", (4, 3)), feed("gw", (4, 5)))
    ex = ht.Executor([bad], ctx=ht.cpu(0), lint="off")
    out = ht.graphboard.render(ex, out_dir=str(tmp_path), lint=True)
    html_text = open(os.path.join(out, "index.html")).read()
    assert "hetulint findings" in html_text
    assert "shape-mismatch" in html_text
    svg = open(os.path.join(out, "output.svg")).read()
    assert "<title>" in svg          # tooltip on the offending node
    dot = open(os.path.join(out, "output.dot")).read()
    assert "tooltip=" in dot


def test_kernels_force_ineligible():
    """Seeded defect (hetukern, docs/KERNELS.md): kernels='force' over an
    optimizer whose parameter cannot take the fused kernel (declared
    float64 — the fused apply is f32-master-precision only) must error at
    define time with provenance on the optimizer node, instead of raising
    a KernelEligibilityError deep inside the jit trace. Odd SIZES are
    fine — the elementwise kernels pad to the tile internally."""
    x = feed("xk", (4, 7))
    w = ht.Variable(name="w_f64_k", value=np.ones((7, 7), np.float64),
                    dtype=np.float64)
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    opt = ht.optim.AdamOptimizer(0.01).minimize(loss)
    cfg = analysis.AnalysisConfig(kernels="force")
    fs = analysis.analyze_graph([loss, opt], config=cfg)
    errs = lints_of(fs, "kernels-force-ineligible")
    assert errs and errs[0].severity == "error"
    assert errs[0].op_name == opt.name
    assert "fused_adam" in errs[0].message and "w_f64_k" in errs[0].message
    # an f32 parameter is eligible regardless of shape: no finding
    x2 = feed("xk2", (4, 7))
    w2 = ht.Variable(name="w_ok_k", value=np.ones((7, 7), np.float32))
    loss2 = ht.reduce_mean_op(ht.matmul_op(x2, w2), [0, 1])
    opt2 = ht.optim.AdamOptimizer(0.01).minimize(loss2)
    assert not lints_of(analysis.analyze_graph([loss2, opt2], config=cfg),
                        "kernels-force-ineligible")
    # and with kernels unset/off the pass stays silent even on the bad one
    assert not lints_of(analysis.analyze_graph([loss, opt],
                                               config=analysis.AnalysisConfig()),
                        "kernels-force-ineligible")


def test_kernels_force_ineligible_embed_grad():
    """Seeded defect: a forced fused_embed_grad over a non-lane-aligned
    embedding width (dim 20) errors with the kernel's reason."""
    vec = feed("vk", (16, 20))
    idx = feed("ik", (16,), np.int64)
    g = ht.embedding_lookup_gradient_op(vec, idx, (100, 20))
    cfg = analysis.AnalysisConfig(kernels="force")
    fs = analysis.analyze_graph([g], config=cfg)
    errs = lints_of(fs, "kernels-force-ineligible")
    assert errs and errs[0].op_name == g.name
    assert "fused_embed_grad" in errs[0].message


def test_kernels_auto_fallback_note(monkeypatch):
    """Seeded defect: under kernels='auto' ON A TPU BACKEND, a kernel
    whose dispatches mostly fell back gets the silent-fallback note (on
    CPU the fallback is the design and must stay silent)."""
    import jax.numpy as jnp
    from hetu_tpu.kernels import registry

    registry.reset_stats()
    try:
        with registry.active("auto"):
            # ineligible shape (dim 20): every dispatch falls back
            for _ in range(3):
                registry.dispatch(
                    "fused_embed_grad",
                    jnp.ones((16, 20), jnp.float32),
                    jnp.zeros((16,), jnp.int32))
        x = feed("xkf", (4, 4))
        g = ht.relu_op(x)
        cfg = analysis.AnalysisConfig(kernels="auto")
        # CPU backend: silent by design
        assert not lints_of(analysis.analyze_graph([g], config=cfg),
                            "kernels-auto-fallback")
        # pretend-TPU: the note names the kernel and the ratio
        monkeypatch.setattr("hetu_tpu.kernels.registry._on_tpu",
                            lambda: True)
        notes = lints_of(analysis.analyze_graph([g], config=cfg),
                         "kernels-auto-fallback")
        assert len(notes) == 1 and notes[0].severity == "note"
        assert "fused_embed_grad" in notes[0].message
    finally:
        registry.reset_stats()


def test_ps_push_ignored_embed_grad_route():
    """The hetukern rows route only suppresses ps-push-ignored when the
    executor would actually wire the push: a resolvable sparse target, the
    push as sole consumer, not an eval target. A typo'd ps_id (or a second
    consumer) keeps the warning."""
    from hetu_tpu.comm_quant import QuantPolicy  # noqa: F401 (idiom parity)
    vocab, dim = 20, 8
    cfg = analysis.AnalysisConfig(comm_mode="PS")

    def build(name, ps_id=None, extra_consumer=False):
        table = ht.init.zeros((vocab, dim), name=name, is_embed=True)
        # true fed placeholders (no value): the PS staging contract
        # requires the lookup index host-side
        idx = ht.Variable(name=f"pi_{name}", dtype=np.int64,
                          trainable=False)
        vec = ht.Variable(name=f"pv_{name}", trainable=False)
        look = ht.embedding_lookup_op(table, idx)
        g = ht.embedding_lookup_gradient_op(vec, idx, (vocab, dim))
        push = ht.parameterServerCommunicate_op(
            g, ps_id=name if ps_id is None else ps_id)
        nodes = [ht.reduce_mean_op(look, [0, 1]), push]
        if extra_consumer:
            nodes.append(ht.reduce_mean_op(g, [0, 1]))
        return nodes

    # wired route: sole-consumer push with a resolvable ps_id — no warn
    ok_nodes = build("t_good")
    assert not lints_of(analysis.analyze_graph(ok_nodes, config=cfg),
                        "ps-push-ignored")
    # typo'd ps_id: the executor will silently drop this push — warn
    bad = build("t_typo", ps_id="no_such_param")
    assert lints_of(analysis.analyze_graph(bad, config=cfg),
                    "ps-push-ignored")
    # second consumer: the executor keeps the op dense and never wires
    # the push (ps_param_node unset) — warn
    multi = build("t_multi", extra_consumer=True)
    assert lints_of(analysis.analyze_graph(multi, config=cfg),
                    "ps-push-ignored")


def test_plan_divergence_seeded_defect():
    """hetuplan (docs/ANALYSIS.md Tier C): a running config whose declared
    comm strategy contradicts the planner's cost-model choice gets a
    plan-divergence warning with provenance — the seeded defect is a CTR
    graph (sparse table + dense towers, planner chooses Hybrid) declared
    comm_mode='AllReduce' by hand."""
    from hetu_tpu.analysis.examples import build_ctr_ps
    graph, _declared = build_ctr_ps()
    bad_cfg = analysis.AnalysisConfig(comm_mode="AllReduce")
    plan = analysis.plan_graph(graph, config=bad_cfg, devices=8)
    assert plan.comm_mode == "Hybrid"
    divs = [f for f in plan.findings(config=bad_cfg)
            if f.lint == "plan-divergence"]
    assert divs and divs[0].severity == "warn"
    assert divs[0].op_name is not None          # op-level provenance
    assert "'AllReduce'" in divs[0].message
    # suppression works like every other lint
    from hetu_tpu.analysis.findings import is_suppressed
    assert all(is_suppressed(f, ("plan-divergence",)) for f in divs)
