"""Distributed (sharded) checkpoint/resume for the flagship path:
save on one mesh, restore on ANOTHER mesh with different specs (resharding
on load), step-numbered retention, and exact training-resume equivalence.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu import checkpoint
from hetu_tpu.models import transformer as tfm
from hetu_tpu.parallel.mesh import auto_mesh

CFG = tfm.TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32, remat=False)


def test_save_restore_across_meshes(tmp_path):
    """Params saved dp-sharded restore correctly tp-sharded (new mesh)."""
    mesh_a = auto_mesh(8)            # all dp
    params = tfm.init_params(jax.random.PRNGKey(0), CFG)
    params = tfm.shard_params(params, CFG, mesh_a)
    checkpoint.save(tmp_path / "ck", params)

    mesh_b = auto_mesh(8, tp=2)      # dp4 x tp2 — different layout
    specs = tfm.param_specs(CFG)
    restored = checkpoint.restore(tmp_path / "ck", like=params,
                                  mesh=mesh_b, specs=specs)
    # values identical, shardings re-applied on the new mesh
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    wqkv = restored["blocks"]["wqkv"]
    assert wqkv.sharding.mesh.shape["tp"] == 2
    assert wqkv.sharding.spec == P(None, None, "tp")


def test_raw_restore_without_target(tmp_path):
    # 0-d arrays, not numpy scalars: orbax's standard handler rejects
    # np.int32(7)-style scalar instances
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
             "step": np.asarray(7, np.int32)}
    checkpoint.save(tmp_path / "raw", state)
    # refuses to clobber by default; force=True overwrites in place
    with pytest.raises(ValueError):
        checkpoint.save(tmp_path / "raw", state)
    checkpoint.save(tmp_path / "raw", {"w": state["w"] * 2,
                                       "step": np.asarray(8, np.int32)},
                    force=True)
    out = checkpoint.restore(tmp_path / "raw")
    np.testing.assert_array_equal(out["w"], state["w"] * 2)
    assert int(out["step"]) == 8


def test_manager_raw_restore_without_target(tmp_path):
    with checkpoint.TrainCheckpointer(tmp_path / "m", keep=2) as ck:
        ck.save_step(5, {"w": np.ones((2, 2), np.float32) * 3})
    with checkpoint.TrainCheckpointer(tmp_path / "m", keep=2) as ck:
        out, step = ck.restore_latest()
        assert step == 5
        np.testing.assert_array_equal(out["w"],
                                      np.ones((2, 2), np.float32) * 3)


def test_train_resume_is_exact(tmp_path):
    """Train 4 steps, checkpoint, train 4 more; vs restore-at-4 + 4 more:
    identical final loss/params — the resume path loses nothing."""
    mesh = auto_mesh(8, tp=2)
    step_fn = tfm.make_train_step(CFG, mesh=mesh, lr=1e-2)
    rng = np.random.RandomState(0)
    toks = [jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32)
            for _ in range(8)]

    params = tfm.shard_params(tfm.init_params(jax.random.PRNGKey(1), CFG),
                              CFG, mesh)
    opt = tfm.init_opt_state(params)
    with checkpoint.TrainCheckpointer(tmp_path / "mgr", keep=2) as ck:
        for i in range(4):
            loss, params, opt = step_fn(params, opt, toks[i],
                                        jnp.roll(toks[i], -1, 1))
            ck.save_step(i, {"params": params, "opt": opt})
        assert ck.latest_step() == 3
        for i in range(4, 8):
            loss, params, opt = step_fn(params, opt, toks[i],
                                        jnp.roll(toks[i], -1, 1))
        straight_loss = float(loss)

    specs = tfm.param_specs(CFG)
    opt_specs = {"m": specs, "v": specs, "t": P()}
    with checkpoint.TrainCheckpointer(tmp_path / "mgr", keep=2) as ck:
        like = {"params": params, "opt": opt}
        state, step = ck.restore_latest(
            like=like, mesh=mesh, specs={"params": specs, "opt": opt_specs})
        assert step == 3
        params2, opt2 = state["params"], state["opt"]
        for i in range(4, 8):
            loss2, params2, opt2 = step_fn(params2, opt2, toks[i],
                                           jnp.roll(toks[i], -1, 1))
    assert float(loss2) == pytest.approx(straight_loss, rel=1e-6)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
