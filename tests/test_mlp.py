"""End-to-end MLP training (SURVEY.md §7 step 2: the minimum slice).

Builds the reference examples/cnn MLP pattern through dataloaders + Executor
and checks the loss decreases and accuracy beats chance by a wide margin.
"""
import numpy as np

import hetu_tpu as ht


def fc(x, shape, name, with_relu=True):
    weight = ht.init.random_normal(shape=shape, stddev=0.1, name=name + "_weight")
    bias = ht.init.random_normal(shape=shape[-1:], stddev=0.1, name=name + "_bias")
    x = ht.matmul_op(x, weight)
    x = x + ht.broadcastto_op(bias, x)
    if with_relu:
        x = ht.relu_op(x)
    return x


def test_mlp_convergence():
    train_x, train_y = ht.data._synthetic_classification(2048, (32,), 10, seed=42)
    train_y = ht.data.convert_to_one_hot(train_y, 10)

    batch_size = 128
    x = ht.dataloader_op([ht.Dataloader(train_x, batch_size, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(train_y, batch_size, "train")])

    h = fc(x, (32, 64), "fc1")
    h = fc(h, (64, 64), "fc2")
    y = fc(h, (64, 10), "fc3", with_relu=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    opt = ht.optim.SGDOptimizer(learning_rate=0.1)
    train_op = opt.minimize(loss)

    ex = ht.Executor({"train": [loss, y, y_, train_op]}, ctx=ht.cpu(0))
    n_batches = ex.get_batch_num("train")
    assert n_batches == 16

    first_epoch_loss, last_epoch_loss = None, None
    for epoch in range(8):
        losses, correct = [], []
        for _ in range(n_batches):
            lv, yv, ytv, _ = ex.run("train")
            losses.append(float(lv.asnumpy()))
            correct.extend(np.argmax(yv.asnumpy(), 1) == np.argmax(ytv.asnumpy(), 1))
        if epoch == 0:
            first_epoch_loss = np.mean(losses)
        last_epoch_loss = np.mean(losses)
        acc = np.mean(correct)
    assert last_epoch_loss < first_epoch_loss * 0.5, \
        f"loss did not halve: {first_epoch_loss} -> {last_epoch_loss}"
    assert acc > 0.8, f"accuracy too low: {acc}"


def test_mlp_validate_subexecutor():
    train_x, train_y = ht.data._synthetic_classification(512, (16,), 4, seed=7)
    train_y1h = ht.data.convert_to_one_hot(train_y, 4)
    x = ht.dataloader_op([ht.Dataloader(train_x, 64, "train"),
                          ht.Dataloader(train_x, 64, "validate")])
    y_ = ht.dataloader_op([ht.Dataloader(train_y1h, 64, "train"),
                           ht.Dataloader(train_y1h, 64, "validate")])
    y = fc(x, (16, 4), "lin", with_relu=False)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(y, y_), [0])
    opt = ht.optim.SGDOptimizer(0.2)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op], "validate": [loss, y, y_]},
                     ctx=ht.cpu(0))
    for _ in range(3 * ex.get_batch_num("train")):
        ex.run("train")
    vloss = np.mean([float(ex.run("validate")[0].asnumpy())
                     for _ in range(ex.get_batch_num("validate"))])
    assert vloss < 1.0
