"""X2hetu TF-GraphDef importer (reference python/hetu/onnx/X2hetu/handler.py).

TF itself is not installable here, so the test AUTHORS a GraphDef with the
same hand-written protobuf codec the importer parses — which also proves the
wire format round-trips (encode -> bytes -> decode) against the real TF
field numbers.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.onnx import x2hetu as x2


def _const_node(name, arr):
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): x2.DT_FLOAT,
          np.dtype(np.int32): x2.DT_INT32,
          np.dtype(np.int64): x2.DT_INT64}[arr.dtype]
    t = x2.TfTensor(
        dtype=dt,
        tensor_shape=x2.TfTensorShape(
            dim=[x2.TfDim(size=int(s)) for s in arr.shape]),
        tensor_content=arr.tobytes())
    return x2.TfNodeDef(name=name, op="Const", attr=[
        x2.TfAttrEntry(key="dtype", value=x2.TfAttrValue(type=dt)),
        x2.TfAttrEntry(key="value", value=x2.TfAttrValue(tensor=t))])


def _mlp_graphdef(w1, b1, w2, b2):
    n = [
        x2.TfNodeDef(name="x", op="Placeholder", attr=[
            x2.TfAttrEntry(key="dtype",
                           value=x2.TfAttrValue(type=x2.DT_FLOAT))]),
        _const_node("w1", w1),
        _const_node("b1", b1),
        _const_node("w2", w2),
        _const_node("b2", b2),
        _const_node("flat_shape", np.asarray([-1, w1.shape[0]], np.int32)),
        x2.TfNodeDef(name="flat", op="Reshape",
                     input=["x", "flat_shape"]),
        x2.TfNodeDef(name="h1", op="MatMul", input=["flat", "w1"]),
        x2.TfNodeDef(name="h1b", op="BiasAdd", input=["h1", "b1"]),
        x2.TfNodeDef(name="h1r", op="Relu", input=["h1b"]),
        x2.TfNodeDef(name="id", op="Identity", input=["h1r"]),
        x2.TfNodeDef(name="logits", op="MatMul", input=["id", "w2"]),
        x2.TfNodeDef(name="logitsb", op="AddV2", input=["logits", "b2"]),
        x2.TfNodeDef(name="probs", op="Softmax", input=["logitsb"]),
    ]
    return x2.TfGraphDef(node=n)


def test_import_frozen_mlp_matches_numpy(tmp_path):
    rng = np.random.RandomState(0)
    w1 = rng.randn(12, 8).astype(np.float32)
    b1 = rng.randn(8).astype(np.float32)
    w2 = rng.randn(8, 4).astype(np.float32)
    b2 = rng.randn(4).astype(np.float32)
    path = str(tmp_path / "mlp.pb")
    x2.save_graphdef(_mlp_graphdef(w1, b1, w2, b2), path)

    nodes = x2.tf2hetu(path)   # parse from DISK: full wire round trip
    ex = ht.Executor([nodes["probs"]], ctx=ht.cpu(0))
    x = rng.randn(5, 3, 4).astype(np.float32)   # reshaped to (5, 12) inside
    out = ex.run(feed_dict={nodes["x"]: x},
                 convert_to_numpy_ret_vals=True)[0]

    h = np.maximum(x.reshape(5, 12) @ w1 + b1, 0.0)
    logits = h @ w2 + b2
    e = np.exp(logits - logits.max(1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(1, keepdims=True),
                               rtol=1e-5, atol=1e-6)


def test_import_elementwise_and_transpose():
    rng = np.random.RandomState(1)
    a = rng.randn(6, 6).astype(np.float32)
    g = x2.TfGraphDef(node=[
        x2.TfNodeDef(name="x", op="Placeholder"),
        _const_node("a", a),
        # y = tanh(x @ a^T) * x - x  (exercises transpose_b, Mul, Sub)
        x2.TfNodeDef(name="mm", op="MatMul", input=["x", "a"], attr=[
            x2.TfAttrEntry(key="transpose_b", value=x2.TfAttrValue(b=1))]),
        x2.TfNodeDef(name="t", op="Tanh", input=["mm"]),
        x2.TfNodeDef(name="m", op="Mul", input=["t", "x"]),
        x2.TfNodeDef(name="y", op="Sub", input=["m", "x"]),
    ])
    nodes = x2.tf2hetu(g.SerializeToString())
    ex = ht.Executor([nodes["y"]], ctx=ht.cpu(0))
    x = rng.randn(3, 6).astype(np.float32)
    out = ex.run(feed_dict={nodes["x"]: x},
                 convert_to_numpy_ret_vals=True)[0]
    ref = np.tanh(x @ a.T) * x - x
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_unsupported_op_raises_with_inventory():
    g = x2.TfGraphDef(node=[
        x2.TfNodeDef(name="q", op="SomeExoticOp")])
    with pytest.raises(NotImplementedError, match="SomeExoticOp"):
        x2.tf2hetu(g.SerializeToString())
