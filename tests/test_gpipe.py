"""Graph-API GPipe pipeline (Executor(..., gpipe=True)) on virtual devices.

Reference: ``SubExecutor4Gpipe`` (gpu_ops/executor.py:435-767) and the
``examples/runner/parallel/gpipe.py`` user surface: per-stage
``ht.context(...)`` blocks, run() on a LIST of microbatch feed_dicts,
optimizer applied once after all microbatches. Correctness oracle (which the
reference never had): the pipeline step must match a single-device step on
the concatenated batch exactly.
"""
import numpy as np
import pytest

import hetu_tpu as ht


def _build_mlp(stage_ctxs):
    """4-layer MLP, one layer per stage context (None = single device)."""
    rng = np.random.RandomState(0)
    dims = [20, 32, 32, 16, 10]
    ws = [(rng.randn(dims[i], dims[i + 1]) * 0.2).astype(np.float32)
          for i in range(4)]

    def fc(h, i, ctx):
        w = ht.Variable(f"w{i}", value=ws[i].copy(), ctx=ctx)
        h = ht.matmul_op(h, w, ctx=ctx)
        return ht.relu_op(h, ctx=ctx) if i < 3 else h

    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    h = x
    var_nodes = []
    for i in range(4):
        ctx = stage_ctxs[i] if stage_ctxs else None
        h = fc(h, i, ctx)
    last_ctx = stage_ctxs[-1] if stage_ctxs else None
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(h, y_, ctx=last_ctx), [0], ctx=last_ctx)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return x, y_, loss, train_op


def _data(n, seed):
    rng = np.random.RandomState(seed)
    xv = rng.randn(n, 20).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return xv, yv


def test_gpipe_matches_single_device():
    M, mb = 4, 8
    xv, yv = _data(M * mb, seed=3)

    # oracle: one device, full concatenated batch, mean loss
    x, y_, loss, train_op = _build_mlp(None)
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=5)
    oracle_losses, oracle_params = [], None
    for _ in range(3):
        lv, _ = ex1.run("train", feed_dict={x: xv, y_: yv},
                        convert_to_numpy_ret_vals=True)
        oracle_losses.append(float(np.mean(lv)))
    oracle_params = [np.asarray(v) for v in ex1.state["params"].values()]

    # pipeline: 4 stages on 4 devices, M microbatches
    ctxs = [ht.cpu(i) for i in range(4)]
    x, y_, loss, train_op = _build_mlp(ctxs)
    exp = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    pipe_losses = []
    for _ in range(3):
        fdl = [{x: xv[m * mb:(m + 1) * mb], y_: yv[m * mb:(m + 1) * mb]}
               for m in range(M)]
        ret = exp.run("train", feed_dict=fdl, convert_to_numpy_ret_vals=True)
        # per-microbatch losses; their mean is the full-batch mean
        pipe_losses.append(float(np.mean([np.mean(v) for v in ret[0]])))
    pipe_params = [np.asarray(v) for v in exp.state["params"].values()]

    np.testing.assert_allclose(oracle_losses, pipe_losses,
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(oracle_params, pipe_params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def _build_mlp_dataloaders(stage_ctxs, xv, yv, mb):
    """The same 4-stage MLP fed by dataloader nodes instead of
    placeholders."""
    rng = np.random.RandomState(0)
    dims = [20, 32, 32, 16, 10]
    ws = [(rng.randn(dims[i], dims[i + 1]) * 0.2).astype(np.float32)
          for i in range(4)]
    x = ht.dataloader_op([ht.Dataloader(xv, mb, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(yv, mb, "train")])
    h = x
    for i in range(4):
        ctx = stage_ctxs[i]
        w = ht.Variable(f"w{i}", value=ws[i].copy(), ctx=ctx)
        h = ht.matmul_op(h, w, ctx=ctx)
        if i < 3:
            h = ht.relu_op(h, ctx=ctx)
    last_ctx = stage_ctxs[-1]
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(h, y_, ctx=last_ctx), [0], ctx=last_ctx)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return loss, train_op


def test_gpipe_dataloader_feeds_match_explicit_feed_list():
    """Dataloader-fed gpipe (round 5; the reference's gpipe is
    feed-list-only): run() with no feeds pulls gpipe_microbatches batches
    per loader per step and matches the explicit feed-list run exactly."""
    M, mb = 4, 8
    xv, yv = _data(M * mb, seed=3)
    ctxs = [ht.cpu(i) for i in range(4)]

    x, y_, loss, train_op = _build_mlp(ctxs)
    ref = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    ref_losses = []
    for _ in range(3):   # data cycles: every step feeds the same epoch
        fdl = [{x: xv[m * mb:(m + 1) * mb], y_: yv[m * mb:(m + 1) * mb]}
               for m in range(M)]
        ret = ref.run("train", feed_dict=fdl, convert_to_numpy_ret_vals=True)
        ref_losses.append(float(np.mean([np.mean(v) for v in ret[0]])))

    loss, train_op = _build_mlp_dataloaders(ctxs, xv, yv, mb)
    exd = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5,
                      gpipe_microbatches=M)
    dl_losses = []
    for _ in range(3):
        ret = exd.run("train", convert_to_numpy_ret_vals=True)
        dl_losses.append(float(np.mean([np.mean(v) for v in ret[0]])))

    np.testing.assert_allclose(dl_losses, ref_losses, rtol=1e-5, atol=1e-6)
    # epoch accounting: each step consumes M batches per loader, so
    # steps-per-epoch is batch_num // M (here: one epoch per step)
    assert exd.get_batch_num("train") == 1

    # forgetting gpipe_microbatches fails loudly, not with a hang/guess
    loss2, train_op2 = _build_mlp_dataloaders(ctxs, xv, yv, mb)
    exn = ht.Executor({"train": [loss2, train_op2]}, gpipe=True, seed=5)
    with pytest.raises(ValueError, match="gpipe_microbatches"):
        exn.run("train")


def test_gpipe_stage_devices_distinct():
    ctxs = [ht.cpu(i) for i in range(4)]
    x, y_, loss, train_op = _build_mlp(ctxs)
    exp = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    sub = exp.subexecutors["train"]
    devs = [st.device for st in sub.stages]
    assert len(set(devs)) == 4, devs
    # params live on their stage's device after a step
    xv, yv = _data(8, seed=1)
    exp.run("train", feed_dict=[{x: xv, y_: yv}])
    for st in sub.stages:
        for node in st.param_nodes:
            assert exp.state["params"][id(node)].devices() == {st.device}


def test_gpipe_dropout_trains_and_eval_is_deterministic():
    """Dropout under the graph-API pipeline (reference: dropout works in any
    placement, gpu_ops/Dropout.py): per-(microbatch, stage) rng keys give
    distinct masks, training still converges on a separable task, and a
    forward-only validate entry (training=False) is mask-free: two runs
    agree exactly."""
    M, mb = 2, 16
    rng = np.random.RandomState(4)
    w_true = rng.randn(20, 10).astype(np.float32)
    xv = rng.randn(M * mb * 4, 20).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[(xv @ w_true).argmax(1)]

    ctx0, ctx1 = ht.cpu(0), ht.cpu(1)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    w1 = ht.Variable("w1", value=(rng.randn(20, 64) * 0.2).astype(np.float32),
                     ctx=ctx0)
    h = ht.relu_op(ht.matmul_op(x, w1, ctx=ctx0), ctx=ctx0)
    h = ht.dropout_op(h, 0.8, ctx=ctx0)
    w2 = ht.Variable("w2", value=(rng.randn(64, 10) * 0.2).astype(np.float32),
                     ctx=ctx1)
    logits = ht.matmul_op(h, w2, ctx=ctx1)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_, ctx=ctx1),
                             [0], ctx=ctx1)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    exp = ht.Executor({"train": [loss, train_op], "validate": [logits]},
                      gpipe=True, seed=5)

    losses = []
    n = M * mb
    for step in range(30):
        lo = (step * n) % len(xv)
        fdl = [{x: xv[lo + m * mb:lo + (m + 1) * mb],
                y_: yv[lo + m * mb:lo + (m + 1) * mb]} for m in range(M)]
        ret = exp.run("train", feed_dict=fdl, convert_to_numpy_ret_vals=True)
        losses.append(float(np.mean([np.mean(v) for v in ret[0]])))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, (
        losses[:5], losses[-5:])

    # eval is deterministic (dropout off outside training)
    vfd = [{x: xv[:mb]}]
    a = exp.run("validate", feed_dict=vfd, convert_to_numpy_ret_vals=True)
    b = exp.run("validate", feed_dict=vfd, convert_to_numpy_ret_vals=True)
    np.testing.assert_array_equal(np.asarray(a[0][0]), np.asarray(b[0][0]))


def test_gpipe_validate_entry_pipelines():
    """A forward-only eval target must also run through the stage pipeline:
    after a train step the params are committed to per-stage devices."""
    ctxs = [ht.cpu(i) for i in range(4)]
    x, y_, loss, train_op = _build_mlp(ctxs)
    exp = ht.Executor({"train": [loss, train_op], "validate": [loss]},
                      gpipe=True, seed=5)
    xv, yv = _data(16, seed=2)
    fdl = [{x: xv[:8], y_: yv[:8]}, {x: xv[8:], y_: yv[8:]}]
    exp.run("train", feed_dict=fdl)
    ret = exp.run("validate", feed_dict=fdl, convert_to_numpy_ret_vals=True)
    vals = [float(np.mean(v)) for v in ret[0]]
    assert len(vals) == 2 and np.all(np.isfinite(vals))
    # validation must not advance training state
    assert exp.state["step"] == 1


def test_gpipe_pp_dp_matches_single_device():
    """Pipeline+DP (reference executor.py:248-256 per-group allreduce):
    2 stages x 2-device dp groups; microbatches shard over each stage's dp
    mesh and must match the single-device full-batch oracle exactly."""
    M, mb = 4, 8
    xv, yv = _data(M * mb, seed=11)

    x, y_, loss, train_op = _build_mlp(None)
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=5)
    oracle_losses = []
    for _ in range(3):
        lv, _ = ex1.run("train", feed_dict={x: xv, y_: yv},
                        convert_to_numpy_ret_vals=True)
        oracle_losses.append(float(np.mean(lv)))
    oracle_params = [np.asarray(v) for v in ex1.state["params"].values()]

    g0, g1 = [ht.cpu(0), ht.cpu(1)], [ht.cpu(2), ht.cpu(3)]
    ctxs = [g0, g0, g1, g1]
    x, y_, loss, train_op = _build_mlp(ctxs)
    exp = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5,
                      comm_mode="AllReduce")
    sub = exp.subexecutors["train"]
    assert len(sub.stages) == 2
    assert all(st.mesh is not None and st.mesh.shape["dp"] == 2
               for st in sub.stages)
    pipe_losses = []
    for _ in range(3):
        fdl = [{x: xv[m * mb:(m + 1) * mb], y_: yv[m * mb:(m + 1) * mb]}
               for m in range(M)]
        ret = exp.run("train", feed_dict=fdl, convert_to_numpy_ret_vals=True)
        pipe_losses.append(float(np.mean([np.mean(v) for v in ret[0]])))
    pipe_params = [np.asarray(v) for v in exp.state["params"].values()]

    np.testing.assert_allclose(oracle_losses, pipe_losses,
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(oracle_params, pipe_params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def _build_cnn_bn(ctx0, ctx1):
    """Tiny conv+BN+pool CNN split into two stages (the repo's CNN zoo is
    BN-heavy; reference pipelines exactly such models)."""
    rng = np.random.RandomState(2)
    w1 = (rng.randn(8, 3, 3, 3) * 0.2).astype(np.float32)
    w2 = (rng.randn(8 * 4 * 4, 10) * 0.2).astype(np.float32)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    c = ht.Variable("c", value=w1.copy(), ctx=ctx0)
    scale = ht.Variable("scale", value=np.ones(8, np.float32), ctx=ctx0)
    bias = ht.Variable("bias", value=np.zeros(8, np.float32), ctx=ctx0)
    h = ht.conv2d_op(x, c, padding=1, stride=1, ctx=ctx0)
    h = ht.batch_normalization_op(h, scale, bias, ctx=ctx0)
    h = ht.relu_op(h, ctx=ctx0)
    h = ht.max_pool2d_op(h, 2, 2, 0, 2, ctx=ctx0)
    w = ht.Variable("w", value=w2.copy(), ctx=ctx1)
    flat = ht.array_reshape_op(h, [-1, 8 * 4 * 4], ctx=ctx1)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(ht.matmul_op(flat, w, ctx=ctx1), y_,
                                  ctx=ctx1), [0], ctx=ctx1)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return x, y_, loss, train_op


def test_gpipe_batchnorm_pipeline():
    """Stateful BatchNorm under gpipe: running stats thread sequentially
    through the microbatches. Oracle: a 1-STAGE gpipe run (same
    per-microbatch semantics) on one device must match the 2-stage pipeline
    exactly — losses, params, and the BN running stats."""
    M, mb = 3, 8
    rng = np.random.RandomState(4)
    xv = rng.randn(M * mb, 3, 8, 8).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[rng.randint(0, 10, M * mb)]
    fdl_of = lambda x, y_: [
        {x: xv[m * mb:(m + 1) * mb], y_: yv[m * mb:(m + 1) * mb]}
        for m in range(M)]

    x, y_, loss, train_op = _build_cnn_bn(ht.cpu(0), ht.cpu(0))
    ex1 = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    assert len(ex1.subexecutors["train"].stages) == 1
    l1 = [float(np.mean([np.mean(v) for v in
                         ex1.run("train", feed_dict=fdl_of(x, y_),
                                 convert_to_numpy_ret_vals=True)[0]]))
          for _ in range(3)]
    p1 = [np.asarray(v) for v in ex1.state["params"].values()]
    s1 = [np.asarray(leaf) for st in ex1.state["op_state"].values()
          for leaf in st.values()]

    x, y_, loss, train_op = _build_cnn_bn(ht.cpu(0), ht.cpu(1))
    ex2 = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    assert len(ex2.subexecutors["train"].stages) == 2
    l2 = [float(np.mean([np.mean(v) for v in
                         ex2.run("train", feed_dict=fdl_of(x, y_),
                                 convert_to_numpy_ret_vals=True)[0]]))
          for _ in range(3)]
    p2 = [np.asarray(v) for v in ex2.state["params"].values()]
    s2 = [np.asarray(leaf) for st in ex2.state["op_state"].values()
          for leaf in st.values()]

    np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-6)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    for a, b in zip(s1, s2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # the stats actually moved off their init (mean 0 / var 1)
    assert any(np.abs(v).max() > 1e-3 for v in s2[:1]), s2[0]


def test_gpipe_explicit_send_recv_markers():
    """pipeline_send_op/pipeline_receive_op are executable stage-boundary
    markers (reference PipelineSend.py:19-44 / PipelineReceive.py:20-48):
    send pins the value to the producing stage, recv (paired with the send
    node at placement time) pins the consumer side, and the boundary
    machinery carries the bytes. The marked pipeline must match the
    unmarked oracle exactly."""
    M, mb = 2, 8
    xv, yv = _data(M * mb, seed=7)

    # oracle: unmarked single-device run
    x, y_, loss, train_op = _build_mlp(None)
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=5)
    lv, _ = ex1.run("train", feed_dict={x: xv, y_: yv},
                    convert_to_numpy_ret_vals=True)
    oracle = float(np.mean(lv))

    # 2-stage pipeline with explicit send/recv markers at the cut
    rng = np.random.RandomState(0)
    dims = [20, 32, 32, 16, 10]
    ws = [(rng.randn(dims[i], dims[i + 1]) * 0.2).astype(np.float32)
          for i in range(4)]
    c0, c1 = ht.cpu(0), ht.cpu(1)
    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    h = x
    for i in range(2):
        w = ht.Variable(f"w{i}", value=ws[i].copy(), ctx=c0)
        h = ht.relu_op(ht.matmul_op(h, w, ctx=c0), ctx=c0)
    sent = ht.pipeline_send_op(h, destination=1, ctx=c0)
    h = ht.pipeline_receive_op(source=sent, ctx=c1)
    for i in range(2, 4):
        w = ht.Variable(f"w{i}", value=ws[i].copy(), ctx=c1)
        h = ht.matmul_op(h, w, ctx=c1)
        if i < 3:
            h = ht.relu_op(h, ctx=c1)
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(h, y_, ctx=c1), [0], ctx=c1)
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    exp = ht.Executor({"train": [loss, train_op]}, gpipe=True, seed=5)
    assert len(exp.subexecutors["train"].stages) == 2
    fdl = [{x: xv[m * mb:(m + 1) * mb], y_: yv[m * mb:(m + 1) * mb]}
           for m in range(M)]
    ret = exp.run("train", feed_dict=fdl, convert_to_numpy_ret_vals=True)
    pipe = float(np.mean([np.mean(v) for v in ret[0]]))
    np.testing.assert_allclose(oracle, pipe, rtol=1e-5, atol=1e-6)


def test_pipeline_recv_requires_paired_send():
    with pytest.raises(TypeError, match="paired"):
        ht.pipeline_receive_op(source=3)


def test_gpipe_without_stage_contexts_raises():
    x, y_, loss, train_op = _build_mlp(None)
    with pytest.raises(ValueError, match="context"):
        ht.Executor({"train": [loss, train_op]}, gpipe=True, ctx=ht.cpu(0))


def test_gpipe_microbatch_list_required():
    ctxs = [ht.cpu(i) for i in range(4)]
    x, y_, loss, train_op = _build_mlp(ctxs)
    exp = ht.Executor({"train": [loss, train_op]}, gpipe=True)
    with pytest.raises(ValueError, match="microbatch"):
        exp.run("train", feed_dict=None)
