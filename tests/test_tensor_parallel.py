"""Graph-API tensor parallelism via ``ht.dispatch`` on the virtual 8-CPU mesh.

Mirrors the reference's ``examples/runner/parallel/data_model_pipeline_mlp.py``
left/middle/right split variants (Dispatch.py:35-49, MatrixMult.py:88-109):
a tuple DeviceGroup ``[(d0, d1), (d2, d3)]`` is 2 workers x 2-way model
parallel. Correctness oracle: every split variant must match the
single-device run; layouts are checked on the stored parameter itself.
"""
import numpy as np
import pytest
import jax

import hetu_tpu as ht


def _mlp_with_dispatch(split, ctx_mp):
    """784->64->10 MLP whose middle matmul is tensor-parallel."""
    rng = np.random.RandomState(0)
    w1v = (rng.randn(32, 64) * 0.1).astype(np.float32)
    w2v = (rng.randn(64, 64) * 0.1).astype(np.float32)
    w3v = (rng.randn(64, 10) * 0.1).astype(np.float32)

    x = ht.Variable(name="x", trainable=False)
    y_ = ht.Variable(name="y", trainable=False)
    h = ht.relu_op(ht.matmul_op(x, ht.Variable("w1", value=w1v.copy())))
    w2_var = w2 = ht.Variable("w2", value=w2v.copy())
    if split is not None:
        with ht.context(ctx_mp):
            if split == "left":
                h = ht.dispatch(h, (2, 1))
                w2 = ht.dispatch(w2, (1, 1), duplicate=2)
            elif split == "right":
                h = ht.dispatch(h, (1, 1), duplicate=2)
                w2 = ht.dispatch(w2, (1, 2))
            else:  # middle: contract-dim split, GSPMD inserts the psum
                h = ht.dispatch(h, (1, 2))
                w2 = ht.dispatch(w2, (2, 1))
            h = ht.matmul_op(h, w2)
            if split != "middle":
                h = ht.dispatch(h, (1, 1))
    else:
        h = ht.matmul_op(h, w2)
    h = ht.relu_op(h)
    logits = ht.matmul_op(h, ht.Variable("w3", value=w3v.copy()))
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return x, y_, w2_var, loss, train_op


def _data(n=16, seed=3):
    rng = np.random.RandomState(seed)
    xv = rng.randn(n, 32).astype(np.float32)
    yv = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return xv, yv


def _train(ex, x, y_, xv, yv, steps=4):
    losses = []
    for _ in range(steps):
        lv = ex.run("train", feed_dict={x: xv, y_: yv},
                    convert_to_numpy_ret_vals=True)[0]
        losses.append(float(np.mean(lv)))
    return losses


@pytest.mark.parametrize("split", ["left", "middle", "right"])
def test_dispatch_matches_single_device(split):
    assert jax.device_count() == 8
    xv, yv = _data()

    x, y_, w2, loss, train_op = _mlp_with_dispatch(None, None)
    ex1 = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0), seed=7)
    losses1 = _train(ex1, x, y_, xv, yv)
    w2_1 = np.asarray(ex1.state["params"][id(w2)])

    ctx_mp = [(ht.cpu(0), ht.cpu(1)), (ht.cpu(2), ht.cpu(3))]  # dp2 x tp2
    x, y_, w2, loss, train_op = _mlp_with_dispatch(split, ctx_mp)
    ex = ht.Executor({"train": [loss, train_op]}, seed=7)
    mesh = ex.config.mesh
    assert mesh is not None and dict(
        zip(mesh.axis_names, mesh.devices.shape)) == {"dp": 2, "tp": 2}
    losses = _train(ex, x, y_, xv, yv)
    w2_n = np.asarray(ex.state["params"][id(w2)])

    np.testing.assert_allclose(losses1, losses, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(w2_1, w2_n, rtol=2e-4, atol=1e-5)


def test_dispatch_shards_parameter_storage():
    """The split weight must actually be STORED split over the model axis
    (per-device shard = half the columns), not replicated."""
    xv, yv = _data()
    ctx_mp = [(ht.cpu(0), ht.cpu(1)), (ht.cpu(2), ht.cpu(3))]
    x, y_, w2, loss, train_op = _mlp_with_dispatch("right", ctx_mp)
    ex = ht.Executor({"train": [loss, train_op]}, seed=7)
    wval = ex.state["params"][id(w2)]
    assert not wval.sharding.is_fully_replicated
    shard_shape = wval.sharding.shard_shape(wval.shape)
    assert shard_shape == (64, 32), shard_shape  # columns split 2-way
    _train(ex, x, y_, xv, yv, steps=2)
    # updates preserve the layout
    wval = ex.state["params"][id(w2)]
    assert wval.sharding.shard_shape(wval.shape) == (64, 32)


def test_dispatch_without_mp_mesh_raises():
    x, y_, w2, loss, train_op = _mlp_with_dispatch(None, None)
    h = ht.dispatch(loss, (1,))  # any dispatch marker in the graph
    with pytest.raises(ValueError, match="model-parallel"):
        ht.Executor({"train": [h, train_op]}, ctx=ht.cpu(0))


def test_dispatch_two_split_dims_rejected():
    v = ht.Variable("v", value=np.ones((4, 4), np.float32))
    with pytest.raises(NotImplementedError):
        ht.dispatch(v, (2, 2))
