"""Test configuration: force an 8-device virtual CPU mesh before jax import.

Mirrors the reference's local-process-cluster test strategy (SURVEY.md §4):
multi-chip behavior is validated on a virtual device mesh, no TPU pod needed.
"""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")
