"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's local-process-cluster test strategy (SURVEY.md §4):
multi-chip behavior is validated on a virtual device mesh, no TPU pod needed.

Note: this environment's sitecustomize pins JAX_PLATFORMS=axon (the tunneled
TPU), so the env var alone is not enough — jax.config.update after import is
authoritative.
"""
import os

# appended last: with duplicate flags, XLA takes the last occurrence
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# shared Executor-driving helpers for op-parity tests (used by test_ops.py
# and test_op_parity.py)
def run_graph_helper(out_node, feeds=None):
    import hetu_tpu as ht
    ex = ht.Executor([out_node], ctx=ht.cpu(0))
    (res,) = ex.run("default", feed_dict=feeds or {})
    return res.asnumpy()


def feed_helper(shape=None, val=None, seed=0, name="x"):
    import numpy as np
    import hetu_tpu as ht
    node = ht.Variable(name=name, trainable=False)
    if val is None:
        val = np.random.RandomState(seed).randn(*shape).astype(np.float32)
    return node, val


def import_example_models(example):
    """Import examples/<example>/models under the bare name ``models``,
    purging any previously-imported zoo (cnn/ctr both use the name).
    Shared by test_models / test_ctr_models / test_onnx."""
    import importlib
    import sys
    path = os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "examples",
        example))
    target = os.path.join(path, "models")
    current = sys.modules.get("models")
    if current is not None and \
            os.path.normpath(os.path.dirname(current.__file__)) != target:
        for k in [k for k in sys.modules
                  if k == "models" or k.startswith("models.")]:
            sys.modules.pop(k)
    if path in sys.path:
        sys.path.remove(path)
    sys.path.insert(0, path)
    return importlib.import_module("models")
