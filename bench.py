"""Benchmark harness — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline config (BASELINE.md): ResNet-18 / CIFAR10-shape data through the
define-then-run Executor on the real chip — samples/sec/chip. Syncs once per
timed window (host<->device roundtrips on the tunneled chip cost ~64ms and
must not be counted per step). ``--all`` also reports the flagship
transformer tokens/s/chip.

vs_baseline: the reference repo publishes no numbers (BASELINE.md); the
recorded baseline is the reference's "≥30% faster than TF1" claim proxied by
our own first-round measurement. Until a cross-framework A/B exists on this
hardware, vs_baseline reports value / BASELINE_REFERENCE (stored below once
round 1 lands).
"""
import json
import sys
import time

import numpy as np

# Round-1 measurement recorded as the running baseline for later rounds
# (v5e-1, 2026-07-29: 4929 samples/s, 26ms step @ bs128).
BASELINE_SAMPLES_PER_SEC = 4929.1


def bench_resnet18(batch_size=128, warmup=5, iters=30):
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "examples", "cnn"))
    import hetu_tpu as ht
    import models

    rng = np.random.RandomState(0)
    n = batch_size * 4
    data_x = rng.randn(n, 3, 32, 32).astype(np.float32)
    data_y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    x = ht.dataloader_op([ht.Dataloader(data_x, batch_size, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(data_y, batch_size, "train")])
    loss, y = models.resnet18(x, y_, 10)
    opt = ht.optim.MomentumOptimizer(learning_rate=0.1)
    train_op = opt.minimize(loss)
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.tpu(0))

    for _ in range(warmup):
        ex.run("train")
    # sync: pull the loss once to drain the queue
    float(ex.run("train")[0].asnumpy())

    t0 = time.time()
    for _ in range(iters - 1):
        ex.run("train")
    last = ex.run("train")[0]
    float(last.asnumpy())  # one sync for the whole window
    dt = (time.time() - t0) / iters
    return batch_size / dt, dt * 1000


def bench_transformer(warmup=3, iters=20):
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=8192, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq_len=512)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = tfm.init_opt_state(params)
    step = tfm.make_train_step(cfg, mesh=None, lr=3e-4)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, 8192, (16, 512)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)
    for _ in range(warmup):
        loss, params, opt = step(params, opt, tok, tgt)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(iters):
        loss, params, opt = step(params, opt, tok, tgt)
    jax.block_until_ready(loss)
    dt = (time.time() - t0) / iters
    return 16 * 512 / dt, dt * 1000


def main():
    samples_per_sec, step_ms = bench_resnet18()
    vs = (samples_per_sec / BASELINE_SAMPLES_PER_SEC
          if BASELINE_SAMPLES_PER_SEC else 1.0)
    print(json.dumps({
        "metric": "resnet18_cifar10_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 3),
        "detail": {"step_ms": round(step_ms, 2), "batch_size": 128},
    }))
    if "--all" in sys.argv:
        toks, tms = bench_transformer()
        print(json.dumps({
            "metric": "transformer_38M_seq512_tokens_per_sec_per_chip",
            "value": round(toks, 0),
            "unit": "tokens/sec/chip",
            "vs_baseline": 1.0,
            "detail": {"step_ms": round(tms, 2)},
        }))


if __name__ == "__main__":
    main()
